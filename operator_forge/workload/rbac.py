"""RBAC rule inference.

Reference: internal/workload/v1/rbac/.  Derives the ``+kubebuilder:rbac``
markers the generated controller needs:

- per-workload rules: manage its own kind and ``<kind>/status``;
- per-child-resource rules: manage whatever the manifests declare;
- recursive escalation: when a child resource is a Role/ClusterRole, the
  controller also needs every permission that role grants
  (rules.go:58-93, role_rule.go:22-125) — otherwise the generated operator
  fails at runtime with escalation errors;
- verb deduplication and group/resource merging (rule.go:39-105).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Optional

CORE_GROUP = "core"
KUBEBUILDER_PREFIX = "// +kubebuilder:rbac"

DEFAULT_RESOURCE_VERBS = [
    "get", "list", "watch", "create", "update", "patch", "delete",
]
DEFAULT_STATUS_VERBS = ["get", "update", "patch"]

# found value -> proper plural (reference rbac.go:56-60)
KNOWN_IRREGULARS = {
    "resourcequota": "resourcequotas",
}

_ES_SUFFIXES = ("ss", "us", "is", "os", "x", "z", "ch", "sh")


def pluralize(kind: str) -> str:
    """Lowercase-pluralize a kind the way kubebuilder's RegularPlural does
    (flect-style English pluralization, good for Kubernetes kinds).
    Already-plural words (``jobs``, ``deployments``) pass through unchanged,
    as RBAC role rules list resources in plural form."""
    word = kind.lower()
    if word in KNOWN_IRREGULARS:
        return KNOWN_IRREGULARS[word]
    if word.endswith("y") and len(word) > 1 and word[-2] not in "aeiou":
        plural = word[:-1] + "ies"
    elif word.endswith(_ES_SUFFIXES):
        plural = word + "es"
    elif word.endswith("s"):
        plural = word
    else:
        plural = word + "s"
    return KNOWN_IRREGULARS.get(plural, plural)


def get_group(group: str) -> str:
    return group if group else CORE_GROUP


def get_resource(kind: str) -> str:
    """Format a kind (possibly ``kind/subresource`` or ``*``) for a rule
    (reference rbac.go:99-116)."""
    parts = kind.split("/")
    base = "*" if parts[0] == "*" else pluralize(parts[0])
    if len(parts) > 1:
        return f"{base}/{parts[1]}"
    return base


@dataclass
class Rule:
    group: str = ""
    resource: str = ""
    urls: list[str] = dc_field(default_factory=list)
    verbs: list[str] = dc_field(default_factory=list)

    def to_marker(self) -> str:
        """Reference rule.go:20-35 ToMarker."""
        if self.urls:
            return (
                f"{KUBEBUILDER_PREFIX}:verbs={';'.join(self.verbs)},"
                f"urls={';'.join(self.urls)}"
            )
        return (
            f"{KUBEBUILDER_PREFIX}:groups={self.group},"
            f"resources={self.resource},verbs={';'.join(self.verbs)}"
        )

    def is_resource_rule(self) -> bool:
        return bool(self.group and self.resource)

    def group_resource_equal(self, other: "Rule") -> bool:
        return self.group == other.group and self.resource == other.resource


class Rules:
    """A deduplicating collection of RBAC rules (reference rules.go)."""

    def __init__(self) -> None:
        self._rules: list[Rule] = []

    def __iter__(self):
        return iter(self._rules)

    def __len__(self) -> int:
        return len(self._rules)

    def as_list(self) -> list[Rule]:
        return list(self._rules)

    def add(self, *new_rules: "Rule | Rules") -> None:
        for item in new_rules:
            if isinstance(item, Rules):
                for rule in item:
                    self._add_rule(rule)
            else:
                self._add_rule(item)

    def _add_rule(self, rule: Rule) -> None:
        if not self._rules:
            self._rules.append(_copy(rule))
            return
        if rule.is_resource_rule():
            self._add_resource_rule(rule)
        else:
            self._add_non_resource_rule(rule)

    def _add_resource_rule(self, rule: Rule) -> None:
        for existing in self._rules:
            if rule.group_resource_equal(existing):
                for verb in rule.verbs:
                    if verb not in existing.verbs:
                        existing.verbs.append(verb)
                return
        self._rules.append(_copy(rule))

    def _add_non_resource_rule(self, rule: Rule) -> None:
        for url in rule.urls:
            for existing in self._rules:
                if url in existing.urls:
                    for verb in rule.verbs:
                        if verb not in existing.verbs:
                            existing.verbs.append(verb)
                    return
        self._rules.append(_copy(rule))


def _copy(rule: Rule) -> Rule:
    return Rule(
        group=rule.group,
        resource=rule.resource,
        urls=list(rule.urls),
        verbs=list(rule.verbs),
    )


def for_workloads(*workloads) -> Rules:
    """Rules for the workload kinds themselves (reference rules.go:37-55
    via rbac.go:79-89 ForWorkloads).  ``workloads`` expose ``api_group``,
    ``domain`` and ``api_kind`` attributes/properties."""
    rules = Rules()
    for workload in workloads:
        if workload is None:
            continue
        group = f"{workload.api_group}.{workload.domain}"
        resource = get_resource(workload.api_kind)
        rules.add(
            Rule(group=group, resource=resource,
                 verbs=list(DEFAULT_RESOURCE_VERBS)),
            Rule(group=group, resource=f"{resource}/status",
                 verbs=list(DEFAULT_STATUS_VERBS)),
            # the orchestrate runtime registers a teardown finalizer on the
            # workload; clusters running the OwnerReferencesPermission-
            # Enforcement admission plugin require explicit permission on
            # the finalizers subresource for that update
            Rule(group=group, resource=f"{resource}/finalizers",
                 verbs=["update"]),
        )
    return rules


def for_resource(manifest: dict) -> Rules:
    """Rules for one child-resource manifest, with Role/ClusterRole
    escalation (reference rules.go:58-93 addForResource)."""
    rules = Rules()
    api_version = str(manifest.get("apiVersion", ""))
    group = api_version.split("/")[0] if "/" in api_version else ""
    kind = str(manifest.get("kind", ""))

    rules.add(
        Rule(
            group=get_group(group),
            resource=get_resource(kind),
            verbs=list(DEFAULT_RESOURCE_VERBS),
        )
    )

    if kind.lower() in ("clusterrole", "role"):
        role_rules = manifest.get("rules")
        if isinstance(role_rules, list):
            for role_rule in role_rules:
                rules.add(_role_rule_to_rules(role_rule))
    return rules


def _string_list(value: Any) -> list[str]:
    if isinstance(value, list):
        return [str(v) for v in value]
    if value is None:
        return []
    return [str(value)]


def _role_rule_to_rules(role_rule: Any) -> Rules:
    """Convert one Role/ClusterRole rule into controller rules
    (reference role_rule.go:43-125)."""
    rules = Rules()
    if not isinstance(role_rule, dict):
        return rules
    groups = _string_list(role_rule.get("apiGroups"))
    resources = _string_list(role_rule.get("resources"))
    verbs = _string_list(role_rule.get("verbs"))
    urls = _string_list(role_rule.get("nonResourceURLs"))

    if not verbs:
        return rules
    if groups and resources:
        for g in groups:
            for r in resources:
                rules.add(
                    Rule(
                        group=get_group(g),
                        resource=get_resource(r),
                        verbs=list(verbs),
                        urls=list(urls),
                    )
                )
    elif urls:
        rules.add(Rule(verbs=list(verbs), urls=list(urls)))
    return rules
