"""Structural-schema validation of custom resources against generated CRDs.

The reference ecosystem leaves this to the API server at apply time (or
the compiled companion CLI's workload.Validate, which checks much less);
`operator-forge validate` checks a CR manifest against the generated
CRD's openAPIV3Schema without a cluster: types, unknown properties, and
required fields.  The same validator backs the test-suite consistency
check that every generated sample satisfies its own CRD schema.
"""

from __future__ import annotations

import os
from typing import Any

from ..utils import yamlcompat as pyyaml


class ValidationError(Exception):
    pass


def validate_instance(instance: Any, schema: dict, path: str = "$") -> list[str]:
    """Validate a decoded object against an openAPI v3 structural schema.

    Covers the subset generated CRDs use: type (object/array/integer/
    boolean/string/number), properties + unknown-field rejection (unless
    x-kubernetes-preserve-unknown-fields), items, and required.
    """
    errors: list[str] = []
    stype = schema.get("type")
    if stype == "object":
        if not isinstance(instance, dict):
            return [f"{path}: expected object, got {type(instance).__name__}"]
        props = schema.get("properties")
        for key in schema.get("required", []):
            if key not in instance or instance.get(key) is None:
                errors.append(f"{path}.{key}: required property missing")
        if props is None:
            return errors  # schema-less object (e.g. metadata): accept all
        for key, value in instance.items():
            if key in props:
                errors.extend(validate_instance(value, props[key], f"{path}.{key}"))
            elif not schema.get("x-kubernetes-preserve-unknown-fields"):
                errors.append(f"{path}.{key}: unknown property")
    elif stype == "array":
        if not isinstance(instance, list):
            return [f"{path}: expected array, got {type(instance).__name__}"]
        for i, item in enumerate(instance):
            errors.extend(
                validate_instance(item, schema.get("items", {}), f"{path}[{i}]")
            )
    elif stype == "integer":
        if not isinstance(instance, int) or isinstance(instance, bool):
            errors.append(f"{path}: expected integer, got {instance!r}")
    elif stype == "number":
        if isinstance(instance, bool) or not isinstance(instance, (int, float)):
            errors.append(f"{path}: expected number, got {instance!r}")
    elif stype == "boolean":
        if not isinstance(instance, bool):
            errors.append(f"{path}: expected boolean, got {instance!r}")
    elif stype == "string":
        if not isinstance(instance, str):
            errors.append(f"{path}: expected string, got {instance!r}")
    return errors


def load_project_crds(project_dir: str) -> list[dict]:
    """Read every CRD under config/crd/bases of a generated project."""
    base = os.path.join(project_dir, "config", "crd", "bases")
    if not os.path.isdir(base):
        raise ValidationError(
            f"no CRDs found under {base}; run `operator-forge create api` first"
        )
    crds = []
    for name in sorted(os.listdir(base)):
        if not name.endswith((".yaml", ".yml")):
            continue
        with open(os.path.join(base, name), encoding="utf-8") as fh:
            for doc in pyyaml.safe_load_all(fh.read()):
                if isinstance(doc, dict) and doc.get("kind") == "CustomResourceDefinition":
                    crds.append(doc)
    return crds


def _version_schema(crd: dict, version: str) -> dict | None:
    for v in crd.get("spec", {}).get("versions", []):
        if v.get("name") == version:
            return v.get("schema", {}).get("openAPIV3Schema", {})
    return None


def validate_cr(project_dir: str, cr: Any, crds: list[dict] | None = None) -> list[str]:
    """Validate one decoded CR against the project's generated CRDs.

    Pass *crds* (from :func:`load_project_crds`) to validate many
    documents without re-reading the CRD files per document.
    """
    if not isinstance(cr, dict):
        return [f"manifest document must be a mapping, got {type(cr).__name__}"]
    kind = cr.get("kind")
    api_version = str(cr.get("apiVersion", ""))
    if not kind or "/" not in api_version:
        return ["manifest needs kind and group/version apiVersion"]
    group, version = api_version.rsplit("/", 1)
    if crds is None:
        crds = load_project_crds(project_dir)
    for crd in crds:
        spec = crd.get("spec", {})
        if spec.get("names", {}).get("kind") != kind:
            continue
        if spec.get("group") != group:
            continue
        schema = _version_schema(crd, version)
        if schema is None:
            served = [v.get("name") for v in spec.get("versions", [])]
            return [
                f"version {version!r} not served by CRD "
                f"{crd['metadata']['name']} (has: {served})"
            ]
        return validate_instance(cr, schema)
    return [f"no generated CRD matches {api_version} {kind}"]
