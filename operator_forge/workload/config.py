"""Workload-config parsing into a Processor tree.

Reference: internal/workload/v1/config/{parse,processor,validate}.go.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field as dc_field

from operator_forge.utils import yamlcompat as pyyaml

from ..utils.globber import glob_files
from .kinds import (
    ComponentWorkload,
    Workload,
    WorkloadCollection,
    WorkloadConfigError,
    decode,
)


class ConfigParseError(Exception):
    pass


@dataclass
class Processor:
    """A parsed workload config plus its component children
    (reference processor.go:16-24)."""

    path: str
    workload: Workload = None
    children: list["Processor"] = dc_field(default_factory=list)

    def get_workloads(self) -> list[Workload]:
        workloads = [self.workload]
        for child in self.children:
            workloads.extend(child.get_workloads())
        return workloads

    def get_processors(self) -> list["Processor"]:
        processors = [self]
        for child in self.children:
            processors.extend(child.get_processors())
        return processors


class _InlineValidator:
    """Uniqueness validation while parsing (reference validate.go:20-77)."""

    def __init__(self) -> None:
        self.names: set[str] = set()
        self.kinds_in_groups: dict[str, list[str]] = {}

    def validate(self, workload: Workload, processor: Processor) -> None:
        if workload.name in self.names:
            raise ConfigParseError(
                "each workload name must be unique; duplicate name "
                f"{workload.name!r} at path {processor.path}"
            )
        try:
            workload.validate()
        except WorkloadConfigError as exc:
            raise ConfigParseError(
                f"error validating workload at path {processor.path}: {exc}"
            ) from exc
        existing = self.kinds_in_groups.get(workload.api_group, [])
        if workload.api_kind in existing:
            raise ConfigParseError(
                "each kind within a group must be unique; duplicate kind "
                f"{workload.api_kind!r} in group {workload.api_group!r} "
                f"at path {processor.path}"
            )
        self.names.add(workload.name)
        self.kinds_in_groups.setdefault(workload.api_group, []).append(
            workload.api_kind
        )


def parse(config_path: str) -> Processor:
    """Parse a workload config (and any component configs it references)
    into a Processor tree (reference parse.go:32-70 Parse)."""
    if not config_path:
        raise ConfigParseError(
            "no workload config provided - workload config required"
        )
    processor = Processor(path=config_path)
    validator = _InlineValidator()
    _parse_into(processor, validator)

    if processor.workload is None:
        raise ConfigParseError(
            f"could not find a workload config at path {config_path}"
        )
    if processor.workload.is_component():
        raise ConfigParseError(
            "a WorkloadCollection is required when using WorkloadComponents; "
            f"no WorkloadCollection found at config path {config_path}"
        )

    all_workloads = processor.get_workloads()
    for child in processor.children:
        _set_dependencies(child.workload, all_workloads)

    return processor


def _parse_into(processor: Processor, validator: _InlineValidator) -> None:
    """Reference parse.go:74-134 (Processor.parse)."""
    try:
        with open(processor.path, "r", encoding="utf-8") as handle:
            raw = handle.read()
    except OSError as exc:
        raise ConfigParseError(
            f"{exc}; error reading file {processor.path}"
        ) from exc

    try:
        documents = [d for d in pyyaml.safe_load_all(raw) if d is not None]
    except pyyaml.YAMLError as exc:
        raise ConfigParseError(
            f"failed to read file {processor.path}: {exc}"
        ) from exc

    if not documents:
        raise ConfigParseError(
            f"no workload config documents found in {processor.path}"
        )

    for document in documents:
        try:
            workload = decode(document, processor.path)
        except WorkloadConfigError as exc:
            raise ConfigParseError(
                f"failed to read file {processor.path}: {exc}"
            ) from exc

        validator.validate(workload, processor)
        workload.set_names()
        processor.workload = workload

        if isinstance(workload, WorkloadCollection):
            _parse_components(processor, workload, validator)


def _parse_components(
    processor: Processor,
    collection: WorkloadCollection,
    validator: _InlineValidator,
) -> None:
    """Reference parse.go:136-171 parseComponents."""
    base_dir = os.path.dirname(processor.path)
    for component_file in collection.component_files:
        try:
            component_paths = glob_files(os.path.join(base_dir, component_file))
        except Exception as exc:
            raise ConfigParseError(
                f"{exc}; error globbing workload config at path {component_file}"
            ) from exc
        for component_path in component_paths:
            if os.path.isdir(component_path):
                continue
            child = Processor(path=component_path)
            processor.children.append(child)
            try:
                _parse_into(child, validator)
            except ConfigParseError as exc:
                raise ConfigParseError(
                    f"{exc}; error parsing workload component config at path "
                    f"{component_path}"
                ) from exc
            if isinstance(child.workload, ComponentWorkload):
                child.workload.config_path = component_path


def _set_dependencies(workload: Workload, workloads: list[Workload]) -> None:
    """Resolve component dependency names to component objects
    (reference parse.go:174-216)."""
    if not isinstance(workload, ComponentWorkload):
        raise ConfigParseError(
            "error converting workload to component workload for workload "
            f"[{workload.name}]"
        )
    workload.component_dependencies = []
    missing = []
    for expected in workload.dependencies:
        dependency = None
        for candidate in workloads:
            if candidate.name == expected and isinstance(
                candidate, ComponentWorkload
            ):
                dependency = candidate
                break
        if dependency is not None:
            workload.component_dependencies.append(dependency)
        else:
            missing.append(expected)
    if missing:
        raise ConfigParseError(
            f"missing dependencies - no workload config provided; missing "
            f"{missing} for component: [{workload.name}]"
        )
