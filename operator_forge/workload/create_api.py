"""The `create api` processing pipeline.

Reference: internal/workload/v1/commands/subcommand/create_api.go.  Order
matters: the collection is processed first (``get_processors`` returns the
parent before its children) so collection markers in component manifests are
rewritten before each component generates its child-resource source code;
finally resource markers are resolved against the aggregated marker set.
"""

from __future__ import annotations

from dataclasses import dataclass
import os

from ..perf import spans
from .config import Processor
from .fieldmarkers import MarkerCollection
from .kinds import ComponentWorkload, StandaloneWorkload, WorkloadCollection


class CreateAPIError(Exception):
    pass


def init_workloads(processor: Processor) -> None:
    """The `init` subcommand logic: just set names
    (reference subcommand/init.go:12-18)."""
    for p in processor.get_processors():
        p.workload.set_names()


@dataclass
class _APIProcessor:
    collection: WorkloadCollection = None
    components: list = None


def create_api(processor: Processor) -> None:
    """Reference create_api.go:31-120 CreateAPI."""
    config_processors = processor.get_processors()
    state = _APIProcessor(components=[])

    # pre-process: load manifests, find collection + components
    # (create_api.go:52-75)
    for p in config_processors:
        workload = p.workload
        workload.load_manifests(os.path.dirname(p.path))
        if isinstance(workload, WorkloadCollection):
            # a collection is still a collection to itself
            state.collection = workload
            workload.spec.collection = workload
            workload.spec.for_collection = True
        elif isinstance(workload, ComponentWorkload):
            state.components.append(workload)

    if state.components:
        processor.workload.set_components(state.components)

    # process: set resources + rbac, aggregate markers (create_api.go:77-111)
    markers = MarkerCollection()
    specs = []
    for p in config_processors:
        workload = p.workload
        if isinstance(workload, ComponentWorkload):
            workload.spec.collection = state.collection
            workload.api_spec.domain = state.collection.api_spec.domain

        try:
            workload.set_resources(p.path)
        except Exception as exc:
            raise CreateAPIError(
                f"{exc}; error setting resources for workload {workload.name}"
            ) from exc

        workload.set_rbac()

        specs.append(workload.spec)
        markers.field_markers.extend(workload.spec.field_markers)
        markers.collection_field_markers.extend(
            workload.spec.collection_field_markers
        )

    # resolve resource markers across all specs (create_api.go:113-119)
    with spans.span("resource-markers"):
        for spec in specs:
            try:
                spec.process_resource_markers(markers)
            except Exception as exc:
                raise CreateAPIError(
                    f"{exc}; error processing resource markers"
                ) from exc
