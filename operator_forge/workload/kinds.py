"""Workload kinds: Standalone, Collection, Component.

Reference: internal/workload/v1/kinds/{workload,standalone,collection,
component,kinds}.go.  Each workload kind carries a ``WorkloadSpec`` whose
``process_manifests`` is the core codegen driver (workload.go:218-291):
marker inspection -> value/comment rewriting -> child-resource creation
(with RBAC) -> Go object source emission -> filename dedup.
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass, field as dc_field
from typing import Any, Optional

from .. import gocodegen
from ..perf import cache as perfcache
from ..perf import parallel_map, spans
from ..utils import to_package_name
from ..yamldoc.load import load_documents
from ..yamldoc.emit import emit_documents
from ..yamldoc.model import to_python
from . import manifests as manifests_mod
from . import rbac
from .api_fields import APIFields
from .companion import CompanionCLI
from .fieldmarkers import (
    CollectionFieldMarker,
    FieldMarker,
    FieldType,
    MarkerCollection,
    MarkerType,
    _FieldMarkerBase,
    inspect_for_yaml,
)


def _transform_manifest(content: str, marker_types: tuple) -> tuple:
    """The pure per-manifest marker pass: inspect ``content`` for the
    requested marker types, rewrite values/comments, and return
    ``(rewritten_content, field markers in inspection order)``.

    Pure in its arguments, so it is memoized content-addressed (stage
    ``manifest-transform``); a cache hit returns fresh marker copies the
    caller may mutate.
    """

    def compute():
        inspected = inspect_for_yaml(content, *marker_types)
        new_content = emit_documents(inspected.documents)
        # when processing a collection's own manifests, any surviving
        # collection-variable references are references to self
        # (reference workload.go:317-326)
        if (
            MarkerType.FIELD in marker_types
            and MarkerType.COLLECTION in marker_types
        ):
            new_content = new_content.replace("!!var collection", "!!var parent")
            new_content = new_content.replace(
                "!!start collection", "!!start parent"
            )
        markers = [
            r.obj
            for r in inspected.results
            if isinstance(r.obj, _FieldMarkerBase)
        ]
        return new_content, markers

    from ..scaffold import render

    key = (content, tuple(mt.value for mt in marker_types))
    with spans.span("marker-inspect"):
        # two tiers: the content-keyed stage cache (cleared with the
        # perf cache) over the lowered-blob tier (a process-level JIT
        # artifact persisted in the ``render.lower`` namespace), so a
        # cache reset replays the pickled transform instead of
        # re-walking the YAML
        return perfcache.memoized(
            "manifest-transform",
            key,
            lambda: render.lowered_blob(
                "workload.manifest_transform", key, compute
            ),
        )


def _build_children(content: str, filename: str) -> list:
    """Child resources (with generated Go source) for REWRITTEN manifest
    content.  Pure in ``content`` — ``filename`` only decorates error
    messages, and errors are never cached — so it is memoized
    content-addressed (stage ``manifest-children``)."""

    def compute():
        children: list[manifests_mod.ChildResource] = []
        shell = manifests_mod.Manifest(filename=filename, content=content)
        for extracted in shell.extract_manifests():
            try:
                docs = [
                    d for d in load_documents(extracted) if d.root is not None
                ]
            except Exception as exc:
                raise ManifestProcessingError(
                    f"{exc}; unable to decode object in manifest file "
                    f"{filename}"
                ) from exc
            if not docs:
                continue
            obj = to_python(docs[0].root)
            if not isinstance(obj, dict) or not obj.get("kind"):
                raise ManifestProcessingError(
                    "manifest object missing 'kind' in manifest file "
                    f"{filename}"
                )
            child = manifests_mod.ChildResource.from_object(obj)
            with spans.span("child-codegen"):
                child.source_code = (
                    gocodegen.generate_for_document_lowered(
                        docs[0], "resourceObj", extracted
                    )
                )
            child.static_content = extracted
            children.append(child)
        return children

    from ..scaffold import render

    return perfcache.memoized(
        "manifest-children",
        (content,),
        lambda: render.lowered_blob(
            "workload.manifest_children", (content,), compute
        ),
    )


class WorkloadKind(enum.Enum):
    STANDALONE = "StandaloneWorkload"
    COLLECTION = "WorkloadCollection"
    COMPONENT = "ComponentWorkload"


class WorkloadConfigError(Exception):
    pass


class ManifestProcessingError(Exception):
    pass


SAMPLE_API_DOMAIN = "acme.com"
SAMPLE_API_GROUP = "apps"
SAMPLE_API_KIND = "MyApp"
SAMPLE_API_VERSION = "v1alpha1"


@dataclass
class WorkloadAPISpec:
    """Reference workload.go:79-86."""

    domain: str = ""
    group: str = ""
    version: str = ""
    kind: str = ""
    cluster_scoped: bool = False

    @classmethod
    def sample(cls) -> "WorkloadAPISpec":
        return cls(
            domain=SAMPLE_API_DOMAIN,
            group=SAMPLE_API_GROUP,
            version=SAMPLE_API_VERSION,
            kind=SAMPLE_API_KIND,
            cluster_scoped=False,
        )


@dataclass
class WorkloadSpec:
    """Processing state shared by all workload kinds
    (reference workload.go:95-106)."""

    resources: list[str] = dc_field(default_factory=list)
    manifests: manifests_mod.Manifests = dc_field(
        default_factory=manifests_mod.Manifests
    )
    field_markers: list[FieldMarker] = dc_field(default_factory=list)
    collection_field_markers: list[CollectionFieldMarker] = dc_field(
        default_factory=list
    )
    for_collection: bool = False
    collection: Optional["WorkloadCollection"] = None
    api_spec_fields: Optional[APIFields] = None
    rbac_rules: Optional[rbac.Rules] = None

    # -- the codegen driver ---------------------------------------------

    def init_spec(self) -> None:
        """Reference workload.go:134-148."""
        self.api_spec_fields = APIFields.new_spec_root()
        if self.needs_collection_ref():
            self.append_collection_ref()
        self.rbac_rules = rbac.Rules()

    def needs_collection_ref(self) -> bool:
        """Components of a collection get a collection reference in their
        spec; the collection itself does not (workload.go:420-422)."""
        return self.collection is not None and not self.for_collection

    def append_collection_ref(self) -> None:
        """Reference workload.go:150-212 appendCollectionRef."""
        if self.api_spec_fields is None or self.collection is None:
            return
        if self.api_spec_fields.name != "Spec":
            return
        sample_namespace = "" if self.collection.is_cluster_scoped() else "default"
        collection_field = APIFields(
            name="Collection",
            type=FieldType.STRUCT,
            manifest_name="collection",
            tags='`json:"collection"`',
            sample="#collection:",
            struct_name="CollectionSpec",
            markers=[
                "+kubebuilder:validation:Optional",
                "Specifies a reference to the collection to use for this workload.",
                "Requires the name and namespace input to find the collection.",
                "If no collection field is set, default to selecting the only",
                "workload collection in the cluster, which will result in an error",
                "if not exactly one collection is found.",
            ],
            children=[
                APIFields(
                    name="Name",
                    type=FieldType.STRING,
                    manifest_name="name",
                    tags='`json:"name"`',
                    sample=f'#name: "{self.collection.api_kind.lower()}-sample"',
                    markers=[
                        "+kubebuilder:validation:Required",
                        "Required if specifying collection.  The name of the collection",
                        "within a specific collection.namespace to reference.",
                    ],
                ),
                APIFields(
                    name="Namespace",
                    type=FieldType.STRING,
                    manifest_name="namespace",
                    tags='`json:"namespace"`',
                    sample=f'#namespace: "{sample_namespace}"',
                    markers=[
                        "+kubebuilder:validation:Optional",
                        '(Default: "") The namespace where the collection exists.  Required only if',
                        "the collection is namespace scoped and not cluster scoped.",
                    ],
                ),
            ],
        )
        self.api_spec_fields.children.append(collection_field)

    def process_manifests(self, *marker_types: MarkerType) -> None:
        """Reference workload.go:218-291.

        The per-manifest work (marker transform + child codegen) is pure
        and independent across manifests, so it runs through
        :func:`operator_forge.perf.parallel_map`; results are absorbed
        into spec state serially in manifest order, which keeps output
        (and every error) identical to the ``OPERATOR_FORGE_JOBS=1`` run.
        """
        self.init_spec()

        def prepare(manifest: manifests_mod.Manifest):
            # errors are carried, not raised: they must surface in
            # manifest order relative to the serial absorb loop below
            # (e.g. a duplicate-name error in an early manifest beats a
            # decode error in a later one).  Ordering is per-manifest:
            # within one multi-document manifest, all documents decode
            # before the duplicate check runs, so a decode error in a
            # later document wins over a duplicate in an earlier one
            # (the serial reference interleaved those two per document)
            try:
                content, markers = self._transformed(manifest, marker_types)
                return content, markers, _build_children(
                    content, manifest.filename
                )
            except Exception as exc:  # re-raised at this manifest's turn
                return exc

        prepared = parallel_map(prepare, self.manifests)

        unique_names: set[str] = set()
        for manifest, outcome in zip(self.manifests, prepared):
            if isinstance(outcome, Exception):
                raise outcome
            content, markers, children = outcome
            manifest.content = content
            self.process_marker_results(markers)
            for child in children:
                if child.unique_name in unique_names:
                    raise ManifestProcessingError(
                        "child resource unique name error; error generating "
                        f"resource definition for resource kind [{child.kind}] "
                        f"with name [{child.name}] "
                        f"[{manifest.filename}]"
                    )
                unique_names.add(child.unique_name)
            manifest.child_resources = children

        manifests_mod.deduplicate_file_names(self.manifests)

    def _transformed(
        self, manifest: manifests_mod.Manifest, marker_types: tuple
    ) -> tuple:
        try:
            return _transform_manifest(manifest.content, marker_types)
        except ManifestProcessingError:
            raise
        except Exception as exc:
            raise ManifestProcessingError(
                f"{exc}; error processing manifest file {manifest.filename}"
            ) from exc

    def process_markers(
        self, manifest: manifests_mod.Manifest, *marker_types: MarkerType
    ) -> None:
        """Reference workload.go:293-329."""
        content, markers = self._transformed(manifest, marker_types)
        self.process_marker_results(markers)
        manifest.content = content

    def process_marker_results(self, markers) -> None:
        """Absorb transformed field/collection markers into spec state
        (reference workload.go:331-381)."""
        for marker in markers:
            if isinstance(marker, CollectionFieldMarker):
                self.collection_field_markers.append(marker)
            elif isinstance(marker, FieldMarker):
                self.field_markers.append(marker)
            else:
                continue

            comments: list[str] = []
            if marker.description:
                comments.extend(marker.description.split("\n"))

            if marker.default is not None:
                has_default = True
                sample = marker.default
            else:
                has_default = False
                sample = marker.original_value

            try:
                self.api_spec_fields.add_field(
                    marker.name, marker.type, comments, sample, has_default
                )
            except Exception as exc:
                raise ManifestProcessingError(str(exc)) from exc

            marker.for_collection = self.for_collection

    def process_resource_markers(self, collection: MarkerCollection) -> None:
        """Reference workload.go:122-132."""
        for manifest in self.manifests:
            for child in manifest.child_resources:
                child.process_resource_markers(collection)


class Workload:
    """Base workload (reference WorkloadBuilder interface,
    workload.go:37-71)."""

    workload_kind: WorkloadKind

    def __init__(self, name: str = ""):
        self.name = name
        self.package_name = ""
        self.api_spec = WorkloadAPISpec()
        self.spec = WorkloadSpec()
        self.companion_root_cmd = CompanionCLI()
        self.companion_sub_cmd = CompanionCLI()

    # -- identity -------------------------------------------------------

    @property
    def domain(self) -> str:
        return self.api_spec.domain

    @property
    def api_group(self) -> str:
        return self.api_spec.group

    @property
    def api_version(self) -> str:
        return self.api_spec.version

    @property
    def api_kind(self) -> str:
        return self.api_spec.kind

    def is_cluster_scoped(self) -> bool:
        return self.api_spec.cluster_scoped

    def is_standalone(self) -> bool:
        return self.workload_kind == WorkloadKind.STANDALONE

    def is_collection(self) -> bool:
        return self.workload_kind == WorkloadKind.COLLECTION

    def is_component(self) -> bool:
        return self.workload_kind == WorkloadKind.COMPONENT

    # -- companion CLI --------------------------------------------------

    def has_root_cmd_name(self) -> bool:
        return self.companion_root_cmd.has_name()

    def has_sub_cmd_name(self) -> bool:
        return self.companion_sub_cmd.has_name()

    def has_child_resources(self) -> bool:
        return len(self.spec.manifests) > 0

    # -- collection wiring ----------------------------------------------

    def get_collection(self) -> Optional["WorkloadCollection"]:
        return self.spec.collection

    def get_components(self) -> list["ComponentWorkload"]:
        return []

    def get_dependencies(self) -> list["ComponentWorkload"]:
        return []

    def set_components(self, components: list["ComponentWorkload"]) -> None:
        raise WorkloadConfigError(
            "cannot set component workloads on a "
            f"{self.workload_kind.value} - only on collections"
        )

    # -- processing -----------------------------------------------------

    def set_names(self) -> None:
        self.package_name = to_package_name(self.name)

    def set_rbac(self) -> None:
        self.spec.rbac_rules.add(rbac.for_workloads(self))

    def set_resources(self, workload_path: str) -> None:
        self.spec.process_manifests(MarkerType.FIELD)

    def load_manifests(self, workload_path: str) -> None:
        """Reference standalone.go:218-233 LoadManifests (same for all)."""
        self.spec.manifests = manifests_mod.expand_manifests(
            workload_path, self.spec.resources
        )
        for manifest in self.spec.manifests:
            manifest.load_content(self.is_collection())

    # GVK pieces become Go package names, directory names, and identifiers;
    # validate their shape up front rather than generating broken code
    _GROUP_RE = re.compile(r"^[a-z][a-z0-9]*$")
    _VERSION_RE = re.compile(r"^v[0-9]+((alpha|beta)[0-9]+)?$")
    _KIND_RE = re.compile(r"^[A-Z][A-Za-z0-9]*$")

    def validate(self) -> None:
        missing = self._missing_fields()
        if missing:
            raise WorkloadConfigError(f"missing required fields: {missing}")
        if not self._GROUP_RE.match(self.api_spec.group):
            raise WorkloadConfigError(
                f"invalid spec.api.group {self.api_spec.group!r}: must be "
                "lowercase alphanumeric starting with a letter (it becomes a "
                "Go package name)"
            )
        if not self._VERSION_RE.match(self.api_spec.version):
            raise WorkloadConfigError(
                f"invalid spec.api.version {self.api_spec.version!r}: must "
                "look like v1, v1alpha1, v2beta3, ..."
            )
        if not self._KIND_RE.match(self.api_spec.kind):
            raise WorkloadConfigError(
                f"invalid spec.api.kind {self.api_spec.kind!r}: must be a "
                "PascalCase Go identifier"
            )

    def _missing_fields(self) -> list[str]:
        missing = []
        if not self.name:
            missing.append("name")
        if not self.api_spec.group:
            missing.append("spec.api.group")
        if not self.api_spec.version:
            missing.append("spec.api.version")
        if not self.api_spec.kind:
            missing.append("spec.api.kind")
        return missing

    def get_rbac_rules(self) -> list[rbac.Rule]:
        return self.spec.rbac_rules.as_list() if self.spec.rbac_rules else []

    def get_api_spec_fields(self) -> Optional[APIFields]:
        return self.spec.api_spec_fields

    def get_manifests(self) -> manifests_mod.Manifests:
        return self.spec.manifests


class StandaloneWorkload(Workload):
    """Reference standalone.go:29-51."""

    workload_kind = WorkloadKind.STANDALONE

    def _missing_fields(self) -> list[str]:
        missing = super()._missing_fields()
        if not self.api_spec.domain:
            missing.insert(1 if self.name else 0, "spec.api.domain")
        return missing

    def set_names(self) -> None:
        super().set_names()
        if self.has_root_cmd_name():
            self.companion_root_cmd.set_common_values(self, False)


class ComponentWorkload(Workload):
    """Reference component.go:34-60."""

    workload_kind = WorkloadKind.COMPONENT

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.dependencies: list[str] = []
        self.component_dependencies: list["ComponentWorkload"] = []
        self.config_path = ""

    def get_dependencies(self) -> list["ComponentWorkload"]:
        return self.component_dependencies

    def set_names(self) -> None:
        super().set_names()
        self.companion_sub_cmd.set_common_values(self, True)

    def set_rbac(self) -> None:
        self.spec.rbac_rules.add(
            rbac.for_workloads(self, self.spec.collection)
        )


class WorkloadCollection(Workload):
    """Reference collection.go:31-53."""

    workload_kind = WorkloadKind.COLLECTION

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.component_files: list[str] = []
        self.components: list[ComponentWorkload] = []

    def _missing_fields(self) -> list[str]:
        missing = super()._missing_fields()
        if not self.api_spec.domain:
            missing.insert(1 if self.name else 0, "spec.api.domain")
        return missing

    def get_components(self) -> list[ComponentWorkload]:
        return self.components

    def set_components(self, components: list[ComponentWorkload]) -> None:
        self.components = components

    def set_names(self) -> None:
        super().set_names()
        if self.has_root_cmd_name():
            self.companion_root_cmd.set_common_values(self, False)
        self.companion_sub_cmd.set_common_values(self, True)

    def set_resources(self, workload_path: str) -> None:
        """Process own manifests with both marker types, then pull collection
        markers out of every component's manifests into this collection's API
        (reference collection.go:156-173)."""
        self.spec.process_manifests(MarkerType.FIELD, MarkerType.COLLECTION)
        for component in self.components:
            for manifest in component.spec.manifests:
                self.spec.process_markers(manifest, MarkerType.COLLECTION)


# -- strict config decoding ---------------------------------------------


def _require_keys(data: dict, allowed: set[str], context: str) -> None:
    unknown = set(data) - allowed
    if unknown:
        raise WorkloadConfigError(
            f"unknown field(s) {sorted(unknown)} in {context}"
        )


def _decode_api(data: Any, context: str) -> WorkloadAPISpec:
    if data is None:
        return WorkloadAPISpec()
    if not isinstance(data, dict):
        raise WorkloadConfigError(f"{context}.api must be a mapping")
    _require_keys(
        data, {"domain", "group", "version", "kind", "clusterScoped"},
        f"{context}.api",
    )
    return WorkloadAPISpec(
        domain=str(data.get("domain") or ""),
        group=str(data.get("group") or ""),
        version=str(data.get("version") or ""),
        kind=str(data.get("kind") or ""),
        cluster_scoped=bool(data.get("clusterScoped") or False),
    )


def _decode_cli(data: Any, context: str) -> CompanionCLI:
    if data is None:
        return CompanionCLI()
    if not isinstance(data, dict):
        raise WorkloadConfigError(f"{context} must be a mapping")
    _require_keys(data, {"name", "description"}, context)
    return CompanionCLI(
        name=str(data.get("name") or ""),
        description=str(data.get("description") or ""),
    )


def _string_list(data: Any, context: str) -> list[str]:
    if data is None:
        return []
    if not isinstance(data, list):
        raise WorkloadConfigError(f"{context} must be a list")
    return [str(item) for item in data]


def decode(data: dict, path: str = "") -> Workload:
    """Decode one workload-config document into its workload object, with
    strict unknown-field checking (reference kinds.go:25-42 Decode +
    yaml KnownFields(true) at config/parse.go:87)."""
    if not isinstance(data, dict):
        raise WorkloadConfigError(f"workload config must be a mapping: {path}")
    _require_keys(data, {"name", "kind", "spec"}, f"workload config {path}")

    kind_str = str(data.get("kind") or "")
    try:
        kind = WorkloadKind(kind_str)
    except ValueError:
        raise WorkloadConfigError(
            f"unrecognized workload kind {kind_str!r} in config {path}"
        ) from None

    name = str(data.get("name") or "")
    spec = data.get("spec") or {}
    if not isinstance(spec, dict):
        raise WorkloadConfigError(f"spec must be a mapping in config {path}")

    common = {"api", "resources"}
    if kind == WorkloadKind.STANDALONE:
        _require_keys(spec, common | {"companionCliRootcmd"}, f"{path}.spec")
        workload: Workload = StandaloneWorkload(name)
        workload.companion_root_cmd = _decode_cli(
            spec.get("companionCliRootcmd"), f"{path}.spec.companionCliRootcmd"
        )
    elif kind == WorkloadKind.COLLECTION:
        _require_keys(
            spec,
            common | {"companionCliRootcmd", "companionCliSubcmd", "componentFiles"},
            f"{path}.spec",
        )
        workload = WorkloadCollection(name)
        workload.companion_root_cmd = _decode_cli(
            spec.get("companionCliRootcmd"), f"{path}.spec.companionCliRootcmd"
        )
        workload.companion_sub_cmd = _decode_cli(
            spec.get("companionCliSubcmd"), f"{path}.spec.companionCliSubcmd"
        )
        workload.component_files = _string_list(
            spec.get("componentFiles"), f"{path}.spec.componentFiles"
        )
    else:
        _require_keys(
            spec, common | {"companionCliSubcmd", "dependencies"}, f"{path}.spec"
        )
        workload = ComponentWorkload(name)
        workload.companion_sub_cmd = _decode_cli(
            spec.get("companionCliSubcmd"), f"{path}.spec.companionCliSubcmd"
        )
        workload.dependencies = _string_list(
            spec.get("dependencies"), f"{path}.spec.dependencies"
        )

    workload.api_spec = _decode_api(spec.get("api"), f"{path}.spec")
    workload.spec.resources = _string_list(
        spec.get("resources"), f"{path}.spec.resources"
    )
    return workload
