"""Companion-CLI command metadata.

Reference: internal/workload/v1/commands/companion/cli.go.  Captures the
name/description of the generated CLI root command and per-workload
subcommands, with defaulting rules per workload type.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from ..utils import to_file_name, to_pascal_case

DEFAULT_DESCRIPTION = "Manage {kind} workload"
DEFAULT_COLLECTION_SUBCOMMAND_NAME = "collection"
DEFAULT_COLLECTION_ROOTCOMMAND_DESCRIPTION = (
    "Manage {kind} collection and components"
)


@dataclass
class CompanionCLI:
    name: str = ""
    description: str = ""
    var_name: str = ""
    file_name: str = ""
    is_subcommand: bool = False
    is_rootcommand: bool = False

    def has_name(self) -> bool:
        return self.name != ""

    def has_description(self) -> bool:
        return self.description != ""

    def set_defaults(self, workload, is_subcommand: bool) -> None:
        """Reference cli.go:39-50 SetDefaults."""
        self.is_subcommand = is_subcommand
        self.is_rootcommand = not is_subcommand
        if not self.has_name():
            self.name = self._default_name(workload)
        if not self.has_description():
            self.description = self._default_description(workload)

    def set_common_values(self, workload, is_subcommand: bool) -> None:
        """Reference cli.go:53-62 SetCommonValues."""
        self.set_defaults(workload, is_subcommand)
        self.file_name = to_file_name(self.name)
        self.var_name = to_pascal_case(self.name)

    def _default_name(self, workload) -> str:
        if workload.is_collection() and self.is_subcommand:
            return DEFAULT_COLLECTION_SUBCOMMAND_NAME
        return workload.api_kind.lower()

    def _default_description(self, workload) -> str:
        kind = workload.api_kind.lower()
        if workload.is_collection() and not self.is_subcommand:
            return DEFAULT_COLLECTION_ROOTCOMMAND_DESCRIPTION.format(kind=kind)
        return DEFAULT_DESCRIPTION.format(kind=kind)

    @staticmethod
    def subcommand_relative_filename(
        root_cmd_name: str, subcommand_folder: str, group: str, file_name: str
    ) -> str:
        """Reference cli.go:76-83 GetSubCmdRelativeFileName."""
        return os.path.join(
            "cmd", root_cmd_name, "commands", subcommand_folder, group,
            file_name + ".go",
        )
