"""Manifest loading/expansion and the ChildResource model.

Reference: internal/workload/v1/manifests/{manifest,child_resource}.go.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field as dc_field
from typing import Optional

from ..perf import cache as perfcache
from ..utils import to_file_name, to_title
from ..utils.globber import glob_manifest_files
from ..yamldoc.model import to_python
from . import rbac
from .fieldmarkers import (
    COLLECTION_FIELD_MARKER_PREFIX,
    FIELD_MARKER_PREFIX,
    MarkerCollection,
    MarkerType,
    RESOURCE_MARKER_COLLECTION_FIELD_NAME,
    RESOURCE_MARKER_FIELD_NAME,
    ResourceMarker,
    inspect_for_yaml,
)


class ManifestError(Exception):
    """Error processing a manifest file."""


@dataclass
class ChildResource:
    """A resource created by the custom resource
    (reference child_resource.go:29-58)."""

    name: str
    unique_name: str
    group: str
    version: str
    kind: str
    static_content: str = ""
    source_code: str = ""
    include_code: str = ""
    # the processed ResourceMarker behind include_code, kept for consumers
    # that evaluate the guard directly (e.g. `operator-forge preview`)
    resource_marker: object = None
    rbac: Optional[rbac.Rules] = None
    # whether metadata.name carries a marker substitution (a !!var expression
    # or !!start/!!end fragment) and therefore has no literal name constant
    name_is_dynamic: bool = False

    def __str__(self) -> str:
        return (
            f"{{Group: {self.group}, Version: {self.version}, "
            f"Kind: {self.kind}, Name: {self.name}}}"
        )

    @classmethod
    def from_object(cls, obj: dict) -> "ChildResource":
        """Build from a decoded manifest object
        (reference child_resource.go:40-58 NewChildResource)."""
        api_version = str(obj.get("apiVersion", ""))
        if "/" in api_version:
            group, version = api_version.split("/", 1)
        else:
            group, version = "", api_version
        metadata = obj.get("metadata") or {}
        name = str(metadata.get("name", ""))
        return cls(
            name=name,
            unique_name=unique_name(obj),
            group=group,
            version=version,
            kind=str(obj.get("kind", "")),
            rbac=rbac.for_resource(obj),
            name_is_dynamic=_is_dynamic_name(name),
        )

    def create_func_name(self) -> str:
        return f"Create{self.unique_name}"

    def init_func_name(self) -> str:
        """CRD children get init funcs so CRDs apply before instances
        (reference child_resource.go:108-120)."""
        if self.kind.lower() == "customresourcedefinition":
            return self.create_func_name()
        return ""

    def name_constant(self) -> str:
        """Literal name, or empty when the name is marker-controlled
        (reference child_resource.go:122-131)."""
        if self.name_is_dynamic:
            return ""
        return self.name

    def process_resource_markers(self, collection: MarkerCollection) -> None:
        """Inspect this resource's static content for a resource marker and
        compile its include/exclude guard
        (reference child_resource.go:69-106)."""
        marker = _scan_resource_marker(self.static_content)
        if marker is None:
            return
        marker.process(collection)
        if marker.include_code:
            self.include_code = marker.include_code
            self.resource_marker = marker


def _scan_resource_marker(content: str):
    """First resource marker in a child's static content, before its
    collection association (``.process``) binds run-specific state.  The
    scan is pure in ``content``, so it is memoized content-addressed;
    hits return a fresh copy safe to mutate."""

    def compute():
        inspected = inspect_for_yaml(content, MarkerType.RESOURCE)
        for result in inspected.results:
            if isinstance(result.obj, ResourceMarker):
                return result.obj
        return None

    from ..scaffold import render

    return perfcache.memoized(
        "resource-marker-scan",
        (content,),
        lambda: render.lowered_blob(
            "workload.resource_marker_scan", (content,), compute
        ),
    )


def _is_dynamic_name(name: str) -> bool:
    lowered = name.lower()
    return lowered.startswith("!!start") or name.startswith("parent.Spec") or (
        name.startswith("collection.Spec")
    )


def unique_name(obj: dict) -> str:
    """Kind + cleaned namespace + cleaned name
    (reference child_resource.go:133-163)."""
    metadata = obj.get("metadata") or {}

    def clean(value: str) -> str:
        out = to_title(str(value))
        for token in ("-", ".", ":", "!!Start", "!!End",
                      "ParentSpec", "CollectionSpec", " "):
            out = out.replace(token, "")
        return out

    return (
        f"{obj.get('kind', '')}"
        f"{clean(metadata.get('namespace', '') or '')}"
        f"{clean(metadata.get('name', '') or '')}"
    )


@dataclass
class Manifest:
    """A single input manifest file (reference manifest.go:19-26)."""

    filename: str
    source_filename: str = ""
    content: str = ""
    child_resources: list[ChildResource] = dc_field(default_factory=list)

    def load_content(self, is_collection: bool) -> None:
        """Read file content; for collection-owned manifests, rewrite
        collection markers into plain field markers since a collection's
        collection is itself (reference manifest.go:82-101)."""
        try:
            with open(self.filename, "r", encoding="utf-8") as handle:
                content = handle.read()
        except OSError as exc:
            raise ManifestError(
                f"{exc}; error processing manifest file {self.filename}"
            ) from exc
        if is_collection:
            content = content.replace(
                COLLECTION_FIELD_MARKER_PREFIX, FIELD_MARKER_PREFIX
            )
            content = content.replace(
                RESOURCE_MARKER_COLLECTION_FIELD_NAME, RESOURCE_MARKER_FIELD_NAME
            )
        self.content = content

    def extract_manifests(self) -> list[str]:
        """Split multi-document content on ``---`` lines
        (reference manifest.go:57-80)."""
        manifests: list[str] = []
        current: list[str] = []
        for line in self.content.split("\n"):
            if line.rstrip(" ") == "---":
                if any(l.strip() for l in current):
                    manifests.append("\n".join(current))
                current = []
            else:
                current.append(line)
        if any(l.strip() for l in current):
            manifests.append("\n".join(current))
        return manifests


class Manifests(list):
    """A collection of manifests (reference manifest.go:28-29)."""

    def func_names(self) -> tuple[list[str], list[str]]:
        """Create/init function names, deduplicated across resources
        (reference manifest.go:118-153)."""
        create_names: list[str] = []
        init_names: list[str] = []
        seen_create: dict[str, int] = {}
        seen_init: dict[str, int] = {}
        for manifest in self:
            for child in manifest.child_resources:
                create = child.create_func_name()
                if seen_create.get(create, 0) > 0:
                    deduped = f"{create}{seen_create[create]}"
                    seen_create[create] += 1
                    create_names.append(deduped)
                else:
                    seen_create[create] = 1
                    create_names.append(create)

                init = child.init_func_name()
                if not init:
                    continue
                if seen_init.get(init, 0) > 0:
                    deduped = f"{init}{seen_init[init]}"
                    seen_init[init] += 1
                    init_names.append(deduped)
                else:
                    seen_init[init] = 1
                    init_names.append(init)
        return create_names, init_names

    def all_child_resources(self) -> list[ChildResource]:
        out: list[ChildResource] = []
        for manifest in self:
            out.extend(manifest.child_resources)
        return out


def from_files(manifest_files: list[str]) -> Manifests:
    return Manifests(Manifest(filename=f) for f in manifest_files)


def expand_manifests(workload_path: str, manifest_paths: list[str]) -> Manifests:
    """Expand glob patterns relative to the workload config directory
    (reference manifest.go:31-53 ExpandManifests)."""
    out = Manifests()
    for pattern in manifest_paths:
        files = glob_manifest_files(os.path.join(workload_path, pattern))
        for path in files:
            rel = os.path.relpath(path, workload_path)
            out.append(
                Manifest(filename=path, source_filename=source_filename(rel))
            )
    return out


def source_filename(relative_name: str) -> str:
    """Unique snake_case ``.go`` name for a source manifest
    (reference manifest.go:156-174 getSourceFilename)."""
    name = os.path.normpath(relative_name)
    name = name.replace("/", "_")
    ext = os.path.splitext(name)[1]
    if ext:
        name = name.replace(ext, "")
    name = name.replace(".", "")
    name += ".go"
    name = to_file_name(name)
    return name.lstrip("_")


def deduplicate_file_names(manifests: Manifests) -> None:
    """Ensure generated source filenames are unique within a workload
    (reference workload.go:386-413 deduplicateFileNames)."""
    taken: set[str] = {"resources.go"}
    for manifest in manifests:
        name = manifest.source_filename
        if name in taken:
            stem = name[: -len(".go")] if name.endswith(".go") else name
            count = 1
            while f"{stem}_{count}.go" in taken:
                count += 1
            manifest.source_filename = f"{stem}_{count}.go"
        taken.add(manifest.source_filename)
