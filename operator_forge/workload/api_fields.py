"""APIFields: the CRD spec-field tree built from dotted marker paths.

Reference: internal/workload/v1/kinds/api.go.  Each field marker's dotted
``name`` path inserts a chain of struct fields ending in a typed leaf; the
tree then renders (a) Go type declarations for the generated API
(``generate_api_spec``) and (b) sample CR YAML (``generate_sample_spec``),
including kubebuilder default/optional/required markers.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Optional

from ..utils import to_title
from .fieldmarkers import FieldType


class FieldOverwriteError(Exception):
    """An attempt to overwrite an existing value was made
    (reference api.go:17 ErrOverwriteExistingValue)."""


def _go_quote(value: str) -> str:
    out = value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    return f'"{out}"'


@dataclass
class APIFields:
    name: str
    type: FieldType
    manifest_name: str = ""
    struct_name: str = ""
    tags: str = ""
    comments: list[str] = dc_field(default_factory=list)
    markers: list[str] = dc_field(default_factory=list)
    children: list["APIFields"] = dc_field(default_factory=list)
    default: str = ""
    # the raw (typed) default value, kept alongside the rendered string so
    # downstream consumers (e.g. CRD schema generation) see real types
    default_value: Any = None
    sample: str = ""
    last: bool = False

    # -- construction ---------------------------------------------------

    @classmethod
    def new_spec_root(cls) -> "APIFields":
        """Reference workload.go:134-141 (WorkloadSpec.init)."""
        return cls(
            name="Spec",
            type=FieldType.STRUCT,
            tags='`json: "spec"`',
            sample="spec:",
        )

    def add_field(
        self,
        path: str,
        field_type: FieldType,
        comments: Optional[list[str]],
        sample: Any,
        has_default: bool,
    ) -> None:
        """Insert a dotted-path field (reference api.go:33-90 AddField)."""
        obj = self
        parts = path.split(".")
        last = parts[-1]

        for part in parts[:-1]:
            found = None
            for child in obj.children:
                if child.manifest_name == part:
                    if child.type != FieldType.STRUCT:
                        raise FieldOverwriteError(
                            "an attempt to overwrite existing value was made "
                            f"for api field {path}"
                        )
                    found = child
                    break
            if found is None:
                found = self._new_child(part, FieldType.STRUCT, sample)
                found.markers.append("+kubebuilder:validation:Optional")
                found.set_struct_name(path)
                obj.children.append(found)
            obj = found

        new_child = self._new_child(last, field_type, sample)
        new_child.last = True
        new_child.set_comments_and_default(comments, sample, has_default)

        for child in obj.children:
            if child.manifest_name == last:
                if not child.is_equal(new_child):
                    raise FieldOverwriteError(
                        "an attempt to overwrite existing value was made "
                        f"for api field {path}"
                    )
                child.set_comments_and_default(comments, sample, has_default)
                return

        obj.children.append(new_child)

    @staticmethod
    def _new_child(name: str, field_type: FieldType, sample: Any) -> "APIFields":
        child = APIFields(
            name=to_title(name),
            manifest_name=name,
            type=field_type,
            tags=f'`json:"{name},omitempty"`',
        )
        child.set_sample(sample)
        return child

    def set_struct_name(self, path: str) -> None:
        """Reference api.go:195-209 generateStructName."""
        parts = ["Spec"]
        for part in path.split("."):
            parts.append(to_title(part))
            if part == self.manifest_name:
                break
        self.struct_name = "".join(parts)

    # -- equality / defaults --------------------------------------------

    def is_equal(self, other: "APIFields") -> bool:
        """Conflict detection for repeated paths (reference api.go:211-227)."""
        if self.type != other.type:
            return False
        if self.default == "" or self.default == other.default or other.default == "":
            if not self.comments or not other.comments:
                return True
            return self.comments == other.comments
        return False

    def get_sample_value(self, sample: Any) -> str:
        """Reference api.go:232-253 getSampleValue."""
        if isinstance(sample, bool):
            return "true" if sample else "false"
        if isinstance(sample, str):
            if self.type == FieldType.STRING:
                return _go_quote(sample)
            return sample
        return f"{sample}"

    def set_sample(self, sample: Any) -> None:
        if self.type == FieldType.STRUCT:
            self.sample = f"{self.manifest_name}:"
        else:
            self.sample = f"{self.manifest_name}: {self.get_sample_value(sample)}"

    def set_default(self, sample: Any) -> None:
        """Reference api.go:264-277 setDefault."""
        self.default = self.get_sample_value(sample)
        self.default_value = sample
        if not self.markers:
            self.markers.extend(
                [
                    f"+kubebuilder:default={self.default}",
                    "+kubebuilder:validation:Optional",
                    f"(Default: {self.default})",
                ]
            )
        self.set_sample(sample)

    def set_comments_and_default(
        self, comments: Optional[list[str]], sample: Any, has_default: bool
    ) -> None:
        if has_default:
            self.set_default(sample)
        if comments:
            self.comments.extend(comments)

    # -- rendering ------------------------------------------------------

    def generate_api_spec(self, kind: str) -> str:
        """Render Go type declarations (reference api.go:92-116)."""
        lines = [
            "",
            f"// {kind}Spec defines the desired state of {kind}.",
            f"type {kind}Spec struct {{",
            "\t// INSERT ADDITIONAL SPEC FIELDS - desired state of cluster",
            '\t// Important: Run "make" to regenerate code after modifying this file',
            "",
        ]
        for child in self.children:
            lines.extend(child._spec_field_lines(kind))
        lines.append("}")
        lines.append("")
        for child in self.children:
            if child.children:
                lines.extend(child._struct_lines(kind))
        return "\n".join(lines) + "\n"

    def _spec_field_lines(self, kind: str) -> list[str]:
        type_name = self.type.go_type
        if self.type == FieldType.STRUCT:
            type_name = kind + self.struct_name
        lines = []
        for marker in self.markers:
            lines.append(f"\t// {marker}")
        for comment in self.comments:
            lines.append(f"\t// {comment}")
        lines.append(f"\t{self.name} {type_name} {self.tags}")
        lines.append("")
        return lines

    def _struct_lines(self, kind: str) -> list[str]:
        if self.type != FieldType.STRUCT:
            return []
        lines = [f"type {kind}{self.struct_name} struct {{"]
        for child in self.children:
            lines.extend(child._spec_field_lines(kind))
        lines.append("}")
        lines.append("")
        for child in self.children:
            if child.children:
                lines.extend(child._struct_lines(kind))
        return lines

    def generate_sample_spec(self, required_only: bool) -> str:
        """Render sample CR YAML (reference api.go:118-136)."""
        lines: list[str] = []
        self._sample_lines(lines, 0, required_only)
        # a spec with no (rendered) fields must still parse as an object,
        # not null — commented-out samples (e.g. the optional collection
        # reference, rendered as "#collection:") don't count as fields
        has_real_field = any(
            line.strip() and not line.lstrip().startswith("#")
            for line in lines[1:]
        )
        if lines and lines[0].endswith(":") and not has_real_field:
            lines[0] += " {}"
        return "\n".join(lines) + "\n"

    def _sample_lines(
        self, lines: list[str], indent: int, required_only: bool
    ) -> None:
        lines.append("  " * indent + self.sample)
        for child in self.children:
            if child.needs_generate(required_only):
                child._sample_lines(lines, indent + 1, required_only)

    def needs_generate(self, required_only: bool) -> bool:
        if not required_only:
            return True
        return self.has_required_field()

    def has_required_field(self) -> bool:
        """A leaf without a default is required (reference api.go:148-160)."""
        if not self.children and self.default == "":
            return True
        return any(child.has_required_field() for child in self.children)
