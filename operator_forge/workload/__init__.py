"""Workload domain model (reference: internal/workload/v1).

Modules:
- :mod:`fieldmarkers`: the three concrete operator-builder markers and the
  YAML transform that rewrites marked values into code variables;
- :mod:`api_fields`: the CRD spec-field tree built from dotted marker paths;
- :mod:`rbac`: RBAC rule inference (workload rules, child-resource rules,
  Role/ClusterRole escalation);
- :mod:`manifests`: manifest loading/expansion and the ChildResource model;
- :mod:`companion`: companion-CLI naming metadata;
- :mod:`kinds`: StandaloneWorkload / WorkloadCollection / ComponentWorkload;
- :mod:`config`: workload-config parsing into a Processor tree;
- :mod:`create_api`: the `create api` processing pipeline.
"""
