"""The concrete operator-builder markers and their YAML transform.

Reference: internal/workload/v1/markers/ — marker definitions
(field_marker.go:18-38, collection_field_marker.go:12-15,
resource_marker.go:24-57, field_types.go:15-23) and the transform pipeline
(markers.go:76-250).

Marker syntax accepted in manifests (identical to the reference so existing
manifests work unchanged):

- ``+operator-builder:field:name=<dotted.path>,type=<string|int|bool>``
  with optional ``default=``, ``description=``, ``replace=`` arguments;
- ``+operator-builder:collection:field:...`` — same, but the generated code
  references the collection's spec;
- ``+operator-builder:resource:field=<name>,value=<v>,include=<bool>``
  (or ``collectionField=``) — includes/excludes the whole resource.
"""

from __future__ import annotations

import enum
import functools
import re
from dataclasses import dataclass, field as dc_field
from typing import Any, Optional, Union

from ..markers import MarkerError, Registry, define
from ..markers.inspector import InspectResult, inspect_documents
from ..utils import to_title
from ..yamldoc import Document, MapEntry, Scalar, VAR_TAG, STR_TAG
from ..yamldoc.load import load_documents

FIELD_MARKER_PREFIX = "+operator-builder:field"
COLLECTION_FIELD_MARKER_PREFIX = "+operator-builder:collection:field"
RESOURCE_MARKER_PREFIX = "+operator-builder:resource"

FIELD_SPEC_PREFIX = "parent.Spec"
COLLECTION_SPEC_PREFIX = "collection.Spec"

RESOURCE_MARKER_FIELD_NAME = "field"
RESOURCE_MARKER_COLLECTION_FIELD_NAME = "collectionField"


class MarkerType(enum.Enum):
    FIELD = "field"
    COLLECTION = "collection"
    RESOURCE = "resource"


class FieldType(enum.Enum):
    """Accepted CRD field types (reference field_types.go:15-23)."""

    UNKNOWN = ""
    STRING = "string"
    INT = "int"
    BOOL = "bool"
    STRUCT = "struct"

    @classmethod
    def from_marker_arg(cls, value: Any) -> "FieldType":
        mapping = {"string": cls.STRING, "int": cls.INT, "bool": cls.BOOL}
        if not isinstance(value, str) or value not in mapping:
            raise MarkerError(f"unable to parse field type {value!r}")
        return mapping[value]

    @property
    def go_type(self) -> str:
        return {
            FieldType.STRING: "string",
            FieldType.INT: "int",
            FieldType.BOOL: "bool",
            FieldType.STRUCT: "struct",
            FieldType.UNKNOWN: "",
        }[self]


# field names reserved for internal purposes
# (reference markers.go:155-173)
RESERVED_FIELD_NAMES = ("collection", "collection.name", "collection.namespace")


class ReservedMarkerError(Exception):
    pass


@dataclass
class _FieldMarkerBase:
    name: str
    type: FieldType
    description: Optional[str] = None
    default: Any = None
    replace: Optional[str] = None

    # processing state (not marker arguments)
    for_collection: bool = dc_field(
        default=False, init=False, metadata={"marker_skip": True}
    )
    source_code_var: str = dc_field(
        default="", init=False, metadata={"marker_skip": True}
    )
    original_value: Any = dc_field(
        default=None, init=False, metadata={"marker_skip": True}
    )

    spec_prefix = FIELD_SPEC_PREFIX

    @property
    def replace_text(self) -> str:
        return self.replace or ""

    def is_field_marker(self) -> bool:
        return isinstance(self, FieldMarker)

    def is_collection_field_marker(self) -> bool:
        return isinstance(self, CollectionFieldMarker)

    def set_original_value(self, value: str) -> None:
        # with replace=, the sample value is the replaced fragment itself
        # (reference field_marker.go:117-125)
        if self.replace_text:
            self.original_value = self.replace_text
        else:
            self.original_value = value


@dataclass
class FieldMarker(_FieldMarkerBase):
    """``+operator-builder:field`` (reference field_marker.go:26-38)."""

    spec_prefix = FIELD_SPEC_PREFIX

    def __str__(self) -> str:
        return (
            f"FieldMarker{{Name: {self.name} Type: {self.type.go_type} "
            f"Default: {self.default}}}"
        )


@dataclass
class CollectionFieldMarker(_FieldMarkerBase):
    """``+operator-builder:collection:field``
    (reference collection_field_marker.go:12-30)."""

    spec_prefix = COLLECTION_SPEC_PREFIX

    def __str__(self) -> str:
        return (
            f"CollectionFieldMarker{{Name: {self.name} "
            f"Type: {self.type.go_type} Default: {self.default}}}"
        )


class ResourceMarkerError(Exception):
    pass


# include/exclude guard snippets emitted into generated create funcs
# (reference resource_marker.go:33-41)
INCLUDE_CODE = """if {var} != {value} {{
\treturn []client.Object{{}}, nil
}}"""

EXCLUDE_CODE = """if {var} == {value} {{
\treturn []client.Object{{}}, nil
}}"""


@dataclass
class ResourceMarker:
    """``+operator-builder:resource`` (reference resource_marker.go:47-57)."""

    field: Optional[str] = None
    collection_field: Optional[str] = None
    value: Any = None
    include: Optional[bool] = None

    include_code: str = dc_field(
        default="", init=False, metadata={"marker_skip": True}
    )
    field_marker: Optional[_FieldMarkerBase] = dc_field(
        default=None, init=False, metadata={"marker_skip": True}
    )

    def __str__(self) -> str:
        return (
            f"ResourceMarker{{Field: {self.field or ''} "
            f"CollectionField: {self.collection_field or ''} "
            f"Value: {self.value} Include: {self.include}}}"
        )

    @property
    def marker_name(self) -> str:
        return self.field or self.collection_field or ""

    @property
    def spec_prefix(self) -> str:
        if self.field is not None:
            return FIELD_SPEC_PREFIX
        return COLLECTION_SPEC_PREFIX

    def validate(self) -> None:
        if self.include is None:
            raise ResourceMarkerError(
                f"resource marker missing 'include' value for marker {self}"
            )
        if not self.marker_name or self.value is None:
            raise ResourceMarkerError(
                f"resource marker missing 'collectionField', 'field' or "
                f"'value' for marker {self}"
            )

    def is_associated(self, marker: _FieldMarkerBase) -> bool:
        """Reference resource_marker.go:196-213."""
        if marker.is_collection_field_marker():
            field_name = self.collection_field or ""
        elif marker.is_field_marker() and marker.for_collection:
            field_name = self.collection_field or self.field or ""
        else:
            field_name = self.field or ""
        return field_name == marker.name

    def process(self, collection: "MarkerCollection") -> None:
        """Associate with a field marker and build the include/exclude guard
        (reference resource_marker.go:142-279)."""
        self.validate()
        for fm in collection.field_markers:
            if self.is_associated(fm):
                self.field_marker = fm
                break
        else:
            for cfm in collection.collection_field_markers:
                if self.is_associated(cfm):
                    self.field_marker = cfm
                    break
        if self.field_marker is None:
            raise ResourceMarkerError(
                f"unable to associate resource marker with 'field' or "
                f"'collectionField' marker; {self}"
            )
        self._set_source_code()

    def _set_source_code(self) -> None:
        var = f"{self.spec_prefix}.{to_title(self.marker_name)}"
        value = self.value
        if isinstance(value, bool):
            value_type = "bool"
        elif type(value) in _GO_TYPE_NAMES:
            value_type = _GO_TYPE_NAMES[type(value)]
        else:
            raise ResourceMarkerError(
                f"resource marker 'value' is of unknown type; {self}"
            )
        marker_type = self.field_marker.type.go_type
        if marker_type != value_type:
            raise ResourceMarkerError(
                "resource marker and field marker have mismatched types; "
                f"expected: {value_type}, got: {marker_type} for marker {self}"
            )
        if value_type == "string":
            rendered = _go_quote(value)
        elif value_type == "bool":
            rendered = "true" if value else "false"
        else:
            rendered = str(value)
        template = INCLUDE_CODE if self.include else EXCLUDE_CODE
        self.include_code = template.format(var=var, value=rendered)


@dataclass
class MarkerCollection:
    """Aggregated field/collection-field markers used to resolve resource
    markers (reference markers.go:56-59)."""

    field_markers: list[FieldMarker] = dc_field(default_factory=list)
    collection_field_markers: list[CollectionFieldMarker] = dc_field(
        default_factory=list
    )


# Go type names keyed by marker-value Python type (hoisted from
# ResourceMarker._set_source_code; bool handled first there since
# bool is an int subclass)
_GO_TYPE_NAMES = {str: "string", int: "int", bool: "bool"}


def _go_quote(value: str) -> str:
    out = value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    return f'"{out}"'


def build_registry(*marker_types: MarkerType) -> Registry:
    registry = Registry()
    for marker_type in marker_types:
        if marker_type == MarkerType.FIELD:
            registry.add(define(FIELD_MARKER_PREFIX, FieldMarker))
        elif marker_type == MarkerType.COLLECTION:
            registry.add(define(COLLECTION_FIELD_MARKER_PREFIX, CollectionFieldMarker))
        elif marker_type == MarkerType.RESOURCE:
            registry.add(define(RESOURCE_MARKER_PREFIX, ResourceMarker))
    return registry


def source_code_variable(prefix: str, name: str) -> str:
    """``parent.Spec.Webstore.Really.Long.Path`` style variable path
    (reference markers.go:184-186: spec prefix + strings.Title(name))."""
    return f"{prefix}.{to_title(name)}"


def source_code_field_variable(marker: _FieldMarkerBase) -> str:
    """In-string variable delimiters consumed by the code generator
    (reference markers.go:178-180)."""
    return f"!!start {marker.source_code_var} !!end"


# title-cased reserved names, computed once instead of per lookup
_RESERVED_TITLED = frozenset(to_title(r) for r in RESERVED_FIELD_NAMES)


def _is_reserved(name: str) -> bool:
    return to_title(name) in _RESERVED_TITLED


@functools.lru_cache(maxsize=256)
def _compile_replace(pattern: str) -> "re.Pattern[str]":
    """Replace-marker patterns recur across manifests and runs; compile
    each distinct pattern once."""
    return re.compile(pattern)


# each dot-separated path segment must title-case into a valid Go identifier
# (the reference silently generates uncompilable code for names like
# "my-field"; rejecting early is a deliberate improvement).  Underscores are
# legal in both Go identifiers and CRD/JSON keys, so snake_case is allowed.
_NAME_SEGMENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _validate_marker_name(name: str) -> None:
    if not name or not all(
        _NAME_SEGMENT_RE.match(segment) for segment in name.split(".")
    ):
        raise MarkerError(
            f"invalid marker field name {name!r}: each dot-separated segment "
            "must start with a letter or underscore and contain only "
            "letters, digits and underscores (it becomes a Go identifier "
            "and a CRD field name)"
        )


def transform_results(results: list[InspectResult]) -> None:
    """Rewrite marked values and comments in place
    (reference markers.go:117-250 transformYAML)."""
    for result in results:
        marker = result.obj
        if not isinstance(marker, _FieldMarkerBase):
            continue

        _validate_marker_name(marker.name)

        marker.source_code_var = source_code_variable(
            marker.spec_prefix, marker.name
        )

        if _is_reserved(marker.name):
            raise ReservedMarkerError(
                f"{marker.name} field marker cannot be used and is reserved "
                "for internal purposes"
            )

        _set_comments(marker, result)
        _set_value(marker, result)


def _append_text(marker: _FieldMarkerBase) -> str:
    if marker.is_collection_field_marker():
        return "controlled by collection field: " + marker.name
    return "controlled by field: " + marker.name


def _set_comments(marker: _FieldMarkerBase, result: InspectResult) -> None:
    """Reference markers.go:198-222 setComments."""
    element = result.element
    marker_text = result.marker_text.rstrip("\n")
    replacement = _append_text(marker)

    # a marker with a backtick string can span several comment lines, so the
    # rewrite must run over the joined comment block, not line by line
    # (reference markers.go:203-222: markerText has "\n" -> "\n#" re-added to
    # match the whole HeadComment; our marker_text is the exact substring of
    # the joined text the scanner consumed)
    scanned = element.all_comment_text()
    foot_joined = "\n".join(element.foot_comments)
    element.foot_comments = []
    head_joined = "\n".join(element.head_comments)
    if marker_text in head_joined:
        head_joined = head_joined.replace(marker_text, replacement)
    elif element.line_comment and marker_text in element.line_comment:
        element.line_comment = element.line_comment.replace(
            marker_text, replacement
        )
    elif marker_text in foot_joined:
        pass  # foot comments are dropped (reference markers.go:219)
    elif marker_text in scanned:
        # the marker spans a head/line/foot boundary: rewrite over the same
        # joined text the scanner saw and fold the result into head comments
        # (foot comments are dropped afterwards, like the reference)
        joined = scanned.replace(marker_text, replacement)
        if foot_joined:
            foot_start = len(scanned) - len(foot_joined)
            marker_start = scanned.find(marker_text)
            if marker_start + len(marker_text) > foot_start:
                # the marker consumed part of the foot block, so everything
                # after it is residual foot text — dropped like plain foot.
                # The search is anchored at the marker position so an earlier
                # pre-existing occurrence of the replacement phrase cannot
                # truncate at the wrong spot (text before the first marker
                # occurrence is unchanged by replace(), so scanned and joined
                # positions coincide up to marker_start).
                end = joined.find(replacement, marker_start) + len(replacement)
                joined = joined[:end]
            elif joined.endswith("\n" + foot_joined):
                joined = joined[: -len("\n" + foot_joined)]
        head_joined = joined
        element.line_comment = None
    # else: a prior result on this element already rewrote an identical
    # marker text (replace() rewrites every occurrence at once) — nothing
    # left to do, and the line comment must not be disturbed
    element.head_comments = head_joined.split("\n") if head_joined else []

    # description lines become comments after the rewritten marker comment
    # (reference markers.go:199-203; appended after the rewrite here so the
    # inserted lines cannot split the marker text the rewrite must match)
    if marker.description:
        description = marker.description.lstrip("\n")
        marker.description = description
        for line in description.split("\n"):
            element.head_comments.append("# " + line)


def _set_value(marker: _FieldMarkerBase, result: InspectResult) -> None:
    """Reference markers.go:226-250 setValue."""
    node = result.value_node
    if not isinstance(node, Scalar):
        raise MarkerError(
            f"field marker {marker.name!r} must annotate a scalar value, "
            f"found {type(node).__name__}"
        )

    marker.set_original_value(node.value)

    if marker.replace_text:
        node.tag = STR_TAG
        try:
            pattern = _compile_replace(marker.replace_text)
        except re.error as exc:
            raise MarkerError(
                f"unable to convert {marker.replace_text!r} to regex: {exc}"
            ) from exc
        node.value = pattern.sub(
            source_code_field_variable(marker).replace("\\", "\\\\"), node.value
        )
        node.style = None
    else:
        node.tag = VAR_TAG
        node.value = marker.source_code_var
        node.style = None


@dataclass
class InspectedYAML:
    documents: list[Document]
    results: list[InspectResult]
    warnings: list[str]


def inspect_for_yaml(
    content: Union[str, bytes], *marker_types: MarkerType
) -> InspectedYAML:
    """Inspect manifest YAML for the requested marker types and apply the
    value/comment transform (reference markers.go:76-88 InspectForYAML)."""
    if isinstance(content, bytes):
        content = content.decode("utf-8")
    registry = build_registry(*marker_types)
    documents = load_documents(content)
    results, warnings = inspect_documents(documents, registry)
    transform_results(results)
    return InspectedYAML(documents=documents, results=results, warnings=warnings)
