"""Render a workload's child manifests for a given custom resource.

This is the native equivalent of the generated companion CLI's
``generate`` subcommand (reference templates/cli/cmd_generate_sub.go:49-332
→ resources.go ``GenerateForCLI``): take a custom-resource manifest plus
the workload config, run the same marker-processing pipeline ``create
api`` uses, substitute the CR's spec values (and the collection CR's, for
components) into each child resource, evaluate resource-marker
include/exclude guards, and emit the resulting manifests.  Unlike the
reference — which requires compiling the generated Go CLI first —
``operator-forge preview`` works straight from the workload config.

It also serves as the round-trip check of SURVEY §7.3: sample CR in,
child manifests out, without a Kubernetes cluster or Go toolchain.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional

from ..utils.names import to_title
from ..yamldoc import (
    Document,
    MapEntry,
    Mapping,
    Scalar,
    Sequence,
    STR_TAG,
    load_documents,
)
from ..yamldoc.emit import emit_documents
from ..yamldoc.model import BOOL_TAG, FLOAT_TAG, INT_TAG, to_python
from .config import Processor, parse
from .create_api import create_api, init_workloads
from .fieldmarkers import (
    COLLECTION_SPEC_PREFIX,
    FIELD_SPEC_PREFIX,
    FieldType,
    source_code_variable,
)
from .kinds import Workload, WorkloadCollection

_START_END_RE = re.compile(r"!!start\s+(.+?)\s+!!end")


class PreviewError(Exception):
    pass


@dataclass
class _VarInfo:
    """Resolution data for one substitution variable."""

    dotted_name: str
    field_type: FieldType
    default: Any = None
    has_default: bool = False


@dataclass
class _Resolver:
    """Resolves ``parent.Spec.X`` / ``collection.Spec.X`` variable paths
    against CR spec dicts, falling back to marker defaults."""

    parent_vars: dict[str, _VarInfo] = field(default_factory=dict)
    collection_vars: dict[str, _VarInfo] = field(default_factory=dict)
    parent_spec: dict = field(default_factory=dict)
    collection_spec: Optional[dict] = None

    def resolve(self, var_path: str):
        if var_path.startswith(f"{FIELD_SPEC_PREFIX}."):
            info = self.parent_vars.get(var_path)
            spec = self.parent_spec
            source = "spec"
        elif var_path.startswith(f"{COLLECTION_SPEC_PREFIX}."):
            info = self.collection_vars.get(var_path)
            if self.collection_spec is None:
                raise PreviewError(
                    f"variable {var_path!r} needs a collection manifest "
                    "(--collection-manifest)"
                )
            spec = self.collection_spec
            source = "collection spec"
        else:
            raise PreviewError(f"unknown variable prefix in {var_path!r}")
        if info is None:
            raise PreviewError(f"no field marker defines variable {var_path!r}")

        found, value = _lookup(spec, info.dotted_name)
        # an explicit YAML null means unset, like the Kubernetes API
        # server's null pruning on apply
        if not found or value is None:
            if info.has_default:
                return info.default
            raise PreviewError(
                f"required field {info.dotted_name!r} missing from {source} "
                "and has no default"
            )
        _check_type(info, value)
        return value


def _lookup(spec: dict, dotted: str):
    node: Any = spec
    for segment in dotted.split("."):
        if not isinstance(node, dict) or segment not in node:
            return False, None
        node = node[segment]
    return True, node


def _check_type(info: _VarInfo, value: Any) -> None:
    expected = {
        FieldType.STRING: str,
        FieldType.INT: int,
        FieldType.BOOL: bool,
    }.get(info.field_type)
    if expected is None:  # struct or unknown: accept as-is
        return
    if expected is int and isinstance(value, bool):
        ok = False
    else:
        ok = isinstance(value, expected)
    if not ok:
        raise PreviewError(
            f"field {info.dotted_name!r} expects {info.field_type.value}, "
            f"got {type(value).__name__} ({value!r})"
        )


def _var_infos(workload: Workload) -> tuple[dict, dict]:
    parent: dict[str, _VarInfo] = {}
    collection: dict[str, _VarInfo] = {}
    for marker in workload.spec.field_markers:
        parent[source_code_variable(FIELD_SPEC_PREFIX, marker.name)] = _VarInfo(
            dotted_name=marker.name,
            field_type=marker.type,
            default=marker.default,
            has_default=marker.default is not None,
        )
    for marker in workload.spec.collection_field_markers:
        collection[
            source_code_variable(COLLECTION_SPEC_PREFIX, marker.name)
        ] = _VarInfo(
            dotted_name=marker.name,
            field_type=marker.type,
            default=marker.default,
            has_default=marker.default is not None,
        )
    return parent, collection


def _collection_own_vars(collection: Optional[WorkloadCollection]) -> dict:
    """Variables of the collection's own API spec, addressable as
    ``collection.Spec.*`` from component manifests."""
    if collection is None:
        return {}
    own: dict[str, _VarInfo] = {}
    for marker in (
        collection.spec.field_markers + collection.spec.collection_field_markers
    ):
        own[
            source_code_variable(COLLECTION_SPEC_PREFIX, marker.name)
        ] = _VarInfo(
            dotted_name=marker.name,
            field_type=marker.type,
            default=marker.default,
            has_default=marker.default is not None,
        )
    return own


def _render_scalar(value: Any) -> Scalar:
    if isinstance(value, bool):
        return Scalar(value="true" if value else "false", tag=BOOL_TAG)
    if isinstance(value, int):
        return Scalar(value=str(value), tag=INT_TAG)
    if isinstance(value, float):
        return Scalar(value=repr(value), tag=FLOAT_TAG)
    return Scalar(value=str(value), tag=STR_TAG)


def _inline_str(value: Any) -> str:
    """Render a substitution inside a larger string the way the generated
    Go code's fmt.Sprintf("%v", ...) would."""
    if isinstance(value, bool):
        return "true" if value else "false"
    return str(value)


def _substitute_node(node, resolver: _Resolver):
    if isinstance(node, Scalar):
        if node.is_var():
            return _render_scalar(resolver.resolve(node.value))
        if "!!start" in node.value:
            new = _START_END_RE.sub(
                lambda m: _inline_str(resolver.resolve(m.group(1))), node.value
            )
            return Scalar(value=new, tag=node.tag, style=node.style)
        return node
    if isinstance(node, Mapping):
        for entry in node.entries:
            entry.value = _substitute_node(entry.value, resolver)
        return node
    if isinstance(node, Sequence):
        for item in node.items:
            item.node = _substitute_node(item.node, resolver)
        return node
    return node


def _guard_allows(child, resolver: _Resolver) -> bool:
    """Evaluate a resource marker's include/exclude guard the way the
    generated Create func's IncludeCode does
    (reference resource_marker.go:241-279)."""
    marker = child.resource_marker
    if marker is None:
        return True
    var = f"{marker.spec_prefix}.{to_title(marker.marker_name)}"
    actual = resolver.resolve(var)
    if marker.include:
        return actual == marker.value
    return actual != marker.value


def _default_namespace(doc: Document, namespace: str) -> None:
    """Default metadata.namespace to the parent's, matching the generated
    create funcs for namespace-scoped parents
    (reference templates/api/resources/definition.go:59-87)."""
    root = doc.root
    if not isinstance(root, Mapping) or not namespace:
        return
    metadata = root.get("metadata")
    if not isinstance(metadata, Mapping):
        return
    existing = metadata.get("namespace")
    if isinstance(existing, Scalar) and existing.value:
        return
    if existing is None:
        metadata.entries.append(
            MapEntry(key=Scalar(value="namespace"), value=Scalar(value=namespace))
        )
    else:
        metadata.entries = [
            e if e.key.value != "namespace"
            else MapEntry(key=e.key, value=Scalar(value=namespace))
            for e in metadata.entries
        ]


def _cr_kind_and_spec(obj: dict, path: str) -> tuple[str, dict, dict]:
    if not isinstance(obj, dict) or not obj.get("kind"):
        raise PreviewError(f"manifest in {path} has no 'kind'")
    spec = obj.get("spec") or {}
    if not isinstance(spec, dict):
        raise PreviewError(f"manifest in {path} has a non-mapping 'spec'")
    metadata = obj.get("metadata") or {}
    if not isinstance(metadata, dict):
        metadata = {}
    return str(obj["kind"]), spec, metadata


def preview(
    config_path: str,
    workload_manifest: str,
    collection_manifest: Optional[str] = None,
) -> str:
    """Render child manifests for every CR document in *workload_manifest*.

    Returns a ``---``-separated YAML stream, like the generated companion
    CLI's ``generate`` output.
    """
    processor: Processor = parse(config_path)
    init_workloads(processor)
    create_api(processor)

    workloads = [p.workload for p in processor.get_processors()]
    by_kind = {w.api_kind: w for w in workloads}
    collection = next(
        (w for w in workloads if isinstance(w, WorkloadCollection)), None
    )

    collection_spec: Optional[dict] = None
    if collection_manifest is not None:
        col_docs = _load_cr_docs(collection_manifest)
        if not col_docs:
            raise PreviewError(f"no documents in {collection_manifest}")
        kind, collection_spec, _ = _cr_kind_and_spec(
            col_docs[0], collection_manifest
        )
        if collection is None:
            raise PreviewError(
                "--collection-manifest given but the workload config has "
                "no collection"
            )
        if kind != collection.api_kind:
            raise PreviewError(
                f"collection manifest kind {kind!r} does not match the "
                f"collection kind {collection.api_kind!r}"
            )

    outputs: list[str] = []
    for obj in _load_cr_docs(workload_manifest):
        kind, spec, metadata = _cr_kind_and_spec(obj, workload_manifest)
        workload = by_kind.get(kind)
        if workload is None:
            raise PreviewError(
                f"kind {kind!r} does not match any workload in "
                f"{config_path} (known: {sorted(by_kind)})"
            )

        parent_vars, collection_vars = _var_infos(workload)
        collection_vars.update(_collection_own_vars(collection))
        resolver = _Resolver(
            parent_vars=parent_vars,
            collection_vars=collection_vars,
            parent_spec=spec,
            collection_spec=(
                spec
                if isinstance(workload, WorkloadCollection)
                and collection_spec is None
                else collection_spec
            ),
        )
        namespace = (
            str(metadata.get("namespace") or "")
            if not workload.is_cluster_scoped()
            else ""
        )

        for manifest in workload.spec.manifests:
            for child in manifest.child_resources:
                if not _guard_allows(child, resolver):
                    continue
                docs = load_documents(child.static_content)
                for doc in docs:
                    if doc.root is None:
                        continue
                    doc.root = _substitute_node(doc.root, resolver)
                    _default_namespace(doc, namespace)
                    outputs.append(
                        emit_documents([doc], explicit_start=False).strip("\n")
                    )

    if not outputs:
        return ""
    return "---\n" + "\n---\n".join(outputs) + "\n"


def _load_cr_docs(path: str) -> list[dict]:
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError as exc:
        raise PreviewError(f"unable to read manifest {path}: {exc}") from exc
    docs = []
    for doc in load_documents(text):
        if doc.root is None:
            continue
        docs.append(to_python(doc.root))
    return docs
