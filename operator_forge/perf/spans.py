"""Lightweight span profiler for the generation pipeline.

Env-gated (``OPERATOR_FORGE_PROFILE=1``) or enabled programmatically
(bench.py).  Spans aggregate wall-clock durations per stage name into a
process-global, thread-safe table; the CLI prints the table to stderr on
exit when the env var is set, and bench.py surfaces it as the ``stages``
breakdown in the BENCH JSON.

Stages are *inclusive* and may nest or run on worker threads, so totals
can overlap and, under ``OPERATOR_FORGE_JOBS>1``, sum to more than the
elapsed wall time — read them as attribution, not as a partition.

``span`` itself is a module attribute swapped between the timing
implementation and a no-op closure returning a shared null context:
with profiling off, a span costs one attribute lookup and zero clock
or environment reads (bench.py's ``span_overhead`` micro-guard holds
the disabled path under 1% of the codegen pipeline).  The swap happens
whenever the enable state changes (:func:`enable`, :func:`use_env`,
:func:`refresh`); code that mutates ``OPERATOR_FORGE_PROFILE`` mid-
process must call :func:`refresh` (the process-pool workers do).
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager

_lock = threading.Lock()
_totals: dict = {}  # name -> [calls, seconds]
_forced = None  # None: follow the env var; bool: programmatic override
_active = False


def _env_enabled() -> bool:
    return os.environ.get("OPERATOR_FORGE_PROFILE", "") not in ("", "0")


def enabled() -> bool:
    return _active


def refresh() -> None:
    """Recompute the enable state (override, else the env var) and swap
    the ``span`` implementation accordingly."""
    global _active, span
    _active = _forced if _forced is not None else _env_enabled()
    span = _span_on if _active else _span_off


def enable(flag: bool = True) -> None:
    """Programmatic on/off override (bench.py, tests)."""
    global _forced
    _forced = flag
    refresh()


def use_env() -> None:
    """Drop any programmatic override; follow ``OPERATOR_FORGE_PROFILE``."""
    global _forced
    _forced = None
    refresh()


def reset() -> None:
    with _lock:
        _totals.clear()


def record(name: str, seconds: float) -> None:
    with _lock:
        entry = _totals.setdefault(name, [0, 0.0])
        entry[0] += 1
        entry[1] += seconds


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def _span_off(name: str):
    """Profiling disabled: hand back the shared null context — no env
    read, no clock read, no generator frame."""
    return _NULL_SPAN


@contextmanager
def _span_on(name: str):
    start = time.perf_counter()
    try:
        yield
    finally:
        record(name, time.perf_counter() - start)


#: time a stage — rebound by :func:`refresh` to the no-op closure when
#: profiling is off (always call as ``spans.span(...)``)
span = _span_off

refresh()


def snapshot() -> dict:
    """``{stage: {"calls": n, "s": seconds}}``, sorted by stage name."""
    with _lock:
        return {
            name: {"calls": calls, "s": round(seconds, 6)}
            for name, (calls, seconds) in sorted(_totals.items())
        }


def report(stream) -> None:
    """Print the aggregate table (slowest stage first)."""
    snap = snapshot()
    if not snap:
        return
    width = max(len(name) for name in snap)
    print(f"{'stage'.ljust(width)}  {'calls':>7}  {'seconds':>10}", file=stream)
    for name, data in sorted(snap.items(), key=lambda kv: -kv[1]["s"]):
        print(
            f"{name.ljust(width)}  {data['calls']:>7}  {data['s']:>10.4f}",
            file=stream,
        )
