"""Lightweight span profiler for the generation pipeline.

Env-gated (``OPERATOR_FORGE_PROFILE=1``) or enabled programmatically
(bench.py).  Spans aggregate wall-clock durations per stage name into a
process-global, thread-safe table; the CLI prints the table to stderr on
exit when the env var is set, and bench.py surfaces it as the ``stages``
breakdown in the BENCH JSON.

Stages are *inclusive* and may nest or run on worker threads, so totals
can overlap and, under ``OPERATOR_FORGE_JOBS>1``, sum to more than the
elapsed wall time — read them as attribution, not as a partition.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager

_lock = threading.Lock()
_totals: dict = {}  # name -> [calls, seconds]
_forced = None  # None: follow the env var; bool: programmatic override


def enabled() -> bool:
    if _forced is not None:
        return _forced
    return os.environ.get("OPERATOR_FORGE_PROFILE", "") not in ("", "0")


def enable(flag: bool = True) -> None:
    """Programmatic on/off override (bench.py, tests)."""
    global _forced
    _forced = flag


def use_env() -> None:
    """Drop any programmatic override; follow ``OPERATOR_FORGE_PROFILE``."""
    global _forced
    _forced = None


def reset() -> None:
    with _lock:
        _totals.clear()


def record(name: str, seconds: float) -> None:
    with _lock:
        entry = _totals.setdefault(name, [0, 0.0])
        entry[0] += 1
        entry[1] += seconds


@contextmanager
def span(name: str):
    """Time a stage; free (no clock reads) when profiling is disabled."""
    if not enabled():
        yield
        return
    start = time.perf_counter()
    try:
        yield
    finally:
        record(name, time.perf_counter() - start)


def snapshot() -> dict:
    """``{stage: {"calls": n, "s": seconds}}``, sorted by stage name."""
    with _lock:
        return {
            name: {"calls": calls, "s": round(seconds, 6)}
            for name, (calls, seconds) in sorted(_totals.items())
        }


def report(stream) -> None:
    """Print the aggregate table (slowest stage first)."""
    snap = snapshot()
    if not snap:
        return
    width = max(len(name) for name in snap)
    print(f"{'stage'.ljust(width)}  {'calls':>7}  {'seconds':>10}", file=stream)
    for name, data in sorted(snap.items(), key=lambda kv: -kv[1]["s"]):
        print(
            f"{name.ljust(width)}  {data['calls']:>7}  {data['s']:>10.4f}",
            file=stream,
        )
