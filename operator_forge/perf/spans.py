"""Span profiler and tracer for the generation pipeline.

Two telemetry layers share one instrumentation point (``spans.span``):

- **Aggregate totals** (``OPERATOR_FORGE_PROFILE=1`` or programmatic
  :func:`enable`): wall-clock durations per stage name in a
  process-global, thread-safe table; the CLI prints the table to stderr
  on exit when the env var is set, and bench.py surfaces it as the
  ``stages`` breakdown in the BENCH JSON.
- **Structured trace events** (``OPERATOR_FORGE_TRACE=path`` or
  programmatic :func:`enable_tracing`): every span additionally records
  a trace event — span id, parent span id, process id, thread id,
  start timestamp, duration, and a small args dict — into a bounded
  ring buffer (:data:`DEFAULT_RING` events, oldest dropped first;
  ``OPERATOR_FORGE_TRACE_EVENTS`` overrides).  The buffer exports as
  Chrome trace-event JSON (:func:`write_chrome_trace` — load it in
  ``chrome://tracing`` / Perfetto), and process-pool workers drain
  their buffers into each task's HMAC-signed result so the parent's
  timeline covers serial, thread, and process execution in one file
  (see :mod:`operator_forge.perf.workers`).

Stages are *inclusive* and may nest or run on worker threads, so totals
can overlap and, under ``OPERATOR_FORGE_JOBS>1``, sum to more than the
elapsed wall time — read them as attribution, not as a partition.

``span`` itself is a module attribute swapped between the tracing
implementation, the timing implementation, and a no-op closure
returning a shared null context: with both layers off, a span costs one
attribute lookup and zero clock or environment reads (bench.py's
``span_overhead`` and ``telemetry`` micro-guards hold the disabled path
under 1% of the codegen pipeline).  The swap happens whenever the
enable state changes (:func:`enable`, :func:`enable_tracing`,
:func:`use_env`, :func:`refresh`); code that mutates the env vars
mid-process must call :func:`refresh` (the process-pool workers do).

Distributed tracing (PR 15): a request that crosses a process boundary
carries a **trace context** — a W3C-traceparent-shaped pair of trace id
and parent span id, derived *deterministically* from the request's own
id (:func:`rpc_context`; never wall-clock randomness).  The receiving
server adopts the context for the request's lifetime
(:func:`remote_segment`): every span recorded under it is tagged with
the trace id and renders its span/parent ids inside a per-request
*segment namespace* (``<segment>:<n>``), so ids from different
processes can never collide, and the segment's top-level spans parent
directly onto the caller's span id.  When the request finishes, the
server drains exactly its segment's events (:func:`drain_trace`) and
ships them back on the response — the same drain-and-merge contract the
process-pool workers have used since PR 6 — so the original client's
ring holds ONE connected timeline from CLI keystroke to pool-worker
instruction (:func:`trace_connectivity` is the graph check the tests
and commit-check assert).  Thread fan-out propagates the context via
:func:`current_context`/:func:`adopt_context` (``perf.parallel_map``
and the workers backends do this automatically).
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from contextlib import contextmanager

_lock = threading.Lock()
_totals: dict = {}  # name -> [calls, seconds]
_forced = None  # None: follow the env var; bool: programmatic override
_active = False
_trace_forced = None  # None: follow OPERATOR_FORGE_TRACE; bool: override
_trace_active = False

#: default trace ring-buffer capacity (events); the ring bounds memory
#: on long serve/watch sessions — a full ring drops the OLDEST events
DEFAULT_RING = 100_000

_ids = itertools.count(1)  # span ids; next() is GIL-atomic
_span_stack = threading.local()  # per-thread open-span id stack
_trace_ctx = threading.local()  # per-thread adopted trace context
# cached: getpid() is a syscall (tens of µs under sandboxed kernels)
# and the pid only changes at fork, where the hook below refreshes it
_PID = os.getpid()


def _ring_capacity() -> int:
    raw = os.environ.get("OPERATOR_FORGE_TRACE_EVENTS", "").strip()
    try:
        n = int(raw) if raw else DEFAULT_RING
    except ValueError:
        n = DEFAULT_RING
    return max(n, 1)


_events: collections.deque = collections.deque(maxlen=DEFAULT_RING)
#: monotonically increasing count of events ever appended — lets the
#: flight recorder detect churn even when the FULL ring's length no
#: longer changes (a saturated deque stays at maxlen forever)
_seq = [0]
#: per-trace shipping queues: a trace-tagged event is bucketed here at
#: append time (in ADDITION to the ring, which keeps its copy for the
#: flight recorder), so :func:`drain_trace` pops O(own events) instead
#: of scanning a saturated 100k ring under the lock per traced request
_trace_buckets: dict = {}  # trace id -> [events], insertion-ordered
_BUCKETS_MAX = 256  # orphaned traces (abandoned requests) FIFO-evict


def _bucket_locked(event) -> None:
    trace = event["args"].get("trace")
    if trace is None:
        return
    bucket = _trace_buckets.get(trace)
    if bucket is None:
        while len(_trace_buckets) >= _BUCKETS_MAX:
            del _trace_buckets[next(iter(_trace_buckets))]
        bucket = _trace_buckets[trace] = []
    bucket.append(event)
    cap = _events.maxlen or DEFAULT_RING
    if len(bucket) > cap:
        del bucket[0]


def _env_enabled() -> bool:
    return os.environ.get("OPERATOR_FORGE_PROFILE", "") not in ("", "0")


def _env_trace_path() -> str:
    return os.environ.get("OPERATOR_FORGE_TRACE", "").strip()


def enabled() -> bool:
    return _active


def trace_enabled() -> bool:
    return _trace_active


def refresh() -> None:
    """Recompute the enable states (overrides, else the env vars) and
    swap the ``span`` implementation accordingly."""
    global _active, _trace_active, span, _events
    _active = _forced if _forced is not None else _env_enabled()
    _trace_active = (
        _trace_forced if _trace_forced is not None
        else bool(_env_trace_path())
    )
    if _trace_active:
        if _events.maxlen != _ring_capacity():
            with _lock:
                _events = collections.deque(_events, maxlen=_ring_capacity())
        span = _span_trace
    elif _active:
        span = _span_on
    else:
        span = _span_off


def enable(flag: bool = True) -> None:
    """Programmatic aggregate-totals on/off override (bench.py, tests)."""
    global _forced
    _forced = flag
    refresh()


def enable_tracing(flag) -> None:
    """Programmatic trace-event on/off override; ``None`` restores the
    ``OPERATOR_FORGE_TRACE`` env-driven state."""
    global _trace_forced
    _trace_forced = flag
    refresh()


def use_env() -> None:
    """Drop the programmatic overrides; follow the env vars."""
    global _forced, _trace_forced
    _forced = None
    _trace_forced = None
    refresh()


def reset() -> None:
    with _lock:
        _totals.clear()


def clear_events() -> None:
    with _lock:
        _events.clear()
        _trace_buckets.clear()


def _clear_events_after_fork() -> None:
    # a forked worker inherits the parent's ring by copy-on-write; its
    # first drain must ship only events the WORKER produced.  The lock
    # is re-created: fork can land while another parent thread holds
    # it, and the child would inherit it locked forever
    global _PID, _lock
    _PID = os.getpid()
    _lock = threading.Lock()
    _events.clear()
    _trace_buckets.clear()
    _seq[0] = 0
    stack = getattr(_span_stack, "ids", None)
    if stack:
        stack.clear()
    # a forked worker must not inherit the forking thread's adopted
    # trace context: its tasks ship their own (pid-suffixed) segment
    _trace_ctx.value = None


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_clear_events_after_fork)


def record(name: str, seconds: float) -> None:
    with _lock:
        entry = _totals.setdefault(name, [0, 0.0])
        entry[0] += 1
        entry[1] += seconds


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def _span_off(name: str, args=None):
    """Telemetry disabled: hand back the shared null context — no env
    read, no clock read, no generator frame."""
    return _NULL_SPAN


@contextmanager
def _span_on(name: str, args=None):
    start = time.perf_counter()
    try:
        yield
    finally:
        record(name, time.perf_counter() - start)


class _TraceSpan:
    """Tracing context: aggregate totals PLUS one ring-buffer event per
    span, with parent linkage via a per-thread open-span stack."""

    __slots__ = ("name", "args", "start", "sid", "parent")

    def __init__(self, name: str, args):
        self.name = name
        self.args = args

    def __enter__(self):
        stack = getattr(_span_stack, "ids", None)
        if stack is None:
            stack = _span_stack.ids = []
        self.parent = stack[-1] if stack else 0
        self.sid = next(_ids)
        stack.append(self.sid)
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        elapsed = time.perf_counter() - self.start
        stack = _span_stack.ids
        if stack and stack[-1] == self.sid:
            stack.pop()
        record(self.name, elapsed)
        # span linkage is authoritative: user args never clobber it
        event_args = dict(self.args) if self.args else {}
        ctx = getattr(_trace_ctx, "value", None)
        if ctx is None:
            event_args["id"] = self.sid
            event_args["parent"] = self.parent
        else:
            # inside an adopted trace context: ids render in the
            # request's segment namespace (collision-free across
            # processes) and the segment's local roots parent onto the
            # caller's span id, so the merged timeline stays one tree
            event_args["id"] = f"{ctx.seg}:{self.sid}"
            event_args["parent"] = (
                f"{ctx.seg}:{self.parent}" if self.parent else ctx.base
            )
            event_args["trace"] = ctx.trace
        event = {
            "name": self.name,
            "ph": "X",
            "pid": _PID,
            "tid": threading.get_ident(),
            "ts": round(self.start * 1e6, 1),
            "dur": round(elapsed * 1e6, 1),
            "args": event_args,
        }
        # appends share the ring lock with every reader: snapshot/drain
        # iterate the deque, and a lock-free append concurrent with
        # that iteration raises RuntimeError (deque mutated) — a traced
        # request would error instead of answering
        with _lock:
            _events.append(event)
            _seq[0] += 1
            _bucket_locked(event)
        return False


def _span_trace(name: str, args=None):
    return _TraceSpan(name, args)


#: time a stage — rebound by :func:`refresh` to the no-op closure when
#: telemetry is off (always call as ``spans.span(...)``).  The optional
#: ``args`` mapping lands in the trace event (small, plain data only).
span = _span_off

refresh()


# -- trace-event access ----------------------------------------------------


def events_snapshot() -> list:
    """A copy of the current ring-buffer contents, oldest first."""
    with _lock:
        return list(_events)


def event_seq() -> int:
    """How many events have EVER been appended to this process's ring
    — the flight recorder's churn signal (a saturated ring's length is
    pinned at maxlen, so length alone cannot detect new activity)."""
    with _lock:
        return _seq[0]


def drain_events() -> list:
    """Pop and return every buffered event (the worker-side shipping
    primitive: each process-pool task drains its ring into the sealed
    result so the parent can merge one timeline).  The per-trace
    shipping buckets empty with it — they only ever hold copies of
    ring events, and a pool worker (which ships THIS way, never via
    :func:`drain_trace`) would otherwise retain every tagged copy for
    its lifetime."""
    with _lock:
        out = list(_events)
        _events.clear()
        _trace_buckets.clear()
    return out


def ingest_events(events) -> None:
    """Append externally produced events (a worker's drained buffer)
    into this process's ring."""
    if not events:
        return
    with _lock:
        _events.extend(events)
        _seq[0] += len(events)
        # a server ingesting a child's shipped segment must be able to
        # drain it onward (coordinator -> client): tagged events join
        # their trace's shipping bucket too
        for event in events:
            _bucket_locked(event)


# -- distributed trace context ---------------------------------------------


class _TraceCtx:
    """An adopted trace context: the trace id every span tags, the
    segment namespace its ids render in, and the caller-side span id
    the segment's local roots parent onto."""

    __slots__ = ("trace", "seg", "base")

    def __init__(self, trace: str, seg: str, base):
        self.trace = trace
        self.seg = seg
        self.base = base

    def as_tuple(self) -> tuple:
        return (self.trace, self.seg, self.base)


def _derive_trace_id(key) -> str:
    """A trace id from a request id — deterministic (same request id,
    same trace id, byte for byte), never entropy."""
    import hashlib

    return hashlib.sha256(
        ("operator-forge-trace:" + repr(key)).encode("utf-8")
    ).hexdigest()[:16]


def _derive_segment(trace: str, parent, label: str) -> str:
    """A segment namespace for one adopted request: deterministic in
    (trace id, caller span, role label) with the pid folded in so two
    servers adopting the same dispatch (a re-dispatched fleet
    submission) can never emit colliding span ids."""
    import hashlib

    return hashlib.sha256(
        f"{trace}|{parent}|{label}|{os.getpid()}".encode("utf-8")
    ).hexdigest()[:10]


def _render_current(ctx, stack):
    """The calling thread's innermost open span id, rendered in the
    active namespace (``ctx`` may be None)."""
    if ctx is not None:
        return f"{ctx.seg}:{stack[-1]}" if stack else ctx.base
    return stack[-1] if stack else 0


def current_context():
    """The calling thread's trace context as a plain tuple — with
    ``base`` re-anchored to the thread's innermost open span, so a
    fan-out layer (``parallel_map``, the workers backends) that hands
    this to its worker threads parents their spans under the span that
    submitted the work.  ``None`` when no context is adopted."""
    ctx = getattr(_trace_ctx, "value", None)
    if ctx is None:
        return None
    stack = getattr(_span_stack, "ids", None)
    return (ctx.trace, ctx.seg, _render_current(ctx, stack or []))


def adopt_context(ctx) -> None:
    """Install (or with ``None`` clear) a propagated trace context on
    the calling thread.  ``ctx`` is the tuple :func:`current_context`
    returns — possibly with a worker-specific segment suffix (the
    process-pool workers append ``.p<pid>`` so their local span
    counters cannot collide with the parent's)."""
    _trace_ctx.value = None if ctx is None else _TraceCtx(*ctx)


@contextmanager
def remote_segment(trace: str, parent, label: str = "serve"):
    """Adopt an incoming request's trace context for the duration of
    its handler: spans recorded inside are tagged with ``trace``,
    namespaced under a fresh deterministic segment, and parented onto
    the caller's ``parent`` span id.  Used by every server transport
    (stdio serve, daemon sessions, the fleet coordinator)."""
    previous = getattr(_trace_ctx, "value", None)
    _trace_ctx.value = _TraceCtx(
        str(trace), _derive_segment(str(trace), parent, label), parent
    )
    try:
        yield
    finally:
        _trace_ctx.value = previous


def context_bound(fn):
    """Bind the calling thread's trace context onto ``fn`` for
    execution on another thread — the ONE capture-adopt-clear wrapper
    every thread fan-out layer shares (``perf.parallel_map`` and the
    workers thread backend), so propagation semantics cannot drift
    between them.  Returns ``fn`` unchanged when no context is
    active."""
    ctx = current_context()
    if ctx is None:
        return fn

    def bound(*args, **kwargs):
        adopt_context(ctx)
        try:
            return fn(*args, **kwargs)
        finally:
            adopt_context(None)

    return bound


def rpc_context(key=None):
    """The trace-context payload an outgoing request should carry
    (``{"id": <trace>, "parent": <span id>}``), or ``None`` when
    tracing is off.  Inside an adopted context the trace id is
    inherited; at the root (the traced CLI client) a new trace id is
    derived deterministically from ``key`` — pass the request's own id
    (a batch submission key, a job id) so re-sends of an idempotent
    request belong to the same trace."""
    if not _trace_active:
        return None
    ctx = getattr(_trace_ctx, "value", None)
    stack = getattr(_span_stack, "ids", None) or []
    if ctx is not None:
        return {"id": ctx.trace, "parent": _render_current(ctx, stack)}
    trace = _derive_trace_id(key if key is not None else next(_ids))
    return {"id": trace, "parent": stack[-1] if stack else 0}


def parse_trace_field(req: dict):
    """Validate a request's ``trace`` field into ``(trace_id, parent)``
    or ``None`` — servers must never crash on a malformed context (it
    is telemetry, not payload)."""
    raw = req.get("trace")
    if not isinstance(raw, dict):
        return None
    trace = raw.get("id")
    if not isinstance(trace, str) or not trace:
        return None
    parent = raw.get("parent")
    if not isinstance(parent, (str, int)):
        parent = 0
    return (trace, parent)


def drain_trace(trace: str) -> list:
    """Pop and return every buffered event tagged with ``trace`` (in
    emit order) — the server-side shipping primitive: a request's
    segment travels back on its response without stealing concurrent
    requests' spans.  The drain pops the trace's *shipping bucket*
    (O(the segment's own events), never an O(ring) scan — a saturated
    server ring would otherwise serialize every traced response on a
    100k-element walk under the lock); the RING keeps its copies, so
    the flight recorder and ``trace-dump`` still see what the server
    did for traced requests after they were answered."""
    with _lock:
        return _trace_buckets.pop(trace, [])


def instant(name: str, args=None) -> None:
    """Record a zero-duration marker event (Chrome ``i`` phase) into
    the ring — request admission markers, anomaly stamps.  Cheap no-op
    when tracing is off.  Carries the same id/parent/trace linkage as a
    span, so markers join the connectivity graph."""
    if not _trace_active:
        return
    stack = getattr(_span_stack, "ids", None) or []
    ctx = getattr(_trace_ctx, "value", None)
    sid = next(_ids)
    event_args = dict(args) if args else {}
    if ctx is None:
        event_args["id"] = sid
        event_args["parent"] = stack[-1] if stack else 0
    else:
        event_args["id"] = f"{ctx.seg}:{sid}"
        event_args["parent"] = _render_current(ctx, stack)
        event_args["trace"] = ctx.trace
    event = {
        "name": name,
        "ph": "i",
        "s": "t",
        "pid": _PID,
        "tid": threading.get_ident(),
        "ts": round(time.perf_counter() * 1e6, 1),
        "args": event_args,
    }
    with _lock:  # see _TraceSpan.__exit__: readers iterate under it
        _events.append(event)
        _seq[0] += 1
        _bucket_locked(event)


def trace_connectivity(events) -> dict:
    """The acceptance check for a merged distributed timeline: every
    event must be transitively parented to a root span (``parent`` 0).
    Returns ``{"ok", "events", "roots", "orphans", "pids"}`` —
    ``orphans`` lists (name, id, dangling ancestor parent) triples for
    diagnosis; ``pids`` is the set of processes contributing spans."""
    ids = {}
    for event in events:
        eid = event["args"].get("id")
        if eid is not None:
            ids[eid] = event["args"].get("parent", 0)
    roots = 0
    orphans = []
    for event in events:
        eid = event["args"].get("id")
        if eid is None:
            orphans.append((event.get("name"), None, None))
            continue
        parent = ids.get(eid, 0)
        if parent == 0:
            roots += 1
            continue
        seen = set()
        while parent != 0:
            if parent not in ids:
                orphans.append((event.get("name"), eid, parent))
                break
            if parent in seen:  # a cycle is as broken as a dangle
                orphans.append((event.get("name"), eid, parent))
                break
            seen.add(parent)
            parent = ids[parent]
    return {
        "ok": not orphans and bool(ids),
        "events": len(events),
        "roots": roots,
        "orphans": orphans[:16],
        "pids": sorted({e.get("pid") for e in events}),
    }


_export_suppressed = False


def suppress_trace_export(flag: bool = True) -> None:
    """Process-pool workers call this (via their shipped task config):
    a worker's nested CLI mains must NOT write the env-configured trace
    file — its events ship back through the sealed result round-trip
    and the parent writes one merged file."""
    global _export_suppressed
    _export_suppressed = flag


def trace_export_suppressed() -> bool:
    return _export_suppressed


def _id_sort_key(eid):
    # local span ids are ints, remote-segment ids are strings; the sort
    # key must be type-stable (a ts tie between the two would otherwise
    # raise) while keeping the historical int ordering
    if isinstance(eid, int):
        return (0, eid, "")
    return (1, 0, str(eid))


def chrome_trace() -> dict:
    """The buffered events as a Chrome trace-event JSON object
    (``chrome://tracing`` / Perfetto's legacy JSON format).  Events are
    sorted by timestamp then span id, so repeated exports of the same
    buffer are byte-identical."""
    events = sorted(
        events_snapshot(),
        key=lambda e: (e["ts"], _id_sort_key(e["args"].get("id", 0))),
    )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "operator-forge"},
    }


def write_chrome_trace(path: str) -> int:
    """Write the Chrome trace JSON to ``path``; returns the number of
    events written.  Best-effort: an unwritable path is reported to
    stderr, never raised (telemetry must not fail the command)."""
    import json
    import sys

    trace = chrome_trace()
    try:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w", encoding="utf-8") as handle:
            json.dump(trace, handle)
    except OSError as exc:
        print(f"trace: cannot write {path}: {exc}", file=sys.stderr)
        return 0
    return len(trace["traceEvents"])


def export_env_trace(announce: bool = True):
    """Write the ``OPERATOR_FORGE_TRACE`` file NOW, if the env var is
    set and export is not worker-suppressed — the drain-path hook: a
    long-running daemon/fleet exiting through the drain machinery must
    not depend on unwinding all the way out of the outermost ``main()``
    to persist its timeline (and a re-export at that outer exit just
    rewrites a superset of the same file).  Returns the event count, or
    ``None`` when no export was configured."""
    import sys

    path = os.environ.get("OPERATOR_FORGE_TRACE", "").strip()
    if not path or _export_suppressed:
        return None
    n = write_chrome_trace(path)
    if announce:
        print(f"trace: {n} events -> {path}", file=sys.stderr)
    return n


# -- aggregate access ------------------------------------------------------


def snapshot() -> dict:
    """``{stage: {"calls": n, "s": seconds}}`` in deterministic report
    order: total seconds descending, stage name as the tie-break — so
    serve ``stats`` and bench ``stages`` diffs are stable run to run."""
    with _lock:
        items = [
            (name, calls, round(seconds, 6))
            for name, (calls, seconds) in _totals.items()
        ]
    items.sort(key=lambda item: (-item[2], item[0]))
    return {
        name: {"calls": calls, "s": seconds}
        for name, calls, seconds in items
    }


def report(stream) -> None:
    """Print the aggregate table (slowest stage first, name
    tie-break — :func:`snapshot` order)."""
    snap = snapshot()
    if not snap:
        return
    width = max(len(name) for name in snap)
    print(f"{'stage'.ljust(width)}  {'calls':>7}  {'seconds':>10}", file=stream)
    for name, data in snap.items():
        print(
            f"{name.ljust(width)}  {data['calls']:>7}  {data['s']:>10.4f}",
            file=stream,
        )
