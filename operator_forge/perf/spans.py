"""Span profiler and tracer for the generation pipeline.

Two telemetry layers share one instrumentation point (``spans.span``):

- **Aggregate totals** (``OPERATOR_FORGE_PROFILE=1`` or programmatic
  :func:`enable`): wall-clock durations per stage name in a
  process-global, thread-safe table; the CLI prints the table to stderr
  on exit when the env var is set, and bench.py surfaces it as the
  ``stages`` breakdown in the BENCH JSON.
- **Structured trace events** (``OPERATOR_FORGE_TRACE=path`` or
  programmatic :func:`enable_tracing`): every span additionally records
  a trace event — span id, parent span id, process id, thread id,
  start timestamp, duration, and a small args dict — into a bounded
  ring buffer (:data:`DEFAULT_RING` events, oldest dropped first;
  ``OPERATOR_FORGE_TRACE_EVENTS`` overrides).  The buffer exports as
  Chrome trace-event JSON (:func:`write_chrome_trace` — load it in
  ``chrome://tracing`` / Perfetto), and process-pool workers drain
  their buffers into each task's HMAC-signed result so the parent's
  timeline covers serial, thread, and process execution in one file
  (see :mod:`operator_forge.perf.workers`).

Stages are *inclusive* and may nest or run on worker threads, so totals
can overlap and, under ``OPERATOR_FORGE_JOBS>1``, sum to more than the
elapsed wall time — read them as attribution, not as a partition.

``span`` itself is a module attribute swapped between the tracing
implementation, the timing implementation, and a no-op closure
returning a shared null context: with both layers off, a span costs one
attribute lookup and zero clock or environment reads (bench.py's
``span_overhead`` and ``telemetry`` micro-guards hold the disabled path
under 1% of the codegen pipeline).  The swap happens whenever the
enable state changes (:func:`enable`, :func:`enable_tracing`,
:func:`use_env`, :func:`refresh`); code that mutates the env vars
mid-process must call :func:`refresh` (the process-pool workers do).
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from contextlib import contextmanager

_lock = threading.Lock()
_totals: dict = {}  # name -> [calls, seconds]
_forced = None  # None: follow the env var; bool: programmatic override
_active = False
_trace_forced = None  # None: follow OPERATOR_FORGE_TRACE; bool: override
_trace_active = False

#: default trace ring-buffer capacity (events); the ring bounds memory
#: on long serve/watch sessions — a full ring drops the OLDEST events
DEFAULT_RING = 100_000

_ids = itertools.count(1)  # span ids; next() is GIL-atomic
_span_stack = threading.local()  # per-thread open-span id stack
# cached: getpid() is a syscall (tens of µs under sandboxed kernels)
# and the pid only changes at fork, where the hook below refreshes it
_PID = os.getpid()


def _ring_capacity() -> int:
    raw = os.environ.get("OPERATOR_FORGE_TRACE_EVENTS", "").strip()
    try:
        n = int(raw) if raw else DEFAULT_RING
    except ValueError:
        n = DEFAULT_RING
    return max(n, 1)


_events: collections.deque = collections.deque(maxlen=DEFAULT_RING)


def _env_enabled() -> bool:
    return os.environ.get("OPERATOR_FORGE_PROFILE", "") not in ("", "0")


def _env_trace_path() -> str:
    return os.environ.get("OPERATOR_FORGE_TRACE", "").strip()


def enabled() -> bool:
    return _active


def trace_enabled() -> bool:
    return _trace_active


def refresh() -> None:
    """Recompute the enable states (overrides, else the env vars) and
    swap the ``span`` implementation accordingly."""
    global _active, _trace_active, span, _events
    _active = _forced if _forced is not None else _env_enabled()
    _trace_active = (
        _trace_forced if _trace_forced is not None
        else bool(_env_trace_path())
    )
    if _trace_active:
        if _events.maxlen != _ring_capacity():
            with _lock:
                _events = collections.deque(_events, maxlen=_ring_capacity())
        span = _span_trace
    elif _active:
        span = _span_on
    else:
        span = _span_off


def enable(flag: bool = True) -> None:
    """Programmatic aggregate-totals on/off override (bench.py, tests)."""
    global _forced
    _forced = flag
    refresh()


def enable_tracing(flag) -> None:
    """Programmatic trace-event on/off override; ``None`` restores the
    ``OPERATOR_FORGE_TRACE`` env-driven state."""
    global _trace_forced
    _trace_forced = flag
    refresh()


def use_env() -> None:
    """Drop the programmatic overrides; follow the env vars."""
    global _forced, _trace_forced
    _forced = None
    _trace_forced = None
    refresh()


def reset() -> None:
    with _lock:
        _totals.clear()


def clear_events() -> None:
    with _lock:
        _events.clear()


def _clear_events_after_fork() -> None:
    # a forked worker inherits the parent's ring by copy-on-write; its
    # first drain must ship only events the WORKER produced.  The lock
    # is re-created: fork can land while another parent thread holds
    # it, and the child would inherit it locked forever
    global _PID, _lock
    _PID = os.getpid()
    _lock = threading.Lock()
    _events.clear()
    stack = getattr(_span_stack, "ids", None)
    if stack:
        stack.clear()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_clear_events_after_fork)


def record(name: str, seconds: float) -> None:
    with _lock:
        entry = _totals.setdefault(name, [0, 0.0])
        entry[0] += 1
        entry[1] += seconds


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def _span_off(name: str, args=None):
    """Telemetry disabled: hand back the shared null context — no env
    read, no clock read, no generator frame."""
    return _NULL_SPAN


@contextmanager
def _span_on(name: str, args=None):
    start = time.perf_counter()
    try:
        yield
    finally:
        record(name, time.perf_counter() - start)


class _TraceSpan:
    """Tracing context: aggregate totals PLUS one ring-buffer event per
    span, with parent linkage via a per-thread open-span stack."""

    __slots__ = ("name", "args", "start", "sid", "parent")

    def __init__(self, name: str, args):
        self.name = name
        self.args = args

    def __enter__(self):
        stack = getattr(_span_stack, "ids", None)
        if stack is None:
            stack = _span_stack.ids = []
        self.parent = stack[-1] if stack else 0
        self.sid = next(_ids)
        stack.append(self.sid)
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        elapsed = time.perf_counter() - self.start
        stack = _span_stack.ids
        if stack and stack[-1] == self.sid:
            stack.pop()
        record(self.name, elapsed)
        # span linkage is authoritative: user args never clobber it
        event_args = dict(self.args) if self.args else {}
        event_args["id"] = self.sid
        event_args["parent"] = self.parent
        _events.append({
            "name": self.name,
            "ph": "X",
            "pid": _PID,
            "tid": threading.get_ident(),
            "ts": round(self.start * 1e6, 1),
            "dur": round(elapsed * 1e6, 1),
            "args": event_args,
        })
        return False


def _span_trace(name: str, args=None):
    return _TraceSpan(name, args)


#: time a stage — rebound by :func:`refresh` to the no-op closure when
#: telemetry is off (always call as ``spans.span(...)``).  The optional
#: ``args`` mapping lands in the trace event (small, plain data only).
span = _span_off

refresh()


# -- trace-event access ----------------------------------------------------


def events_snapshot() -> list:
    """A copy of the current ring-buffer contents, oldest first."""
    with _lock:
        return list(_events)


def drain_events() -> list:
    """Pop and return every buffered event (the worker-side shipping
    primitive: each process-pool task drains its ring into the sealed
    result so the parent can merge one timeline)."""
    with _lock:
        out = list(_events)
        _events.clear()
    return out


def ingest_events(events) -> None:
    """Append externally produced events (a worker's drained buffer)
    into this process's ring."""
    if not events:
        return
    with _lock:
        _events.extend(events)


_export_suppressed = False


def suppress_trace_export(flag: bool = True) -> None:
    """Process-pool workers call this (via their shipped task config):
    a worker's nested CLI mains must NOT write the env-configured trace
    file — its events ship back through the sealed result round-trip
    and the parent writes one merged file."""
    global _export_suppressed
    _export_suppressed = flag


def trace_export_suppressed() -> bool:
    return _export_suppressed


def chrome_trace() -> dict:
    """The buffered events as a Chrome trace-event JSON object
    (``chrome://tracing`` / Perfetto's legacy JSON format).  Events are
    sorted by timestamp then span id, so repeated exports of the same
    buffer are byte-identical."""
    events = sorted(
        events_snapshot(),
        key=lambda e: (e["ts"], e["args"].get("id", 0)),
    )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "operator-forge"},
    }


def write_chrome_trace(path: str) -> int:
    """Write the Chrome trace JSON to ``path``; returns the number of
    events written.  Best-effort: an unwritable path is reported to
    stderr, never raised (telemetry must not fail the command)."""
    import json
    import sys

    trace = chrome_trace()
    try:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w", encoding="utf-8") as handle:
            json.dump(trace, handle)
    except OSError as exc:
        print(f"trace: cannot write {path}: {exc}", file=sys.stderr)
        return 0
    return len(trace["traceEvents"])


# -- aggregate access ------------------------------------------------------


def snapshot() -> dict:
    """``{stage: {"calls": n, "s": seconds}}`` in deterministic report
    order: total seconds descending, stage name as the tie-break — so
    serve ``stats`` and bench ``stages`` diffs are stable run to run."""
    with _lock:
        items = [
            (name, calls, round(seconds, 6))
            for name, (calls, seconds) in _totals.items()
        ]
    items.sort(key=lambda item: (-item[2], item[0]))
    return {
        name: {"calls": calls, "s": seconds}
        for name, calls, seconds in items
    }


def report(stream) -> None:
    """Print the aggregate table (slowest stage first, name
    tie-break — :func:`snapshot` order)."""
    snap = snapshot()
    if not snap:
        return
    width = max(len(name) for name in snap)
    print(f"{'stage'.ljust(width)}  {'calls':>7}  {'seconds':>10}", file=stream)
    for name, data in snap.items():
        print(
            f"{name.ljust(width)}  {data['calls']:>7}  {data['s']:>10.4f}",
            file=stream,
        )
