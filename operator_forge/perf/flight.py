"""Anomaly flight recorder: post-mortems without pre-arranged tracing.

PR 6's ``operator-forge trace`` answers "where did the time go?" — but
only if the process was wrapped in advance, and only if it lives to
export.  A long-running daemon or fleet coordinator that gets killed
mid-request, or that quietly absorbs deadline abandonments and lock
timeouts for hours, leaves nothing.  This module is the black box: the
always-on bounded trace ring (servers enable event tracing for their
lifetime) is snapshotted to an **HMAC-signed on-disk capsule** —

- whenever an **anomaly** fires: request deadline abandonment, a
  busy/lock-timeout rejection, client disconnect mid-request, worker
  poison-task quarantine, cache entry quarantine, daemon
  suspect/evict, fleet re-dispatch (each site calls :func:`anomaly`,
  which is a two-comparison no-op when the recorder is disarmed);
- **periodically** (``OPERATOR_FORGE_FLIGHT_S``, default 5s): a
  rolling per-pid capsule refreshed whenever the ring has grown, so a
  SIGKILL — which by definition cannot run an exit hook — still leaves
  the last few seconds of spans on disk;
- at **drain** (:func:`flush` with ``final=True``): the clean-shutdown
  export the daemon/fleet teardown calls.

Capsules live under ``OPERATOR_FORGE_FLIGHT_DIR`` (default:
``<cache root>/flight/``, inside the cache dir's budget — ``cache gc``
reports and sweeps them, so the recorder can never grow unbounded) and
are bounded by ``OPERATOR_FORGE_FLIGHT_KEEP`` (default 16, oldest
removed first).  Each capsule is a two-line file: a JSON header
carrying an HMAC-SHA256 signature under the PR 1 per-user cache key,
then the canonical-JSON body (anomaly log + ring snapshot + process
metadata).  :func:`verify_capsule` authenticates before trusting —
the same client-side-verification trust model as the disk cache and
the remote tier.

Anomaly *recording* is decoupled from capsule *writing*: sites may
fire while holding scheduler locks, so :func:`anomaly` only appends to
a bounded in-memory log and wakes the recorder thread; all file I/O
happens there (or in an explicit :func:`flush`).  Capsule writes are
debounced (at most one anomaly capsule per
``OPERATOR_FORGE_FLIGHT_DEBOUNCE_S``, default 1s) so an anomaly storm
costs one snapshot, not one file per event.  A write failure is
counted (``flight.write_errors``), never raised — telemetry must not
fail the command — and the ``flight.write_error@capsule`` chaos kind
proves that path deterministically.

The live ring is also served on demand: the ``trace-dump`` serve op
returns :func:`dump` (ring snapshot + anomaly log) from a running
serve/daemon/fleet process, no kill required.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

from . import env_number

#: capsule format marker (header key ``fmt``)
FORMAT = "operator-forge-flight-v1"

DEFAULT_KEEP = 16
DEFAULT_INTERVAL_S = 5.0
DEFAULT_DEBOUNCE_S = 1.0
#: bounded in-memory anomaly log (newest kept)
ANOMALY_LOG_MAX = 256

_lock = threading.Lock()
_armed = [False]
_dir_override = [None]
_anomalies: collections.deque = collections.deque(maxlen=ANOMALY_LOG_MAX)
_pending = [0]            # anomalies not yet captured in a capsule
_last_write = [0.0]       # monotonic time of the last anomaly capsule
_seq = [0]                # capsule sequence number (per process)
_wake = threading.Event()
_thread = [None]
_stop = threading.Event()


def _reset_after_fork() -> None:
    # a forked pool worker is not a server: it must neither inherit a
    # recorder thread (fork drops threads anyway) nor keep writing the
    # parent's capsules
    global _lock
    _lock = threading.Lock()
    _armed[0] = False
    _thread[0] = None
    _anomalies.clear()
    _pending[0] = 0


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_after_fork)


# -- knobs -----------------------------------------------------------------


def flight_dir() -> str:
    """Where capsules land: ``OPERATOR_FORGE_FLIGHT_DIR``, programmatic
    override, else ``<cache root>/flight`` — inside the cache
    directory so the existing budget machinery (``cache gc``) owns the
    footprint."""
    if _dir_override[0] is not None:
        return _dir_override[0]
    raw = os.environ.get("OPERATOR_FORGE_FLIGHT_DIR", "").strip()
    if raw:
        return raw
    from . import cache as pf_cache

    return os.path.join(pf_cache.get_cache().root(), "flight")


def keep_budget() -> int:
    """Max capsules kept on disk (``OPERATOR_FORGE_FLIGHT_KEEP``,
    default 16; oldest removed first).  The rolling periodic capsule
    rewrites one file per pid, so it consumes a single slot."""
    return env_number(
        "OPERATOR_FORGE_FLIGHT_KEEP", DEFAULT_KEEP, cast=int, minimum=1
    )


def interval_s() -> float:
    """Periodic-export cadence (``OPERATOR_FORGE_FLIGHT_S``, default
    5s; <= 0 disables the rolling capsule, anomaly capsules still
    write)."""
    return env_number(
        "OPERATOR_FORGE_FLIGHT_S", DEFAULT_INTERVAL_S, minimum=None
    )


def debounce_s() -> float:
    """Minimum gap between anomaly capsules
    (``OPERATOR_FORGE_FLIGHT_DEBOUNCE_S``, default 1s)."""
    return env_number(
        "OPERATOR_FORGE_FLIGHT_DEBOUNCE_S", DEFAULT_DEBOUNCE_S,
        minimum=0.0,
    )


def capsule_events() -> int:
    """How many ring events (the newest) one capsule snapshots
    (``OPERATOR_FORGE_FLIGHT_EVENTS``, default 2048).  A busy daemon's
    FULL ring is ~100k events ≈ tens of MB of canonical JSON — writing
    that every rolling tick would burn a core on serialization and
    stream tens of MB to disk for the process's whole lifetime; a
    post-mortem wants the last few seconds, and 2048 spans IS several
    seconds of even a very hot server."""
    return env_number(
        "OPERATOR_FORGE_FLIGHT_EVENTS", 2048, cast=int, minimum=16
    )


def configure(directory=None) -> None:
    """Programmatic capsule-directory override (tests, bench legs);
    ``None`` restores env/default selection."""
    _dir_override[0] = directory


def armed() -> bool:
    return _armed[0]


# -- anomaly sites ---------------------------------------------------------


def anomaly(kind: str, detail=None) -> None:
    """Record one anomaly.  Disarmed (every non-server process), this
    is a single list-index check — the planted sites ride the same
    <1% disabled-path budget as the span sites.  Armed, it appends to
    the bounded log, stamps an instant marker into the trace ring
    (joining the request's connectivity graph when a trace context is
    active), counts ``flight.anomalies``, and wakes the recorder
    thread to write a debounced capsule."""
    if not _armed[0]:
        return
    from . import metrics, spans

    entry = {
        "kind": kind,
        "detail": detail,
        "t": round(time.time(), 3),
    }
    with _lock:
        _anomalies.append(entry)
        _pending[0] += 1
    metrics.counter("flight.anomalies").inc()
    metrics.counter(f"flight.anomaly.{kind}").inc()
    spans.instant(f"anomaly:{kind}", args=(
        dict(detail) if isinstance(detail, dict) else
        ({"detail": detail} if detail is not None else None)
    ))
    _wake.set()


def anomaly_log() -> list:
    """The bounded in-memory anomaly log, oldest first."""
    with _lock:
        return list(_anomalies)


def dump() -> dict:
    """The live flight surface (the ``trace-dump`` op's payload): the
    current ring snapshot plus the anomaly log — the same data a
    capsule would persist, served from the running process."""
    from . import spans

    return {
        "anomalies": anomaly_log(),
        "armed": _armed[0],
        "events": spans.events_snapshot(),
        "pid": os.getpid(),
    }


# -- capsules --------------------------------------------------------------


def _capsule_doc(kind: str) -> dict:
    from .. import __version__
    from . import spans

    events = spans.events_snapshot()
    budget = capsule_events()
    return {
        "anomalies": anomaly_log(),
        # the newest tail only (see capsule_events): bounded
        # serialization cost and capsule size however full the ring is
        "events": events[-budget:],
        "events_dropped": max(0, len(events) - budget),
        "kind": kind,
        "pid": os.getpid(),
        "version": __version__,
        "written_at": round(time.time(), 3),
    }


def _write_capsule(kind: str, path: str) -> bool:
    """Serialize, sign, and atomically publish one capsule.  Never
    raises: a recorder that cannot write must not take the server (or
    the anomaly site) down with it."""
    from . import cache as pf_cache
    from . import faults, metrics

    try:
        if faults.should_fire("flight.write_error", "capsule"):
            raise OSError("injected fault: flight.write_error@capsule")
        body = json.dumps(
            _capsule_doc(kind), sort_keys=True, separators=(",", ":")
        ).encode("utf-8")
        key = pf_cache._load_hmac_key()
        header = {
            "fmt": FORMAT,
            "sig": (
                pf_cache._sign(key, body).hex() if key is not None
                else ""
            ),
        }
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "wb") as handle:
            handle.write(json.dumps(header, sort_keys=True).encode())
            handle.write(b"\n")
            handle.write(body)
        os.replace(tmp, path)
    except (OSError, ValueError, TypeError):
        metrics.counter("flight.write_errors").inc()
        return False
    metrics.counter("flight.capsules").inc()
    return True


def _sanitize(kind: str) -> str:
    return "".join(c if c.isalnum() or c in "._" else "-" for c in kind)


def _enforce_keep(base: str) -> None:
    try:
        names = [
            n for n in os.listdir(base)
            if n.startswith("capsule-") and n.endswith(".json")
        ]
    except OSError:
        return
    budget = keep_budget()
    if len(names) <= budget:
        return
    stamped = []
    for name in names:
        try:
            stamped.append(
                (os.stat(os.path.join(base, name)).st_mtime_ns, name)
            )
        except OSError:
            continue
    for _mtime, name in sorted(stamped)[: max(0, len(stamped) - budget)]:
        try:
            os.remove(os.path.join(base, name))
        except OSError:
            pass


def _write_anomaly_capsule(kind: str) -> bool:
    base = flight_dir()
    with _lock:
        _seq[0] += 1
        seq = _seq[0]
        _pending[0] = 0
        _last_write[0] = time.monotonic()
    path = os.path.join(
        base, f"capsule-{os.getpid()}-{seq:04d}-{_sanitize(kind)}.json"
    )
    ok = _write_capsule(kind, path)
    if ok:
        _enforce_keep(base)
    return ok


def _write_rolling_capsule() -> bool:
    # one rolling file per pid, refreshed in place: the SIGKILL
    # survivor.  It rewrites rather than accumulates, so it takes one
    # keep-budget slot forever
    path = os.path.join(flight_dir(), f"capsule-{os.getpid()}-ring.json")
    return _write_capsule("periodic", path)


def flush(final: bool = False) -> bool:
    """Write pending anomalies (and, with ``final``, a drain capsule)
    synchronously — the teardown hook, also handy for tests.  Returns
    whether anything was written."""
    wrote = False
    with _lock:
        pending = _pending[0]
    if pending:
        kind = "anomaly"
        log = anomaly_log()
        if log:
            kind = log[-1]["kind"]
        wrote = _write_anomaly_capsule(kind) or wrote
    if final and _armed[0]:
        from . import spans

        if spans.events_snapshot():
            wrote = _write_anomaly_capsule("drain") or wrote
    return wrote


# -- capsule reading --------------------------------------------------------


def read_capsule(path: str) -> tuple:
    """``(authenticated, doc)`` for a capsule file.  ``authenticated``
    is True only when the body verifies against the local HMAC key
    (the same trust rule as the disk cache: bytes from disk are
    claims, the signature is the proof).  Raises ``OSError`` /
    ``ValueError`` on an unreadable or structurally broken file."""
    import hmac as _hmac

    from . import cache as pf_cache

    with open(path, "rb") as handle:
        raw = handle.read()
    head, sep, body = raw.partition(b"\n")
    if not sep:
        raise ValueError(f"{path}: not a flight capsule (no header)")
    header = json.loads(head.decode("utf-8"))
    if header.get("fmt") != FORMAT:
        raise ValueError(f"{path}: not a flight capsule")
    doc = json.loads(body.decode("utf-8"))
    key = pf_cache._load_hmac_key()
    sig = header.get("sig", "")
    authenticated = bool(
        key is not None and sig
        and _hmac.compare_digest(
            bytes.fromhex(sig), pf_cache._sign(key, body)
        )
    )
    return authenticated, doc


def verify_capsule(path: str) -> bool:
    """Whether ``path`` is a structurally valid, HMAC-authenticated
    capsule (never raises)."""
    try:
        authenticated, _doc = read_capsule(path)
    except (OSError, ValueError, TypeError):
        return False
    return authenticated


def capsule_ttl_s() -> float:
    """How long a capsule stays relevant before ``cache gc`` sweeps it
    (``OPERATOR_FORGE_FLIGHT_TTL_S``, default 7 days)."""
    return env_number(
        "OPERATOR_FORGE_FLIGHT_TTL_S", 7 * 24 * 3600.0, minimum=0.0
    )


def sweep(default_base=None) -> dict:
    """The ``cache gc`` hook: report the capsule footprint and remove
    *expired* capsules — older than :func:`capsule_ttl_s`, or beyond
    the :func:`keep_budget` (oldest first) — so the recorder can never
    grow unbounded even if the owning server died before its own
    enforcement ran.  ``default_base`` is only the fallback when no
    env/programmatic override is set (``cache gc`` passes ``<its
    root>/flight`` so a root-overridden store sweeps its own capsules)
    — the override resolution itself lives HERE, in one place.
    Returns ``{"entries", "bytes", "removed", "bytes_reclaimed"}``
    (post-sweep footprint)."""
    if _dir_override[0] is not None:
        base = _dir_override[0]
    elif os.environ.get("OPERATOR_FORGE_FLIGHT_DIR", "").strip():
        base = os.environ["OPERATOR_FORGE_FLIGHT_DIR"].strip()
    elif default_base is not None:
        base = default_base
    else:
        base = flight_dir()
    try:
        names = [
            n for n in os.listdir(base)
            if n.startswith("capsule-") and n.endswith(".json")
        ]
    except OSError:
        names = []
    stamped = []
    for name in names:
        path = os.path.join(base, name)
        try:
            st = os.stat(path)
        except OSError:
            continue
        stamped.append((st.st_mtime, st.st_size, path))
    stamped.sort()
    ttl = capsule_ttl_s()
    cutoff = time.time() - ttl
    budget = keep_budget()
    overflow = max(0, len(stamped) - budget)
    removed = 0
    freed = 0
    survivors_entries = 0
    survivors_bytes = 0
    for i, (mtime, size, path) in enumerate(stamped):
        expired = mtime < cutoff or i < overflow
        if expired:
            try:
                os.remove(path)
            except OSError:
                survivors_entries += 1
                survivors_bytes += size
                continue
            removed += 1
            freed += size
        else:
            survivors_entries += 1
            survivors_bytes += size
    return {
        "entries": survivors_entries,
        "bytes": survivors_bytes,
        "removed": removed,
        "bytes_reclaimed": freed,
    }


# -- the recorder thread ----------------------------------------------------


def _recorder_loop() -> None:
    from . import spans

    last_seq = -1
    while True:
        interval = interval_s()
        timeout = interval if interval > 0 else 3600.0
        with _lock:
            pending = _pending[0]
            since_last = time.monotonic() - _last_write[0]
        if pending:
            remaining = debounce_s() - since_last
            if remaining <= 0:
                flush()
                continue
            # a debounce-deferred anomaly must not wait out the whole
            # periodic interval (or, with the periodic export disabled,
            # the next anomaly) — wake exactly when its window expires
            timeout = min(timeout, remaining)
        _wake.wait(timeout)
        _wake.clear()
        if _stop.is_set():
            return
        if not _armed[0]:
            continue
        with _lock:
            pending = _pending[0]
            since_last = time.monotonic() - _last_write[0]
        if pending and since_last >= debounce_s():
            flush()
            continue
        if interval > 0:
            # churn is detected by the append counter, not the ring
            # length — a saturated ring's length is pinned at maxlen
            # while its contents keep turning over, and the rolling
            # capsule exists precisely for the last few seconds before
            # a SIGKILL
            seq = spans.event_seq()
            if seq and seq != last_seq:
                last_seq = seq
                _write_rolling_capsule()


def arm(directory=None) -> None:
    """Turn the recorder on (servers call this at boot): anomaly sites
    go live and the periodic recorder thread starts.  Idempotent."""
    if directory is not None:
        configure(directory)
    _armed[0] = True
    thread = _thread[0]
    if thread is None or not thread.is_alive():
        _stop.clear()
        thread = threading.Thread(
            target=_recorder_loop, daemon=True, name="flight-recorder",
        )
        _thread[0] = thread
        thread.start()


def disarm(final: bool = False) -> None:
    """Turn the recorder off (server teardown; ``final`` writes the
    drain capsule first).  Idempotent; the thread is woken so it can
    observe the stop flag and retire."""
    if final and _armed[0]:
        flush(final=True)
    _armed[0] = False
    _stop.set()
    _wake.set()
    thread = _thread[0]
    if thread is not None and thread is not threading.current_thread():
        thread.join(2.0)
    _thread[0] = None


def reset() -> None:
    """Test hygiene: disarm, drop the log and overrides."""
    disarm()
    with _lock:
        _anomalies.clear()
        _pending[0] = 0
        _last_write[0] = 0.0
    _stop.clear()
    _wake.clear()
    configure(None)
