"""Performance infrastructure for the generation pipeline.

Three coordinated pieces (PR 1 tentpole):

- :mod:`operator_forge.perf.cache` — a content-addressed cache that keys
  each pipeline stage on a hash of its inputs (workload-config bytes,
  manifest bytes, CLI flags, generator version) and optionally persists
  to ``.operator-forge-cache/``;
- :func:`parallel_map` — ordered thread-pool execution for the
  independent per-manifest and per-file steps (``OPERATOR_FORGE_JOBS``);
- :mod:`operator_forge.perf.spans` — a lightweight span profiler
  (``OPERATOR_FORGE_PROFILE=1``) surfaced as the ``stages`` breakdown in
  the benchmark JSON.

The Go reference has none of this (it regenerates everything on every
run); all three are additive and default to behavior-preserving modes:
output bytes are identical with the cache off, on, warm, serial, or
parallel.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor

_pool = None
_pool_size = 0
_pool_lock = threading.Lock()


def _executor(jobs: int) -> ThreadPoolExecutor:
    """Process-shared worker pool, recreated only when the configured job
    count changes — per-call pool construction costs more than the small
    pipeline tasks it would run."""
    global _pool, _pool_size
    with _pool_lock:
        if _pool is None or _pool_size != jobs:
            if _pool is not None:
                _pool.shutdown(wait=False)
            _pool = ThreadPoolExecutor(
                max_workers=jobs, thread_name_prefix="operator-forge"
            )
            _pool_size = jobs
        return _pool


def n_jobs() -> int:
    """Worker count for parallel pipeline stages.

    ``OPERATOR_FORGE_JOBS`` overrides; the default is the machine's CPU
    count.  Values below 1 (or unparseable) select the serial path.
    """
    raw = os.environ.get("OPERATOR_FORGE_JOBS", "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            return 1
    return os.cpu_count() or 1


def parallel_map(fn, items):
    """Ordered map over ``items``, using a thread pool when more than one
    job is configured.

    Results are collected in input order and the first exception (in
    input order) propagates, so a successful parallel run is observably
    equivalent to the ``OPERATOR_FORGE_JOBS=1`` serial loop —
    byte-identical output is proven by tests/test_perf_parallel.py.  On
    a mid-run failure, tasks in other chunks may still complete (their
    side effects are not rolled back), so partial state can differ from
    a serial run that stops at the failing item — the ``make -j`` trade.

    Items are dispatched as one contiguous chunk per worker (scheduling
    59 one-file writes as 59 futures costs more than the writes).  Tasks
    must not call ``parallel_map`` themselves: the pool is shared, so
    nested waits could starve it.
    """
    items = list(items)
    jobs = n_jobs()
    if jobs <= 1 or len(items) <= 1:
        return [fn(item) for item in items]
    jobs = min(jobs, len(items))
    step = (len(items) + jobs - 1) // jobs
    chunks = [items[i : i + step] for i in range(0, len(items), step)]

    def run_chunk(chunk):
        return [fn(item) for item in chunk]

    out = []
    for chunk_result in _executor(jobs).map(run_chunk, chunks):
        out.extend(chunk_result)
    return out
