"""Performance infrastructure for the generation pipeline.

Three coordinated pieces (PR 1 tentpole):

- :mod:`operator_forge.perf.cache` — a content-addressed cache that keys
  each pipeline stage on a hash of its inputs (workload-config bytes,
  manifest bytes, CLI flags, generator version) and optionally persists
  to ``.operator-forge-cache/``;
- :func:`parallel_map` — ordered thread-pool execution for the
  independent per-manifest and per-file steps (``OPERATOR_FORGE_JOBS``);
- :mod:`operator_forge.perf.spans` — a lightweight span profiler
  (``OPERATOR_FORGE_PROFILE=1``) surfaced as the ``stages`` breakdown in
  the benchmark JSON.

The Go reference has none of this (it regenerates everything on every
run); all three are additive and default to behavior-preserving modes:
output bytes are identical with the cache off, on, warm, serial, or
parallel.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor

_pools: dict = {}  # max_workers -> shared ThreadPoolExecutor
_pool_lock = threading.Lock()


def _forget_pools_after_fork() -> None:
    # a forked child (perf.workers process backend) inherits the
    # executor objects but not their threads; reusing one would hang
    _pools.clear()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_forget_pools_after_fork)


def _shutdown_pools() -> None:
    with _pool_lock:
        for pool in _pools.values():
            pool.shutdown(wait=False)
        _pools.clear()


import atexit  # noqa: E402

atexit.register(_shutdown_pools)


def _executor(jobs: int) -> ThreadPoolExecutor:
    """Process-shared worker pool, one per worker count — per-call pool
    construction costs more than the small pipeline tasks it would run.
    Pools are never shut down mid-run: concurrent parallel_map callers
    with different job counts (batch groups fanning out vet/test work
    at once) must not tear down each other's executor, so each size
    keeps its own pool until process exit.  The distinct sizes in play
    are a handful (CPU count plus explicit OPERATOR_FORGE_JOBS values),
    and idle threads are near-free."""
    with _pool_lock:
        pool = _pools.get(jobs)
        if pool is None:
            pool = _pools[jobs] = ThreadPoolExecutor(
                max_workers=jobs, thread_name_prefix="operator-forge"
            )
        return pool


def env_number(name: str, default, *, cast=float, minimum=0):
    """Parse a numeric tuning knob from the environment.

    Empty/missing or unparseable values fall back to ``default``; the
    result is floored at ``minimum`` (pass ``minimum=None`` to skip the
    clamp).  One definition for every ``OPERATOR_FORGE_*`` numeric knob
    — timeouts, retry budgets, fault-hang duration — so the parse rule
    can't drift between subsystems."""
    raw = os.environ.get(name, "").strip()
    try:
        value = cast(raw) if raw else default
    except ValueError:
        value = default
    if minimum is not None and value < minimum:
        value = minimum
    return value


def n_jobs() -> int:
    """Worker count for parallel pipeline stages.

    ``OPERATOR_FORGE_JOBS`` overrides; the default is the machine's CPU
    count.  Values below 1 (or unparseable) select the serial path.
    """
    raw = os.environ.get("OPERATOR_FORGE_JOBS", "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            return 1
    return os.cpu_count() or 1


def parallel_map(fn, items):
    """Ordered map over ``items``, using a thread pool when more than one
    job is configured.

    Results are collected in input order and the first exception (in
    input order) propagates, so a successful parallel run is observably
    equivalent to the ``OPERATOR_FORGE_JOBS=1`` serial loop —
    byte-identical output is proven by tests/test_perf_parallel.py.  On
    a mid-run failure, tasks in other chunks may still complete (their
    side effects are not rolled back), so partial state can differ from
    a serial run that stops at the failing item — the ``make -j`` trade.

    Items are dispatched as one contiguous chunk per worker (scheduling
    59 one-file writes as 59 futures costs more than the writes).  Tasks
    must not call ``parallel_map`` themselves: the pool is shared, so
    nested waits could starve it.
    """
    items = list(items)
    jobs = n_jobs()
    if jobs <= 1 or len(items) <= 1:
        return [fn(item) for item in items]
    jobs = min(jobs, len(items))
    step = (len(items) + jobs - 1) // jobs
    chunks = [items[i : i + step] for i in range(0, len(items), step)]

    # distributed tracing: a caller handling a traced request fans its
    # work onto pool threads — each chunk adopts the caller's trace
    # context so its spans stay tagged (and parented) inside the
    # request's segment.  No active context (the overwhelmingly common
    # case) costs one attribute read per map
    from . import spans as _spans

    def run_chunk(chunk):
        return [fn(item) for item in chunk]

    run_chunk = _spans.context_bound(run_chunk)

    out = []
    for chunk_result in _executor(jobs).map(run_chunk, chunks):
        out.extend(chunk_result)
    return out
