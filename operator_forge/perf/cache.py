"""Content-addressed pipeline cache.

Each cached stage is keyed on a SHA-256 over its complete inputs —
manifest/workload-config bytes, CLI flags, and the generator version —
so a hit can only replay work whose output is byte-identical to a fresh
computation.  Two granularities:

- **stage memoization** (:func:`memoized`): per-manifest marker
  inspection, per-manifest child-resource codegen, and per-child
  resource-marker scans are memoized in-process;
- **pipeline plans** (:func:`plan_get` / :func:`plan_put`): the fully
  rendered file plan (FileSpecs + Fragments) of an ``init`` /
  ``create api`` run, validated against a dependency snapshot (input
  file hashes, glob results, and the pre-existing CRD state the renderer
  merges against) so a warm re-run over unchanged fixtures skips the
  whole compile pipeline and goes straight to byte-identical writes.

Modes (``OPERATOR_FORGE_CACHE``):

- ``off``  — every lookup misses; nothing is stored.
- ``mem``  — in-process memoization only (the default; a fresh process
  always starts cold, so single-shot CLI behavior is unchanged).
- ``disk`` — ``mem`` plus persistence under ``.operator-forge-cache/``
  (override the location with ``OPERATOR_FORGE_CACHE_DIR``) so warm
  state survives across processes.

Values are stored pickled: a hit always deserializes a fresh copy, so
callers may freely mutate returned objects without corrupting the cache
(several pipeline objects — field markers, child resources — are mutated
after the cacheable stage computes them).
"""

from __future__ import annotations

import collections
import enum
import hashlib
import hmac
import os
import pickle
import tempfile
import threading
from dataclasses import dataclass, field as dc_field

from .. import __version__
from . import faults

# bump to invalidate every previously persisted entry when the record
# layout (not the generator output) changes
_SCHEMA = 1

_MODES = ("off", "mem", "disk")
DEFAULT_MODE = "mem"
DEFAULT_DIR = ".operator-forge-cache"
#: damaged persisted entries are moved here (never deleted in place and
#: never re-read): ``<root>/quarantine/<stage>-<key>.pkl``
QUARANTINE_DIRNAME = "quarantine"
#: disk-store size ceiling (``OPERATOR_FORGE_CACHE_MAX_MB`` overrides;
#: values <= 0 disable pruning)
DEFAULT_MAX_MB = 256


class _Miss:
    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return "MISS"


#: sentinel distinguishing "not cached" from a cached ``None``
MISS = _Miss()


def _hash_update(h, obj) -> None:
    """Canonical tagged hashing for plain key parts (no pickle: pickle
    bytes vary with object identity/memoization, hashes must not)."""
    if obj is None:
        h.update(b"N;")
    elif isinstance(obj, bool):
        h.update(b"B1;" if obj else b"B0;")
    elif isinstance(obj, int):
        h.update(b"I%d;" % obj)
    elif isinstance(obj, float):
        h.update(b"F" + repr(obj).encode("ascii") + b";")
    elif isinstance(obj, str):
        data = obj.encode("utf-8")
        h.update(b"S%d:" % len(data))
        h.update(data)
    elif isinstance(obj, bytes):
        h.update(b"Y%d:" % len(obj))
        h.update(obj)
    elif isinstance(obj, enum.Enum):
        _hash_update(h, obj.value)
    elif isinstance(obj, (tuple, list)):
        h.update(b"T(")
        for item in obj:
            _hash_update(h, item)
        h.update(b")")
    elif isinstance(obj, dict):
        h.update(b"D(")
        for key in sorted(obj):
            _hash_update(h, key)
            _hash_update(h, obj[key])
        h.update(b")")
    else:
        raise TypeError(
            f"cache key parts must be plain data, got {type(obj).__name__}"
        )


def hash_parts(*parts) -> str:
    """SHA-256 hex digest over canonically encoded key parts."""
    h = hashlib.sha256()
    _hash_update(h, parts)
    return h.hexdigest()


def file_sha(path: str):
    """SHA-256 of a file's bytes, or ``None`` when unreadable/missing
    (missing is a valid, cacheable dependency state)."""
    try:
        with open(path, "rb") as handle:
            return hashlib.sha256(handle.read()).hexdigest()
    except OSError:
        return None


def dir_state(output_dir: str, reldir: str) -> tuple:
    """Sorted ``(relpath, sha)`` listing of the plain files directly under
    ``output_dir/reldir`` — the renderer's view of previously scaffolded
    CRD bases.  A missing directory is the empty listing."""
    base = os.path.join(output_dir, reldir)
    out = []
    try:
        names = sorted(os.listdir(base))
    except OSError:
        return ()
    for name in names:
        path = os.path.join(base, name)
        if os.path.isfile(path):
            out.append((name, file_sha(path)))
    return tuple(out)


# -- disk-blob authentication -------------------------------------------
#
# Disk entries are pickles, and the default cache dir is cwd-relative —
# a cloned repository could ship a crafted ``.operator-forge-cache/``
# whose pickle executes code on load.  Every persisted blob is therefore
# HMAC-signed with a per-user key stored OUTSIDE any shippable tree
# (``~/.cache/operator-forge/cache.key``); a blob that does not verify
# is treated as a miss and never unpickled.

_KEY_BYTES = 32
_SIG_BYTES = hashlib.sha256().digest_size
_hmac_key = None
_hmac_lock = threading.Lock()


def _key_path() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return os.path.join(base, "operator-forge", "cache.key")


def _load_hmac_key():
    """The per-user signing key, created on first use.  ``None`` (no
    writable home) disables disk persistence entirely."""
    global _hmac_key
    with _hmac_lock:
        if _hmac_key is not None:
            return _hmac_key or None  # b"" caches the unavailable state
        path = _key_path()
        try:
            with open(path, "rb") as handle:
                key = handle.read()
            if len(key) == _KEY_BYTES:
                _hmac_key = key
                return key
        except OSError:
            pass
        key = os.urandom(_KEY_BYTES)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
            with os.fdopen(fd, "wb") as handle:
                handle.write(key)
        except FileExistsError:
            try:  # lost a creation race: use the winner's key
                with open(path, "rb") as handle:
                    key = handle.read()
            except OSError:
                _hmac_key = b""
                return None
            if len(key) != _KEY_BYTES:
                _hmac_key = b""
                return None
        except OSError:
            _hmac_key = b""
            return None
        _hmac_key = key
        return key


def _sign(key: bytes, blob: bytes) -> bytes:
    return hmac.new(key, blob, hashlib.sha256).digest()


def _damage_entry(path: str, kind: str) -> None:
    """Chaos-harness damage applied to a just-persisted entry —
    deterministic stand-ins for bit rot (``cache.corrupt``), a torn
    write (``cache.torn``), and a zeroed inode (``cache.zero``).  Every
    variant fails verification on the next read and lands in
    quarantine; none is ever unpickled."""
    try:
        size = os.path.getsize(path)
        if kind == "cache.zero":
            with open(path, "wb"):
                pass
        elif kind == "cache.torn":
            with open(path, "r+b") as handle:
                handle.truncate(max(size // 2, 1))
        else:  # cache.corrupt: flip the last payload byte
            with open(path, "r+b") as handle:
                handle.seek(size - 1)
                last = handle.read(1)
                handle.seek(size - 1)
                handle.write(bytes([last[0] ^ 0xFF]))
    except OSError:
        pass


class ContentCache:
    """Thread-safe content-addressed store with hit/miss accounting."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # insertion/recency-ordered: get() marks entries used so the
        # mem tier evicts least-recently-USED when over budget (a
        # long-lived daemon would otherwise grow without bound)
        self._mem: collections.OrderedDict = collections.OrderedDict()
        self._mem_bytes = 0
        self._stats: dict = {}
        self._mode_override = None
        self._root_override = None
        # bytes persisted since the last size check: gc on write is
        # amortized so a hot loop never walks the store per put
        self._written_since_gc = 0
        # one disk sweep at a time: two writers crossing the amortized
        # threshold together must not both walk-and-evict the store
        self._gc_inflight = False
        # callbacks run by reset(): sibling in-process caches (the
        # gocheck scan/index identity layers) register here so one
        # reset() call returns the whole process to a cold state
        self.reset_hooks: list = []

    # -- configuration --------------------------------------------------

    def mode(self) -> str:
        if self._mode_override is not None:
            return self._mode_override
        raw = os.environ.get("OPERATOR_FORGE_CACHE", DEFAULT_MODE)
        raw = raw.strip().lower()
        return raw if raw in _MODES else DEFAULT_MODE

    def root(self) -> str:
        if self._root_override is not None:
            return self._root_override
        return os.environ.get("OPERATOR_FORGE_CACHE_DIR", DEFAULT_DIR)

    def configure(self, mode=None, root=None) -> None:
        """Override (or with ``None`` restore) the env-driven mode/root."""
        if mode is not None and mode not in _MODES:
            raise ValueError(f"unknown cache mode {mode!r}; known: {_MODES}")
        self._mode_override = mode
        self._root_override = root

    def reset(self) -> None:
        """Drop all in-memory entries and statistics (persisted disk
        entries survive — they are re-validated content hashes)."""
        with self._lock:
            self._mem.clear()
            self._mem_bytes = 0
            self._stats.clear()
        for hook in list(self.reset_hooks):
            hook()

    def stats(self) -> dict:
        with self._lock:
            return {stage: dict(count) for stage, count in self._stats.items()}

    def _count(self, stage: str, what: str) -> None:
        with self._lock:
            entry = self._stats.setdefault(stage, {"hits": 0, "misses": 0})
            entry[what] = entry.get(what, 0) + 1

    # -- store ----------------------------------------------------------

    def _disk_path(self, stage: str, key: str) -> str:
        return os.path.join(self.root(), stage, key[:2], key + ".pkl")

    # -- mem-tier budget -------------------------------------------------
    #
    # The mem tier shares the OPERATOR_FORGE_CACHE_MAX_MB ceiling with
    # the disk store.  Accounting is byte-exact (blob lengths) and all
    # mutation happens under self._lock, so concurrent daemon sessions
    # can put/evict without racing the totals.

    def _mem_store_locked(self, mem_key: tuple, blob: bytes) -> None:
        old = self._mem.pop(mem_key, None)
        if old is not None:
            self._mem_bytes -= len(old)
        self._mem[mem_key] = blob
        self._mem_bytes += len(blob)

    def _mem_drop_locked(self, mem_key: tuple) -> None:
        old = self._mem.pop(mem_key, None)
        if old is not None:
            self._mem_bytes -= len(old)

    def _evict_mem_locked(self, limit: int) -> int:
        evicted = 0
        while self._mem_bytes > limit and self._mem:
            _key, blob = self._mem.popitem(last=False)
            self._mem_bytes -= len(blob)
            evicted += 1
        return evicted

    def _mem_insert(self, mem_key: tuple, blob: bytes) -> None:
        """Store a mem-tier blob and enforce the budget (LRU)."""
        limit = self.max_bytes()
        with self._lock:
            self._mem_store_locked(mem_key, blob)
            evicted = (
                self._evict_mem_locked(limit) if limit > 0 else 0
            )
        if evicted:
            from . import metrics

            metrics.counter("cache.mem_evictions").inc(evicted)

    def mem_footprint(self) -> tuple:
        """(entries, bytes) currently resident in the mem tier."""
        with self._lock:
            return len(self._mem), self._mem_bytes

    # -- quarantine -----------------------------------------------------

    def _quarantine_file(self, path: str, stage: str) -> bool:
        """Move a damaged persisted entry into ``quarantine/``.  The
        one unacceptable outcome is leaving a bad file in place to be
        re-read (and re-fail) forever, so if the move itself fails the
        entry is removed instead.  Returns whether the file is gone
        from the live store — ``False`` means it could be neither
        moved nor removed, so callers must not report it healed."""
        from . import metrics

        dest_dir = os.path.join(self.root(), QUARANTINE_DIRNAME)
        try:
            os.makedirs(dest_dir, exist_ok=True)
            os.replace(
                path,
                os.path.join(dest_dir, f"{stage}-{os.path.basename(path)}"),
            )
        except OSError:
            try:
                os.remove(path)
            except OSError:
                return False  # unmovable AND unremovable: still in place
        metrics.counter("cache.quarantined").inc()
        self._count(stage, "quarantined")
        from . import flight

        # a quarantined entry means on-disk damage happened under this
        # process: capture the ring around the detection
        flight.anomaly(
            "cache.quarantine",
            {"stage": stage, "entry": os.path.basename(path)},
        )
        return True

    def _corrupt_entry(self, stage: str, key: str) -> None:
        """Account a detected-corrupt entry (counter + namespace) and
        quarantine whatever is persisted under it."""
        from . import metrics

        metrics.counter("cache.corrupt_entries").inc()
        self._count(stage, "corrupt")
        path = self._disk_path(stage, key)
        if os.path.exists(path):
            self._quarantine_file(path, stage)

    def get(self, stage: str, key: str, record_stats: bool = True):
        """Fetch a value; returns :data:`MISS` when absent.  Hits always
        return a freshly deserialized copy.

        Three read-through tiers: mem, then (disk mode) the local disk
        store, then — with ``OPERATOR_FORGE_REMOTE_CACHE`` configured —
        the remote tier.  A remote hit is HMAC-verified with the local
        key before it is ever unpickled, then populates the local
        tiers, so later lookups stay local."""
        mode = self.mode()
        if mode == "off":
            return MISS
        with self._lock:
            blob = self._mem.get((stage, key))
            if blob is not None:
                # LRU freshness: a hit is a use, so eviction under the
                # mem budget stays least-recently-USED
                self._mem.move_to_end((stage, key))
        if blob is None and mode == "disk":
            blob = self._disk_read(stage, key)
            if blob is not None:
                self._mem_insert((stage, key), blob)
        if blob is None:
            blob = self._remote_read(stage, key)
            if blob is not None:
                self._mem_insert((stage, key), blob)
        if blob is None:
            if record_stats:
                self._count(stage, "misses")
            return MISS
        try:
            value = pickle.loads(blob)
        except Exception:
            # a corrupt entry is a miss — but never a *silent* one: it
            # is counted, attributed to its namespace, dropped from the
            # mem store, and its disk file quarantined so the same bad
            # bytes can never be re-read
            with self._lock:
                self._mem_drop_locked((stage, key))
            self._corrupt_entry(stage, key)
            if record_stats:
                self._count(stage, "misses")
            return MISS
        if record_stats:
            self._count(stage, "hits")
        return value

    def put(self, stage: str, key: str, value):
        """Store a value (pickled immediately, so later caller mutations
        of ``value`` cannot leak into the cache).  Returns ``value``."""
        mode = self.mode()
        if mode == "off":
            return value
        try:
            blob = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:
            return value  # unpicklable values simply aren't cached
        self._mem_insert((stage, key), blob)
        if mode == "disk":
            self._disk_write(stage, key, blob)
        self._remote_write(stage, key, blob)
        return value

    def _remote_read(self, stage: str, key: str):
        """The third read-through tier: a verified pickle blob from the
        remote cache, or ``None``.  On a hit the disk tier is populated
        too (re-signed with the local key), so the entry survives this
        process.  Never raises — remote failures degrade inside
        :mod:`operator_forge.perf.remote`."""
        from . import remote

        if not remote.active():
            return None
        blob = remote.fetch(stage, key)
        if blob is None:
            return None
        self._count(stage, "remote_hits")
        if self.mode() == "disk":
            self._disk_write(stage, key, blob)
        return blob

    def _remote_write(self, stage: str, key: str, blob: bytes) -> None:
        """Write-behind to the remote tier: enqueue and return — the
        upload happens off the hot path (bounded queue, batched,
        flushed at exit; backlog drops with a counter)."""
        from . import remote

        if not remote.active():
            return
        remote.enqueue_put(stage, key, blob)

    def _disk_read(self, stage: str, key: str):
        """Read and authenticate a persisted blob; anything unsigned,
        tampered, or unverifiable is a miss (never unpickled)."""
        signing_key = _load_hmac_key()
        if signing_key is None:
            return None
        try:
            with open(self._disk_path(stage, key), "rb") as handle:
                data = handle.read()
        except OSError:
            return None
        if len(data) <= _SIG_BYTES:
            # a zero-byte or truncated-below-signature file: a torn
            # write, not an absent entry — quarantine it
            self._corrupt_entry(stage, key)
            return None
        signature, blob = data[:_SIG_BYTES], data[_SIG_BYTES:]
        if not hmac.compare_digest(signature, _sign(signing_key, blob)):
            # tampered, bit-rotted, or torn mid-blob: never unpickled,
            # and never left in place to fail verification again
            self._corrupt_entry(stage, key)
            return None
        try:
            # mark the entry used: relatime/noatime mounts barely move
            # atime, so without this the LRU eviction would degrade to
            # FIFO-by-write and evict the hottest entries first (Go's
            # build cache touches entries on Get for the same reason)
            os.utime(self._disk_path(stage, key))
        except OSError:
            pass
        return blob

    def _disk_write(self, stage: str, key: str, blob: bytes) -> None:
        signing_key = _load_hmac_key()
        if signing_key is None:
            return  # no key, no persistence; the mem entry stands
        path = self._disk_path(stage, key)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
            with os.fdopen(fd, "wb") as handle:
                handle.write(_sign(signing_key, blob) + blob)
            os.replace(tmp, path)
        except OSError:
            return  # persistence is best-effort
        for kind in faults.fire(
            "disk", "cache.corrupt", "cache.torn", "cache.zero"
        ):
            # every kind fire() logged and counted must materialize:
            # two kinds landing on the same hit apply in spec order
            # (each damages whatever bytes the previous one left), or
            # fired()/faults.injected would overstate the injection
            _damage_entry(path, kind)
        self._maybe_gc(len(blob) + _SIG_BYTES)

    # -- eviction --------------------------------------------------------

    def max_bytes(self) -> int:
        """The disk-store ceiling in bytes (<= 0 disables pruning)."""
        from . import env_number

        mb = env_number(
            "OPERATOR_FORGE_CACHE_MAX_MB", float(DEFAULT_MAX_MB), minimum=None
        )
        return int(mb * 1024 * 1024)

    def _maybe_gc(self, written: int) -> None:
        """Amortized on-write pruning: walk the store only after enough
        new bytes accumulated to plausibly move the total.  Concurrent
        writers (daemon sessions) crossing the threshold together elect
        ONE sweeper — the rest return immediately, their bytes already
        folded into the shared accumulator."""
        limit = self.max_bytes()
        if limit <= 0:
            return
        with self._lock:
            self._written_since_gc += written
            if self._written_since_gc < max(limit // 32, 1024 * 1024):
                return
            if self._gc_inflight:
                return  # another writer is already sweeping
            self._gc_inflight = True
            self._written_since_gc = 0
        try:
            self.gc()
        except OSError:
            pass
        finally:
            with self._lock:
                self._gc_inflight = False

    def gc(self, max_bytes=None) -> dict:
        """Prune the disk store to ``max_bytes`` (default: the
        ``OPERATOR_FORGE_CACHE_MAX_MB`` ceiling), removing least-
        recently-used entries first (by atime, ties by mtime).  Only
        ``.pkl`` blobs are touched; removal is whole-file, so an entry
        is either absent (a miss) or intact-and-signed — pruning can
        never produce a blob that fails HMAC verification, and a reader
        holding an open handle keeps its data (POSIX unlink semantics).
        Returns a summary dict (stable key order; ``entries_removed`` /
        ``bytes_reclaimed`` / ``bytes_remaining`` are the CLI's JSON
        contract, the rest detail).  Evictions are counted in the
        metrics registry (``cache.evictions`` /
        ``cache.bytes_reclaimed``) whether the sweep came from the
        amortized on-write trigger or ``cache gc``."""
        limit = self.max_bytes() if max_bytes is None else int(max_bytes)
        root = self.root()
        entries = []  # (atime_ns, mtime_ns, size, path)
        total = 0
        for dirpath, dirnames, filenames in os.walk(root):
            # quarantined entries are out of the live store: not counted
            # against the ceiling, and never "evicted" back to life
            dirnames[:] = [
                d for d in dirnames if d != QUARANTINE_DIRNAME
            ]
            for name in filenames:
                if not name.endswith(".pkl"):
                    continue
                path = os.path.join(dirpath, name)
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                entries.append(
                    (st.st_atime_ns, st.st_mtime_ns, st.st_size, path)
                )
                total += st.st_size
        removed = 0
        freed = 0
        if limit > 0 and total > limit:
            for _atime, _mtime, size, path in sorted(entries):
                try:
                    os.remove(path)
                except OSError:
                    continue
                removed += 1
                freed += size
                if total - freed <= limit:
                    break
        if removed:
            from . import metrics

            metrics.counter("cache.evictions").inc(removed)
            metrics.counter("cache.bytes_reclaimed").inc(freed)
        quarantine = self.quarantine_stats()
        # flight-recorder capsules share the cache dir's budget: every
        # gc reports their footprint and sweeps the expired ones (past
        # their TTL, or beyond the keep budget), so the recorder can
        # never grow unbounded even after its owning server died
        from . import flight

        # the recorder's own override resolution applies (env or
        # programmatic dir wins); this store's root is only the default
        capsules = flight.sweep(
            default_base=os.path.join(root, "flight")
        )
        return {
            "entries_removed": removed,
            "bytes_reclaimed": freed,
            "bytes_remaining": total - freed,
            # quarantined files are excluded from the live accounting
            # above, but they still occupy disk — report them so `gc`
            # consumers see the whole footprint, not just the store
            "quarantine_entries": quarantine["entries"],
            "quarantine_bytes": quarantine["bytes"],
            "flight_entries": capsules["entries"],
            "flight_bytes": capsules["bytes"],
            "flight_removed": capsules["removed"],
            "flight_bytes_reclaimed": capsules["bytes_reclaimed"],
            "entries": len(entries),
            "max_bytes": limit,
            "removed": removed,
            "bytes_before": total,
            "bytes_after": total - freed,
        }

    def enforce_budget(self) -> dict:
        """Bound BOTH resident tiers to the ``OPERATOR_FORGE_CACHE_MAX_MB``
        ceiling right now — the daemon's maintenance-tick hook.  The
        on-write triggers only fire while entries are being written; a
        long-lived daemon that mostly replays would otherwise never
        evict, so this applies the mem LRU eviction unconditionally and
        runs the disk LRU sweep (disk mode only) through the same
        single-sweeper election as the amortized path.  Returns
        ``{"mem_evicted": n, "disk": gc-summary-or-None}``."""
        out = {"mem_evicted": 0, "disk": None}
        limit = self.max_bytes()
        if limit <= 0:
            return out
        with self._lock:
            evicted = self._evict_mem_locked(limit)
        if evicted:
            from . import metrics

            metrics.counter("cache.mem_evictions").inc(evicted)
        out["mem_evicted"] = evicted
        if self.mode() == "disk":
            with self._lock:
                if self._gc_inflight:
                    return out  # a writer's sweep is already running
                self._gc_inflight = True
            try:
                out["disk"] = self.gc()
            except OSError:
                pass
            finally:
                with self._lock:
                    self._gc_inflight = False
        return out

    # -- quarantine accounting -------------------------------------------

    def quarantine_stats(self) -> dict:
        """Disk footprint of the quarantine directory: totals plus a
        per-namespace breakdown (file names are
        ``<stage>-<key>.pkl``, and stage names never contain ``-``
        followed by a hex key, so the split on the LAST dash is
        unambiguous).  The directory is flat, so this is one scandir."""
        base = os.path.join(self.root(), QUARANTINE_DIRNAME)
        entries = 0
        total = 0
        by_namespace: dict = {}
        try:
            names = sorted(os.listdir(base))
        except OSError:
            names = []
        for name in names:
            path = os.path.join(base, name)
            try:
                size = os.stat(path).st_size
            except OSError:
                continue
            entries += 1
            total += size
            stem = name[:-4] if name.endswith(".pkl") else name
            stage = stem.rpartition("-")[0] or stem
            record = by_namespace.setdefault(
                stage, {"entries": 0, "bytes": 0}
            )
            record["entries"] += 1
            record["bytes"] += size
        return {
            "entries": entries,
            "bytes": total,
            "by_namespace": {k: by_namespace[k] for k in sorted(by_namespace)},
        }

    def purge_quarantine(self) -> dict:
        """Delete every quarantined file (``cache gc
        --purge-quarantine``): quarantine exists so damaged bytes are
        preserved for inspection, not forever — this is the reclaim
        path.  Returns ``{"entries_removed", "bytes_reclaimed"}``."""
        base = os.path.join(self.root(), QUARANTINE_DIRNAME)
        removed = 0
        freed = 0
        try:
            names = sorted(os.listdir(base))
        except OSError:
            names = []
        for name in names:
            path = os.path.join(base, name)
            try:
                size = os.stat(path).st_size
                os.remove(path)
            except OSError:
                continue
            removed += 1
            freed += size
        return {"entries_removed": removed, "bytes_reclaimed": freed}

    # -- verification ----------------------------------------------------

    def verify(self, repair: bool = False) -> dict:
        """Scan the whole persisted store, authenticating and
        unpickling every entry — the no-toolchain analogue of GOCACHE
        verification.  An entry is *bad* when it is unreadable, shorter
        than a signature, fails HMAC, or (signed, therefore ours) fails
        to unpickle.  With ``repair`` bad entries move to
        ``quarantine/``; without it the scan only reports.  Returns a
        stable-key-order summary: ``scanned`` / ``ok`` / ``bad`` /
        ``quarantined`` / ``entries`` (sorted store-relative paths of
        the bad ones).  ``quarantined`` can lag ``bad`` when an entry
        could be neither moved nor removed (e.g. a read-only store
        dir) — such entries are still live, not healed."""
        from . import metrics

        signing_key = _load_hmac_key()
        root = self.root()
        scanned = ok = quarantined = 0
        bad_entries: list = []
        if signing_key is None:
            # no signing key means disk persistence is disabled: the
            # read path never touches these files, so nothing can be
            # authenticated and nothing is "damage" — scanning would
            # condemn (and with repair, quarantine) an entire store the
            # runtime already ignores
            return {
                "scanned": scanned,
                "ok": ok,
                "bad": 0,
                "quarantined": quarantined,
                "entries": [],
            }
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames if d != QUARANTINE_DIRNAME
            )
            for name in sorted(filenames):
                if not name.endswith(".pkl"):
                    continue
                path = os.path.join(dirpath, name)
                scanned += 1
                good = False
                try:
                    with open(path, "rb") as handle:
                        data = handle.read()
                    if len(data) > _SIG_BYTES:  # key non-None: early-out above
                        signature = data[:_SIG_BYTES]
                        blob = data[_SIG_BYTES:]
                        if hmac.compare_digest(
                            signature, _sign(signing_key, blob)
                        ):
                            pickle.loads(blob)  # signed by us: safe
                            good = True
                except Exception:
                    good = False
                if good:
                    ok += 1
                    continue
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                bad_entries.append(rel)
                if repair:
                    # counted only on a successful quarantine: a
                    # report-only scan is an idempotent observation
                    # (its JSON carries the bad count), and a failed
                    # move leaves the entry for the next scan to retry
                    # — counting either would show phantom repeat
                    # corruption in stats
                    stage = rel.split("/", 1)[0]
                    if self._quarantine_file(path, stage):
                        # same accounting pair the inline read path
                        # records (_corrupt_entry): the global counter
                        # AND the per-namespace attribution, so serve
                        # stats reconcile against cache.corrupt_entries
                        metrics.counter("cache.corrupt_entries").inc()
                        self._count(stage, "corrupt")
                        quarantined += 1
        return {
            "scanned": scanned,
            "ok": ok,
            "bad": len(bad_entries),
            "quarantined": quarantined,
            "entries": sorted(bad_entries),
        }


_CACHE = ContentCache()


def _new_locks_after_fork() -> None:
    # fork (the perf.workers process pool) can land while another
    # parent thread holds a cache lock; the child would inherit it
    # locked and deadlock on its first get/put
    global _hmac_lock
    _hmac_lock = threading.Lock()
    _CACHE._lock = threading.Lock()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_new_locks_after_fork)


def get_cache() -> ContentCache:
    return _CACHE


def configure(mode=None, root=None) -> None:
    _CACHE.configure(mode, root)


def reset() -> None:
    _CACHE.reset()


def stats() -> dict:
    return _CACHE.stats()


def gc(max_bytes=None) -> dict:
    return _CACHE.gc(max_bytes)


def verify(repair: bool = False) -> dict:
    return _CACHE.verify(repair)


def remote_active() -> bool:
    """Whether the remote tier participates in lookups right now (an
    address is configured, the client has not degraded, and a signing
    key exists) — callers that gate pickling-store round trips on
    ``mode == "disk"`` widen the gate with this."""
    from . import remote

    return remote.active()


def memoized(stage: str, key_parts: tuple, compute):
    """Memoize ``compute()`` under a content hash of ``key_parts``.

    On a miss the freshly computed object is returned directly (and a
    pristine pickled copy stored); on a hit an independent copy is
    deserialized — either way the caller owns the returned object.
    """
    cache = _CACHE
    if cache.mode() == "off":
        return compute()
    # __version__ is part of every key: a persisted (disk-mode) entry
    # must never replay an older generator's output
    key = hash_parts(_SCHEMA, __version__, *key_parts)
    hit = cache.get(stage, key)
    if hit is not MISS:
        return hit
    return cache.put(stage, key, compute())


# -- pipeline plans ------------------------------------------------------

_PLAN_STAGE = "plan"


@dataclass
class PlanRecord:
    """A cached file plan plus the dependency snapshot that must still
    hold for the plan to be replayed."""

    # (path, sha-or-None) for every input file the pipeline read
    dep_files: list = dc_field(default_factory=list)
    # (kind, pattern, resolved-paths) — new files matching a config's
    # component/manifest glob must invalidate even though no recorded
    # file changed
    dep_globs: list = dc_field(default_factory=list)
    # (reldir, acceptable dir_state listings) — output-tree state the
    # renderer merged against (existing CRD bases).  Acceptable states:
    # the one captured BEFORE the plan executed, and the plan's own
    # output (re-rendering over own output is a fixed point, so a re-run
    # over the just-scaffolded tree may replay the plan)
    out_state: list = dc_field(default_factory=list)
    plan: object = None


def _glob_results(kind: str, pattern: str) -> tuple:
    from ..utils.globber import glob_files, glob_manifest_files

    try:
        if kind == "manifests":
            return tuple(glob_manifest_files(pattern))
        return tuple(glob_files(pattern))
    except Exception:
        return ("<glob-error>",)


def plan_get(key_parts: tuple, output_dir: str):
    """Return the cached plan for ``key_parts`` if every recorded
    dependency (file hashes, glob results, output-dir CRD state) still
    matches; ``None`` otherwise."""
    cache = _CACHE
    if cache.mode() == "off":
        return None
    key = hash_parts(_SCHEMA, __version__, _PLAN_STAGE, *key_parts)
    record = cache.get(_PLAN_STAGE, key, record_stats=False)
    valid = record is not MISS and isinstance(record, PlanRecord)
    if valid:
        for path, sha in record.dep_files:
            if file_sha(path) != sha:
                valid = False
                break
    if valid:
        for kind, pattern, resolved in record.dep_globs:
            if _glob_results(kind, pattern) != tuple(resolved):
                valid = False
                break
    if valid:
        for reldir, listings in record.out_state:
            if dir_state(output_dir, reldir) not in [
                tuple(listing) for listing in listings
            ]:
                valid = False
                break
    cache._count(_PLAN_STAGE, "hits" if valid else "misses")
    return record.plan if valid else None


def plan_put(
    key_parts: tuple,
    plan,
    dep_files=(),
    dep_globs=(),
    out_state=(),
) -> None:
    """Store a plan with its dependency snapshot.  ``dep_files`` are
    hashed now; ``dep_globs`` are (kind, pattern) pairs resolved now;
    ``out_state`` is (reldir, acceptable-listings) pairs supplied by the
    caller (pre-execution state plus the plan's own output state)."""
    cache = _CACHE
    if cache.mode() == "off":
        return
    key = hash_parts(_SCHEMA, __version__, _PLAN_STAGE, *key_parts)
    record = PlanRecord(
        dep_files=[(path, file_sha(path)) for path in dep_files],
        dep_globs=[
            (kind, pattern, _glob_results(kind, pattern))
            for kind, pattern in dep_globs
        ],
        out_state=[
            (reldir, tuple(tuple(listing) for listing in listings))
            for reldir, listings in out_state
        ],
        plan=plan,
    )
    cache.put(_PLAN_STAGE, key, record)
