"""Shared stream-socket address plumbing for every network surface.

The daemon, the fleet coordinator, and the remote cache server all
speak over the same two transports — a unix socket (``unix:/path`` or
any bare path) or TCP (``host:port`` / ``:port``) — and each grew its
own copy of the parse/bind/connect boilerplate.  This module is the
single shared implementation: one parser, one listener factory (stale
unix-path unlink, ``SO_REUSEADDR`` for TCP, optional accept deadline),
one client-side connector (deadline on both the connect and subsequent
reads), and one bound-address formatter (resolving a TCP port-0 bind
to the real port).  ``operator_forge.perf.remote.parse_listen`` stays
as a re-export for the PR 9 import surface.
"""

from __future__ import annotations

import os
import socket


def parse_listen(addr: str):
    """Parse a listen/connect address: ``unix:/path`` (or any string
    containing a path separator) selects a unix socket, ``host:port``
    (or ``:port``) TCP."""
    addr = addr.strip()
    if not addr:
        raise ValueError("empty remote cache address")
    if addr.startswith("unix:"):
        return ("unix", addr[len("unix:"):])
    if os.sep in addr or "/" in addr:
        return ("unix", addr)
    host, sep, port = addr.rpartition(":")
    if not sep:
        raise ValueError(
            f"remote cache address {addr!r} must be unix:/path, a "
            "socket path, or host:port"
        )
    try:
        port_n = int(port)
    except ValueError:
        raise ValueError(
            f"remote cache address {addr!r}: port must be an integer"
        ) from None
    return ("tcp", host or "127.0.0.1", port_n)


def bind_listener(addr, backlog: int = 64, accept_timeout=None):
    """Bind and return a listening socket for ``addr`` (a string in
    :func:`parse_listen` syntax, or an already-parsed spec tuple).  A
    unix bind unlinks a stale socket path first; a TCP bind sets
    ``SO_REUSEADDR``.  ``accept_timeout`` (seconds) makes ``accept``
    poll instead of block forever — how the daemon and coordinator
    notice a shutdown flag."""
    spec = parse_listen(addr) if isinstance(addr, str) else addr
    if spec[0] == "unix":
        try:
            os.unlink(spec[1])
        except OSError:
            pass
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            sock.bind(spec[1])
            sock.listen(backlog)
        except BaseException:
            sock.close()
            raise
    else:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((spec[1], spec[2]))
            sock.listen(backlog)
        except BaseException:
            sock.close()
            raise
    if accept_timeout is not None:
        sock.settimeout(accept_timeout)
    return sock


def bound_address(spec, listener) -> str:
    """The actual bound address for a listener made from ``spec`` —
    resolves a TCP port-0 bind to the kernel-assigned port."""
    if spec[0] == "unix":
        return spec[1]
    host, port = listener.getsockname()[:2]
    return f"{host}:{port}"


def connect_stream(addr, timeout=None):
    """Connect to ``addr`` (:func:`parse_listen` syntax or a parsed
    spec) and return the socket, with ``timeout`` applied to both the
    connect and subsequent reads.  Raises the usual ``OSError`` family
    on failure; the partially-opened socket is always closed."""
    spec = parse_listen(addr) if isinstance(addr, str) else addr
    if spec[0] == "unix":
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            if timeout is not None:
                sock.settimeout(timeout)
            sock.connect(spec[1])
        except BaseException:
            sock.close()
            raise
        return sock
    sock = socket.create_connection((spec[1], spec[2]), timeout=timeout)
    sock.settimeout(timeout)
    return sock
