"""In-memory buffer overlays — the editor's unsaved bytes (PR 17).

An *overlay* maps an absolute file path to the content an editor holds
in a dirty buffer.  While registered, the whole checking path behaves
exactly as if the file had those bytes on disk: the content-addressed
cache keys (:func:`operator_forge.gocheck.cache.file_sha_stat`,
``tree_state``, ``go_file_state``), the dependency-graph source nodes,
and every gocheck read site resolve through the overlay first — so a
vet of unsaved content is byte-identical to a save-then-vet of the same
bytes, and the replay/identity contract survives without the overlay
ever touching the filesystem.

Design constraints:

- **zero cost when unused** — the hot paths (``file_sha_stat`` on a
  10k-file tree, every source read) probe :func:`get`/:func:`sha`,
  which bail on a plain truthiness check of the store before taking
  any lock;
- **session-scoped** — the daemon registers overlays under the owning
  session's id and clears them when the session closes, so one
  editor's unsaved buffers can never leak into another client's view
  of the tree after it disconnects;
- **push wakeups** — every mutation bumps a generation counter and
  notifies a condition; the ``subscribe`` op's poll waits on it, so an
  overlay edit wakes the push-diagnostics loop immediately instead of
  waiting out the watch interval;
- **worker shipping** — :func:`snapshot_for_shipping` / :func:`adopt`
  move the store into process-pool workers per task (the
  ``perf.workers`` config channel), so the thread/process identity
  matrix holds with overlays active.

Overlays target *existing paths* (registering one for a path that does
not exist on disk is a ``bad_request`` at the protocol layer); a file
that vanishes after registration still contributes its overlay bytes to
``tree_state``/``go_file_state`` so the content keys stay coherent.
"""

from __future__ import annotations

import hashlib
import os
import threading

_cond = threading.Condition()
#: abspath -> (text, sha, version, owner)
_overlays: dict = {}
#: basenames of every overlaid path — the pre-normalization probe: a
#: query whose final component is a plain name that matches no overlay
#: basename cannot be overlaid under any spelling, so the hot lookup
#: (``file_sha_stat`` on every walked file) skips ``os.path.abspath``
_names: set = set()
_gen = [0]
_next_version = [0]


def _norm(path: str) -> str:
    return os.path.abspath(path)


def _refresh_names_locked() -> None:
    _names.clear()
    _names.update(p.rsplit(os.sep, 1)[-1] for p in _overlays)


def _maybe(path: str) -> bool:
    """Whether *path* could name an overlaid file without normalizing
    it.  Only a plain final component proves a negative — ``""``,
    ``"."`` and ``".."`` tails change under abspath, so they fall
    through to the normalized lookup."""
    tail = path.rsplit(os.sep, 1)[-1]
    return tail in _names or tail in ("", ".", "..")


def _bump_locked() -> None:
    _gen[0] += 1
    _cond.notify_all()


def _invalidate(path: str) -> int:
    """Sweep the dependency graph for an overlay mutation: the file's
    source node (keyed the way the per-file analysis nodes record
    their edges — the absolute path the driver's walk produced)."""
    from .depgraph import GRAPH

    return GRAPH.invalidate([("src", path)])


def set_overlay(path: str, text: str, owner=None) -> dict:
    """Register (or replace) the overlay for *path*; returns
    ``{"version", "sha", "dirtied", "overlays"}``.  Invalidation runs
    outside the store lock (the graph has its own)."""
    path = _norm(path)
    sha = hashlib.sha256(text.encode("utf-8")).hexdigest()
    with _cond:
        _next_version[0] += 1
        version = _next_version[0]
        _overlays[path] = (text, sha, version, owner)
        _names.add(path.rsplit(os.sep, 1)[-1])
        count = len(_overlays)
        _bump_locked()
    dirtied = _invalidate(path)
    return {
        "version": version, "sha": sha,
        "dirtied": dirtied, "overlays": count,
    }


def clear_overlay(path: str) -> bool:
    """Drop the overlay for *path* (the gopls didClose analogue); the
    next read sees the disk bytes again.  Returns whether one was
    registered."""
    path = _norm(path)
    with _cond:
        existed = _overlays.pop(path, None) is not None
        if existed:
            _refresh_names_locked()
            _bump_locked()
    if existed:
        _invalidate(path)
    return existed


def clear_owner(owner) -> list:
    """Drop every overlay registered under *owner* (a daemon session
    closing) and invalidate each path; returns the cleared paths."""
    with _cond:
        cleared = [
            path for path, entry in _overlays.items()
            if entry[3] == owner
        ]
        for path in cleared:
            del _overlays[path]
        if cleared:
            _refresh_names_locked()
            _bump_locked()
    for path in cleared:
        _invalidate(path)
    return cleared


def get(path: str):
    """Overlay text for *path*, or ``None``.  The no-overlay fast path
    is one truthiness check — no lock, no normalization — and with
    overlays registered, a basename probe rules out the common miss
    before paying ``os.path.abspath``."""
    if not _overlays:
        return None
    entry = _overlays.get(path)
    if entry is None:
        if not _maybe(path):
            return None
        entry = _overlays.get(_norm(path))
    return None if entry is None else entry[0]


def sha(path: str):
    """Overlay content sha for *path*, or ``None`` (same fast path as
    :func:`get`)."""
    if not _overlays:
        return None
    entry = _overlays.get(path)
    if entry is None:
        if not _maybe(path):
            return None
        entry = _overlays.get(_norm(path))
    return None if entry is None else entry[1]


def count() -> int:
    return len(_overlays)


def owned(owner) -> int:
    """How many overlays *owner* holds — the daemon's interactive-
    session test (a session with live overlays is an editor, and its
    vets get dispatch priority)."""
    if not _overlays:
        return 0
    with _cond:
        return sum(1 for e in _overlays.values() if e[3] == owner)


def paths_under(root: str) -> list:
    """Sorted ``(abspath, sha)`` of overlays inside *root* — merged
    into ``tree_state``/``go_file_state`` so an overlaid file that
    vanished from disk still contributes its bytes to content keys."""
    if not _overlays:
        return []
    root = _norm(root)
    prefix = root + os.sep
    with _cond:
        return sorted(
            (path, entry[1]) for path, entry in _overlays.items()
            if path == root or path.startswith(prefix)
        )


def signatures_under(root: str) -> dict:
    """``{relpath: ("overlay", version)}`` for overlays inside *root*
    — merged into the watch/subscribe snapshot so an overlay edit (or
    clear) reads as a tree change and triggers the minimal re-run."""
    if not _overlays:
        return {}
    root = _norm(root)
    prefix = root + os.sep
    out: dict = {}
    with _cond:
        for path, entry in _overlays.items():
            if path == root or path.startswith(prefix):
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                out[rel] = ("overlay", entry[2])
    return out


def generation() -> int:
    """Monotonic mutation counter — the subscribe wakeup's edge."""
    return _gen[0]


def wait_change(seen: int, timeout: float) -> int:
    """Block until the generation moves past *seen* (an overlay was
    set or cleared) or *timeout* elapses; returns the current
    generation either way."""
    with _cond:
        if _gen[0] == seen:
            _cond.wait(timeout)
        return _gen[0]


def read_text(path: str, encoding: str = "utf-8", errors=None) -> str:
    """Overlay-aware file read: the overlay's text when one is
    registered, the disk bytes otherwise (raising exactly like
    ``open`` on a missing/unreadable file)."""
    text = get(path)
    if text is not None:
        return text
    with open(path, encoding=encoding, errors=errors) as fh:
        return fh.read()


def read_bytes(path: str) -> bytes:
    """Overlay-aware binary read (the interpreted ``os.ReadFile``)."""
    text = get(path)
    if text is not None:
        return text.encode("utf-8")
    with open(path, "rb") as fh:
        return fh.read()


def snapshot_for_shipping():
    """``{path: text}`` for the workers config channel, or ``None``
    when the store is empty (so an overlay-free task ships nothing and
    the worker pays nothing)."""
    if not _overlays:
        return None
    with _cond:
        return {path: entry[0] for path, entry in _overlays.items()}


def adopt(mapping) -> None:
    """Replace the store wholesale (process-pool worker side of
    :func:`snapshot_for_shipping`); owners are not shipped — a worker's
    overlays live exactly one task."""
    with _cond:
        changed = (
            {p: e[0] for p, e in _overlays.items()} != dict(mapping or {})
        )
        if not changed:
            return
        _overlays.clear()
        for path, text in (mapping or {}).items():
            _next_version[0] += 1
            sha_ = hashlib.sha256(text.encode("utf-8")).hexdigest()
            _overlays[_norm(path)] = (
                text, sha_, _next_version[0], None,
            )
        _refresh_names_locked()
        _bump_locked()


def clear_all() -> list:
    """Drop every overlay (tests, teardown); returns cleared paths."""
    with _cond:
        cleared = list(_overlays)
        _overlays.clear()
        _names.clear()
        if cleared:
            _bump_locked()
    for path in cleared:
        _invalidate(path)
    return cleared
