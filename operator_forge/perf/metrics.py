"""Process-global metrics registry: counters, gauges, histograms.

The observability counterpart of :mod:`operator_forge.perf.spans`
(which answers "where did the time go?"): this module answers "how much
work happened, and how fast was each unit?".  Three instrument kinds,
all thread-safe and cheap enough to stay always-on:

- :class:`Counter` — monotonically increasing integer (cache
  evictions, worker-pool task submissions/completions);
- :class:`Gauge` — a settable point-in-time value (worker-pool queue
  depth), or a *callback* gauge read lazily at snapshot time;
- :class:`Histogram` — fixed-bucket latency distribution with
  count/sum and interpolated p50/p99 (per-serve-job and
  per-watch-cycle seconds).

:func:`snapshot` renders the registry in stable key order (instrument
kind, then name, then fixed fields within), so serve ``stats`` diffs
and ``operator-forge stats --json`` output are deterministic for a
given sequence of observations.  :func:`report` additionally pulls the
sibling observability surfaces — per-namespace ContentCache hit/miss
attribution and the dependency graph's dirty/reused/recomputed
counters — into one stable-ordered document (the ``stats`` payload).

No module-level imports of the cache/graph layers: they import *this*
module (eviction accounting), so the pull direction stays lazy to keep
the import graph acyclic.
"""

from __future__ import annotations

import os
import threading

#: default latency buckets (seconds) — tuned for the serve/watch loop:
#: sub-ms replays up to multi-second cold batch runs
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_lock = threading.Lock()
_counters: dict = {}
_gauges: dict = {}
_callback_gauges: dict = {}
_histograms: dict = {}
#: named payload providers merged into the full observability report —
#: the daemon registers its session surface, the fleet coordinator its
#: member table — so `operator-forge stats` and the serve ``stats`` op
#: render one document without this module knowing either subsystem
_stats_sources: dict = {}


def _new_lock_after_fork() -> None:
    # fork (the perf.workers process pool) can land while another
    # parent thread holds the registry lock; the child would inherit
    # it locked and deadlock on its first instrument update.  All
    # instruments read the module global at call time, so reassigning
    # is sufficient.
    global _lock
    _lock = threading.Lock()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_new_lock_after_fork)


class Counter:
    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with _lock:
            self._value += n

    def value(self) -> int:
        with _lock:
            return self._value


class Gauge:
    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0

    def set(self, value) -> None:
        with _lock:
            self._value = value

    def add(self, n=1) -> None:
        with _lock:
            self._value += n

    def value(self):
        with _lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram.  Buckets are cumulative-upper-bound
    counts (Prometheus-style ``le``); quantiles interpolate linearly
    inside the winning bucket, which is exact enough for p50/p99
    reporting and requires no per-observation allocation."""

    __slots__ = ("name", "buckets", "_counts", "_count", "_sum", "_max")

    def __init__(self, name: str, buckets=DEFAULT_BUCKETS):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +inf overflow
        self._count = 0
        self._sum = 0.0
        self._max = 0.0

    def observe(self, value: float) -> None:
        idx = len(self.buckets)
        for i, bound in enumerate(self.buckets):
            if value <= bound:
                idx = i
                break
        with _lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += value
            if value > self._max:
                self._max = value

    def _quantile_from(self, counts, count, peak, q: float):
        rank = q * count
        seen = 0.0
        for i, bucket_count in enumerate(counts):
            if bucket_count == 0:
                continue
            if seen + bucket_count >= rank:
                if i == len(self.buckets):
                    # overflow bucket: the tracked maximum is the
                    # honest upper estimate (never silently clamp to
                    # the top bound — a 45s job must not read as 10s)
                    return max(peak, self.buckets[-1])
                lower = self.buckets[i - 1] if i > 0 else 0.0
                upper = self.buckets[i]
                frac = (rank - seen) / bucket_count
                estimate = lower + (upper - lower) * min(max(frac, 0.0), 1.0)
                # interpolation reads the bucket's upper range, but no
                # quantile can exceed the largest observation
                return min(estimate, peak)
            seen += bucket_count
        return max(peak, self.buckets[-1])

    def quantile(self, q: float):
        """Interpolated quantile estimate; ``None`` when empty.
        Quantiles landing in the overflow bucket report the observed
        maximum (an upper bound) instead of clamping to the top
        bucket bound."""
        with _lock:
            count = self._count
            counts = list(self._counts)
            peak = self._max
        if count == 0:
            return None
        return self._quantile_from(counts, count, peak, q)

    def summary(self) -> dict:
        with _lock:
            count = self._count
            total = self._sum
            counts = list(self._counts)
            peak = self._max
        out = {
            "count": count,
            "sum": round(total, 6),
            "max": round(peak, 6),
            "p50": None,
            "p99": None,
        }
        if count:
            out["p50"] = round(
                self._quantile_from(counts, count, peak, 0.50), 6
            )
            out["p99"] = round(
                self._quantile_from(counts, count, peak, 0.99), 6
            )
        return out


def counter(name: str) -> Counter:
    with _lock:
        inst = _counters.get(name)
        if inst is None:
            inst = _counters[name] = Counter(name)
    return inst


def gauge(name: str) -> Gauge:
    with _lock:
        inst = _gauges.get(name)
        if inst is None:
            inst = _gauges[name] = Gauge(name)
    return inst


def register_gauge(name: str, fn) -> None:
    """A callback gauge: ``fn()`` is read at snapshot time — for
    values that already live elsewhere and would otherwise need
    continuous mirroring."""
    with _lock:
        _callback_gauges[name] = fn


def unregister_gauge(name: str) -> None:
    """Drop a callback gauge registration (the daemon registers a
    per-session queue-depth gauge per connection and must release it
    when the session closes, or a long-lived daemon's snapshot would
    grow one dead key per client ever served)."""
    with _lock:
        _callback_gauges.pop(name, None)


def register_stats_source(name: str, fn) -> None:
    """``fn()`` is called per stats render and its result becomes the
    report's ``name`` key (the daemon's per-session queue surface, the
    fleet coordinator's per-daemon lease/in-flight table).  Shared by
    the serve ``stats`` op and ``operator-forge stats``/`fleet-status`,
    so a registered surface appears on every stats transport at once."""
    with _lock:
        _stats_sources[name] = fn


def unregister_stats_source(name: str) -> None:
    with _lock:
        _stats_sources.pop(name, None)


def stats_sources() -> dict:
    """The registered source payloads, rendered now, in stable (sorted
    name) order; a source that raises is skipped — a stats render must
    never fail because one subsystem's snapshot did."""
    with _lock:
        sources = dict(_stats_sources)
    out = {}
    for name in sorted(sources):
        try:
            out[name] = sources[name]()
        except Exception:
            pass
    return out


def histogram(name: str, buckets=DEFAULT_BUCKETS) -> Histogram:
    with _lock:
        inst = _histograms.get(name)
        if inst is None:
            inst = _histograms[name] = Histogram(name, buckets)
    return inst


def counters_snapshot() -> dict:
    """``{name: value}`` for every counter — the cheap raw form the
    process-pool workers use to compute per-task deltas for shipping
    (gauges and histograms stay process-local)."""
    with _lock:
        return {name: c._value for name, c in _counters.items()}


def ingest_counters(deltas: dict) -> None:
    """Merge a worker's shipped counter deltas into this registry, so
    events that happened inside a pool child (a quarantined cache
    entry, a retried job) are visible in the parent's stats."""
    for name, value in deltas.items():
        if isinstance(value, int) and value > 0:
            counter(name).inc(value)


def reset() -> None:
    """Drop every instrument, callback-gauge registrations included
    (tests and bench legs re-register what they need; a leaked
    registration would keep its closure alive and make snapshots
    test-order dependent)."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _callback_gauges.clear()
        _histograms.clear()
        _stats_sources.clear()


def snapshot() -> dict:
    """The registry in stable key order:
    ``{"counters": {...}, "gauges": {...}, "histograms": {...}}`` with
    names sorted inside each kind and fixed fields per histogram."""
    with _lock:
        counter_items = {n: c._value for n, c in _counters.items()}
        gauge_items = {n: g._value for n, g in _gauges.items()}
        callbacks = dict(_callback_gauges)
        histogram_items = list(_histograms.items())
    for name, fn in callbacks.items():
        try:
            gauge_items[name] = fn()
        except Exception:
            gauge_items[name] = None
    return {
        "counters": {n: counter_items[n] for n in sorted(counter_items)},
        "gauges": {n: gauge_items[n] for n in sorted(gauge_items)},
        "histograms": {
            n: h.summary()
            for n, h in sorted(histogram_items, key=lambda kv: kv[0])
        },
    }


def cache_report() -> dict:
    """Per-namespace ContentCache hit/miss counters with hit ratios,
    stable key order (namespaces sorted; hits/misses/ratio fixed
    within) — the attribution surface serve ``stats`` has reported
    since PR 5, now shared with the ``stats`` CLI."""
    from . import cache as pf_cache

    out: dict = {}
    snap = pf_cache.stats()
    # quarantined files are invisible to the in-memory counters (they
    # are cross-process disk state) — fold the per-namespace disk
    # accounting in so `stats` reports the reclaimable footprint, not
    # just this process's detections.  Only namespaces with entries
    # appear, so a clean store adds nothing
    quarantine = pf_cache.get_cache().quarantine_stats()["by_namespace"]
    for stage in sorted(set(snap) | set(quarantine)):
        counts = snap.get(stage, {})
        hits = counts.get("hits", 0)
        misses = counts.get("misses", 0)
        total = hits + misses
        out[stage] = {
            "hits": hits,
            "misses": misses,
            "ratio": round(hits / total, 4) if total else 0.0,
        }
        # the damage-attribution counts (corrupt, quarantined,
        # remote_*) ride along when present — dropping them here would
        # leave the per-namespace records cache.py keeps unreachable
        # from every stats surface
        for key in sorted(counts):
            if key not in ("hits", "misses"):
                out[stage][key] = counts[key]
        if stage in quarantine:
            out[stage]["quarantine_entries"] = quarantine[stage]["entries"]
            out[stage]["quarantine_bytes"] = quarantine[stage]["bytes"]
    return out


def artifact_report() -> dict:
    """This process's artifact-plane attribution in stable key order:
    how much of its work came off the remote cache tier (hit/miss/
    corrupt/put round trips) and how many worker-shipped closure
    hydrations it performed (``compile.hydrated`` + ``render.hydrated``
    — the cold-worker ~15-19x proof).  The daemon ships this in every
    fleet heartbeat so ``fleet-status`` can attribute the shared
    remote tier per member; the serve ``stats`` op reports it for the
    local process."""
    counts = counters_snapshot()
    return {
        "hydrated": counts.get("compile.hydrated", 0)
        + counts.get("render.hydrated", 0),
        "remote_corrupt": counts.get("cache.remote_corrupt", 0),
        "remote_hits": counts.get("cache.remote_hits", 0),
        "remote_misses": counts.get("cache.remote_misses", 0),
        "remote_puts": counts.get("cache.remote_puts", 0),
    }


#: overflow tenant label once the cardinality cap is hit
SLO_OVERFLOW = "overflow"


def _slo_tenant_cap() -> int:
    """Max distinct SLO tenants tracked (``OPERATOR_FORGE_SLO_TENANTS``,
    default 64).  Tenants are hashes of served target paths, so a
    long-lived daemon fed ever-new directories (CI runs with per-run
    temp outputs) would otherwise grow the registry — and every stats/
    capsule payload — without bound; tenant #cap+1 onward aggregates
    under the ``overflow`` label instead."""
    from . import env_number

    return env_number(
        "OPERATOR_FORGE_SLO_TENANTS", 64, cast=int, minimum=1
    )


def _slo_key(tenant: str) -> str:
    """Route a tenant label through the cardinality cap: an already-
    tracked tenant keeps its slot, a new one past the cap lands in
    ``overflow``.  Tracked means a histogram OR a miss counter — a
    tenant whose every request was deadline-abandoned has only the
    counter, and it must consume a slot like any other (slo_report
    emits a row per miss counter too)."""
    with _lock:
        if (
            f"slo.{tenant}.seconds" in _histograms
            or f"slo.{tenant}.deadline_misses" in _counters
        ):
            return tenant
        tracked = {
            n[len("slo."):-len(".seconds")]
            for n in _histograms
            if n.startswith("slo.") and n.endswith(".seconds")
        } | {
            n[len("slo."):-len(".deadline_misses")]
            for n in _counters
            if n.startswith("slo.") and n.endswith(".deadline_misses")
        }
    return (
        tenant if len(tracked) < _slo_tenant_cap() else SLO_OVERFLOW
    )


def observe_slo(tenant: str, seconds: float) -> None:
    """Record one request's latency for a tenant (the ``serve.job.
    <tree-hash>`` project-namespace label the daemon partitions replay
    records by) — feeds :func:`slo_report`'s per-tenant histograms.
    Cardinality-bounded: see :func:`_slo_tenant_cap`."""
    histogram(f"slo.{_slo_key(tenant)}.seconds").observe(seconds)


def count_deadline_miss(tenant: str) -> None:
    """One deadline-abandoned request charged to its tenant (same
    cardinality routing as :func:`observe_slo`)."""
    counter(f"slo.{_slo_key(tenant)}.deadline_misses").inc()


def slo_report() -> dict:
    """Per-tenant SLO telemetry in stable key order: for every tenant
    with an ``slo.<tenant>.seconds`` histogram, the request count,
    interpolated p50/p99/p999, observed max, and the deadline-miss
    counter.  Tenants are the daemon's project-namespace labels, so
    ``stats`` / ``fleet-status --json`` / the bench ``slo`` leg all
    attribute latency to the same keys the cache partitions by."""
    with _lock:
        hists = {
            name: inst for name, inst in _histograms.items()
            if name.startswith("slo.") and name.endswith(".seconds")
        }
        misses = {
            name: c._value for name, c in _counters.items()
            if name.startswith("slo.")
            and name.endswith(".deadline_misses")
        }
    out: dict = {}
    for name in sorted(hists):
        tenant = name[len("slo."):-len(".seconds")]
        hist = hists[name]
        summary = hist.summary()
        out[tenant] = {
            "count": summary["count"],
            "deadline_misses": misses.get(
                f"slo.{tenant}.deadline_misses", 0
            ),
            "max": summary["max"],
            "p50": summary["p50"],
            "p99": summary["p99"],
            "p999": (
                round(hist.quantile(0.999), 6)
                if summary["count"] else None
            ),
        }
    # a tenant can miss deadlines without ever completing a request
    # (every attempt abandoned): it must still appear, not vanish
    for name in sorted(misses):
        tenant = name[len("slo."):-len(".deadline_misses")]
        if tenant not in out:
            out[tenant] = {
                "count": 0,
                "deadline_misses": misses[name],
                "max": 0.0,
                "p50": None,
                "p99": None,
                "p999": None,
            }
    return {tenant: out[tenant] for tenant in sorted(out)}


def tier_report() -> dict:
    """Execution-tier attribution (PR 11): the gocheck tier ceiling and
    the ladder counters — bodies lowered to closures, promoted to
    bytecode, reconstituted from manifests, registry reuse, bytecode
    program executions, and deopts — in stable key order.  Worker
    processes ship the same counters in their sealed-result deltas, so
    a resident daemon's numbers aggregate fleet-wide."""
    import sys

    compiler = sys.modules.get("operator_forge.gocheck.compiler")
    renderer = sys.modules.get("operator_forge.scaffold.render")
    if compiler is None and renderer is None:
        return {"mode": None}
    out = {"mode": compiler.mode() if compiler is not None else None}
    if compiler is not None:
        compiler.flush_counters()  # reconcile the lock-free tallies
    if renderer is not None:
        renderer.flush_counters()
        out["render_mode"] = renderer.mode()
    counts = counters_snapshot()
    for name in (
        "compile.lowered", "compile.promoted", "compile.hydrated",
        "compile.reused", "bytecode.executed", "bytecode.deopt",
        "sched.goroutines", "sched.leaked", "sched.deadlocks",
        "render.lowered", "render.hydrated", "render.executed",
        "render.deopt",
        "sanitize.checked", "sanitize.clock_merges", "sanitize.races",
    ):
        out[name] = counts.get(name, 0)
    return out


def editor_report() -> dict:
    """The editor-loop surface (PR 17) in stable key order: live
    overlay count, overlay registrations, supersede counts (queued vs
    in-flight), and the push-diagnostics cycle latency summary.  Lazy
    like :func:`tier_report`: a process that never imported the overlay
    store reports zeros without importing it here."""
    import sys

    overlay = sys.modules.get("operator_forge.perf.overlay")
    counts = counters_snapshot()
    with _lock:
        push = _histograms.get("editor.push_cycle.seconds")
    push_summary = push.summary() if push is not None else None
    return {
        "overlays": overlay.count() if overlay is not None else 0,
        "overlay_sets": counts.get("editor.overlay_sets", 0),
        "boost_delays": counts.get("editor.boost_delays", 0),
        "push_cycles": push_summary["count"] if push_summary else 0,
        "push_p50": push_summary["p50"] if push_summary else None,
        "push_p99": push_summary["p99"] if push_summary else None,
        "superseded": counts.get("editor.superseded", 0),
        "superseded_inflight": counts.get(
            "editor.superseded_inflight", 0
        ),
    }


def report() -> dict:
    """The whole observability surface in one stable-ordered document:
    cache attribution, the editor-loop surface, graph counters, the
    metrics registry, the execution-tier ladder, and the span table
    (the serve ``stats`` op and ``operator-forge stats`` both render
    this)."""
    from . import spans
    from .depgraph import GRAPH

    out = {
        "cache": cache_report(),
        "editor": editor_report(),
        "graph": GRAPH.counters(),
        "metrics": snapshot(),
        "slo": slo_report(),
        "spans": spans.snapshot(),
        "tiers": tier_report(),
    }
    # registered subsystem surfaces (daemon sessions, fleet members)
    # ride along as extra top-level keys, sorted after the fixed six
    out.update(stats_sources())
    return out
