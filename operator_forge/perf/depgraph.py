"""First-class dependency graph: minimal recomputation for the edit loop.

Every caching layer before this one was all-or-nothing at its
granularity — the project index was keyed on the *entire* file-hash
set, the analysis driver replayed only byte-identical whole runs, and
batch groups replayed only at tree fixed points — so the dominant real
workload, "edit one file, re-vet/re-test", paid near-cold cost even
though 95% of its inputs were unchanged.  This module is the engine
that makes recomputation proportional to the size of the edit (the
minimal-rebuild property of incremental build systems, cf. "Build
Systems à la Carte"-style verifying traces):

- **Nodes** are content-keyed artifacts: a file's per-analyzer
  diagnostics, a package's test-suite result, the project index.
- **Edges** are recorded automatically as a computation reads its
  inputs: anything consulted under :meth:`DepGraph.recording` (a file's
  bytes, a package's exported surface) lands in the node's dependency
  trace via :meth:`DepGraph.read`, without the orchestration layer
  enumerating inputs up front.
- **Validation** is signature-based: a node replays only while every
  recorded dependency's *current* signature matches the one recorded at
  build time, so a single-file edit invalidates exactly that file's
  nodes plus their transitive dependents and nothing else.

Persistence piggybacks on :mod:`operator_forge.perf.cache`: each node's
``(value, deps)`` trace is stored under its namespace in the shared
:class:`~operator_forge.perf.cache.ContentCache` (honoring
``OPERATOR_FORGE_CACHE`` off|mem|disk and the HMAC-signed disk format),
while the in-process node table makes repeat validations a dict lookup.
``off`` mode callers skip the graph entirely (see ``memo``), so the
cache-off path pays zero overhead and always recomputes live.

Counters (``dirty`` / ``reused`` / ``recomputed``) feed the serve
layer's ``stats`` op and the per-cycle ``graph`` report of the
``watch`` loop.

**Invalidation provenance** (PR 6): the graph also records *why* each
node went dirty — the changed input edge that failed validation and,
for reverse-dependency sweeps, the chain of node keys from the root
cause to the dirtied node.  :meth:`DepGraph.provenance` returns the
recorded table (bounded, deterministic order) and
:meth:`DepGraph.last_invalidation` the most recent sweep's summary;
the serve ``stats`` op surfaces both.  The *deterministic* explain
report (``operator-forge explain``) is derived structurally from the
tree instead (:mod:`operator_forge.gocheck.explain`), because this
recorded table legitimately differs across cache modes and worker
backends — an ``off``-mode run installs no nodes at all, and process
workers keep their own graphs.
"""

from __future__ import annotations

import threading

from . import cache as pf_cache


def _render_key(key) -> str:
    """Human/JSON rendering of a plain-data node or input key:
    ``("src", "a.go")`` → ``src:a.go``; long composite keys keep their
    leading namespace tag plus the string parts worth reading."""
    if isinstance(key, tuple):
        parts = [str(p) for p in key if isinstance(p, (str, int, bool))]
        return ":".join(parts) if parts else repr(key)
    return str(key)


class _Node:
    __slots__ = ("value", "deps")

    def __init__(self, value, deps: dict):
        self.value = value
        self.deps = deps


class DepGraph:
    """Thread-safe verifying-trace dependency graph."""

    #: recorded-provenance table cap: known keys keep updating, but no
    #: NEW keys are stored past it (bounds memory on long serve
    #: sessions; the counters still count every dirtied node)
    PROVENANCE_CAP = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._nodes: dict = {}   # key -> _Node
        self._rdeps: dict = {}   # dep key -> set of node keys
        self._tls = threading.local()
        self._counts = {"dirty": 0, "reused": 0, "recomputed": 0}
        # node key -> {"cause": root input key, "via": key chain}
        self._prov: dict = {}
        self._last_invalidation: dict = {}

    # -- counters --------------------------------------------------------

    def counters(self) -> dict:
        """``{"dirty", "reused", "recomputed"}`` in stable key order."""
        with self._lock:
            return {
                "dirty": self._counts["dirty"],
                "reused": self._counts["reused"],
                "recomputed": self._counts["recomputed"],
            }

    def count(self, what: str, n: int = 1) -> None:
        """Bump a counter (layers doing their own trace validation —
        the index delta path — report reuse/recompute through this)."""
        with self._lock:
            self._counts[what] += n

    def reset(self) -> None:
        with self._lock:
            self._nodes.clear()
            self._rdeps.clear()
            self._prov.clear()
            self._last_invalidation = {}
            for name in self._counts:
                self._counts[name] = 0

    # -- provenance ------------------------------------------------------

    def _record_cause(self, key, cause, via=()) -> None:
        # caller holds self._lock; a key already in the table always
        # updates (stale causes must not outlive the cap), only NEW
        # keys stop landing once the cap is reached
        if key in self._prov or len(self._prov) < self.PROVENANCE_CAP:
            self._prov[key] = {"cause": cause, "via": tuple(via)}

    def provenance(self) -> list:
        """The recorded why-did-this-recompute table, deterministic
        order (sorted by node key repr): one entry per dirtied or
        stale-validated node — ``{"node", "cause", "via"}``, each a
        plain-data key rendered with :func:`_render_key`."""
        with self._lock:
            items = list(self._prov.items())
        out = [
            {
                "node": _render_key(key),
                "cause": _render_key(entry["cause"]),
                "via": [_render_key(k) for k in entry["via"]],
            }
            for key, entry in items
        ]
        out.sort(key=lambda e: (e["node"], e["cause"]))
        return out

    def last_invalidation(self) -> dict:
        """Summary of the most recent :meth:`invalidate` sweep:
        ``{"roots": [...], "dirtied": n}`` (empty before any sweep)."""
        with self._lock:
            return dict(self._last_invalidation)

    # -- automatic edge recording ----------------------------------------

    def recording(self):
        """Context manager collecting every :meth:`read` made on this
        thread into a dependency dict (nested frames each see their own
        reads plus their children's — an input consulted by a
        subcomputation is an input of the whole)."""
        return _RecordingFrame(self)

    def read(self, key, sig) -> None:
        """Note that the in-flight computation consulted input ``key``
        whose current content signature is ``sig``.  A no-op outside
        :meth:`recording` frames."""
        frames = getattr(self._tls, "frames", None)
        if frames:
            for deps in frames:
                deps[key] = sig

    # -- nodes -----------------------------------------------------------

    def _first_stale(self, deps: dict, current_sig_of):
        """The first dependency key whose current signature no longer
        matches the recorded one (the *changed input edge*), or
        ``None`` when the whole trace still validates."""
        for dep_key, dep_sig in deps.items():
            if current_sig_of(dep_key) != dep_sig:
                return dep_key
        return None

    def _valid(self, deps: dict, current_sig_of) -> bool:
        return self._first_stale(deps, current_sig_of) is None

    def _install(self, key, value, deps: dict) -> None:
        with self._lock:
            old = self._nodes.get(key)
            if old is not None:
                for dep_key in old.deps:
                    self._rdeps.get(dep_key, set()).discard(key)
            self._nodes[key] = _Node(value, deps)
            for dep_key in deps:
                self._rdeps.setdefault(dep_key, set()).add(key)

    def invalidate(self, keys) -> int:
        """Drop the nodes depending (transitively) on any of ``keys``
        — the reverse-dependency sweep a file edit triggers.  Returns
        how many nodes were dirtied (also added to the ``dirty``
        counter).  Each dropped node's provenance is recorded: the
        root-cause input key it was reached from and the chain of node
        keys in between."""
        roots = list(keys)
        with self._lock:
            # queue entries: (key, root cause key, chain of keys walked
            # from the cause to — but not including — this key)
            queue = [(key, key, ()) for key in roots]
            dropped = 0
            seen = set()
            while queue:
                key, cause, via = queue.pop()
                if key in seen:
                    continue
                seen.add(key)
                for dependent in self._rdeps.pop(key, ()):
                    queue.append((dependent, cause, via + (key,)))
                node = self._nodes.pop(key, None)
                if node is not None:
                    dropped += 1
                    self._record_cause(key, cause, via)
                    for dep_key in node.deps:
                        self._rdeps.get(dep_key, set()).discard(key)
            self._counts["dirty"] += dropped
            self._last_invalidation = {
                "roots": sorted(_render_key(key) for key in roots),
                "dirtied": dropped,
            }
        return dropped

    def _replay(self, value, deps: dict):
        """A hit still *consumed* its recorded inputs: replay them into
        any enclosing recording frame, so a composed computation's
        trace includes what its replayed subcomputations consulted."""
        for dep_key, dep_sig in deps.items():
            self.read(dep_key, dep_sig)
        return value

    def peek(self, namespace: str, key: tuple, current_sig_of):
        """The in-memory hit path of :meth:`memo` alone: the node's
        value when it is present and validates, else
        :data:`~operator_forge.perf.cache.MISS` — no build, no
        persistent-cache consultation, no cause recording.  A caller
        with many candidate keys (the per-file analysis sweep) probes
        them serially and fans out only the misses, so a warm replay
        never pays thread-pool scheduling for pure table lookups.  A
        hit performs exactly :meth:`memo`'s hit bookkeeping, so the
        reuse counters cannot tell the two paths apart."""
        if pf_cache.get_cache().mode() == "off":
            return pf_cache.MISS
        with self._lock:
            node = self._nodes.get(key)
        if node is None:
            return pf_cache.MISS
        if self._first_stale(node.deps, current_sig_of) is not None:
            return pf_cache.MISS
        self.count("reused")
        pf_cache.get_cache()._count(namespace, "hits")
        return self._replay(node.value, node.deps)

    # -- the one-stop memoization entry point ----------------------------

    def memo(self, namespace: str, key: tuple, current_sig_of, build,
             deps=None, store_if=None):
        """Return the node for ``key``, recomputing minimally.

        ``key`` is a plain-data tuple (it doubles, hashed, as the
        ContentCache key under ``namespace``).  ``current_sig_of`` maps
        a dependency key to its *current* signature (``None`` = cannot
        validate).  ``build()`` produces the value; its inputs are the
        ``deps`` mapping when given, otherwise whatever ``build``
        reported through :meth:`read` while running under a recording
        frame.  ``store_if(value)`` may veto recording (transient
        faults must never replay).  ``OPERATOR_FORGE_CACHE=off``
        bypasses every store and always builds live.
        """
        cache = pf_cache.get_cache()
        if cache.mode() == "off":
            return build()
        with self._lock:
            node = self._nodes.get(key)
        if node is not None:
            stale = self._first_stale(node.deps, current_sig_of)
            if stale is None:
                self.count("reused")
                cache._count(namespace, "hits")
                return self._replay(node.value, node.deps)
            # the changed input edge that dirtied this node, recorded
            # at the moment staleness is observed
            with self._lock:
                self._record_cause(key, stale)
        ckey = pf_cache.hash_parts(key)
        record = cache.get(namespace, ckey, record_stats=False)
        if (
            record is not pf_cache.MISS
            and isinstance(record, tuple)
            and len(record) == 2
            and isinstance(record[1], dict)
        ):
            value, traced = record
            stale = self._first_stale(traced, current_sig_of)
            if stale is None:
                self._install(key, value, traced)
                self.count("reused")
                cache._count(namespace, "hits")
                return self._replay(value, traced)
            if node is None:
                with self._lock:
                    self._record_cause(key, stale)
        cache._count(namespace, "misses")
        self.count("recomputed")
        if deps is None:
            with self.recording() as traced:
                value = build()
            deps = traced
        else:
            value = build()
        if store_if is not None and not store_if(value):
            return value
        deps = dict(deps)
        self._install(key, value, deps)
        cache.put(namespace, ckey, (value, deps))
        return value


class _RecordingFrame:
    def __init__(self, graph: DepGraph):
        self._graph = graph
        self.deps: dict = {}

    def __enter__(self) -> dict:
        tls = self._graph._tls
        if not hasattr(tls, "frames"):
            tls.frames = []
        tls.frames.append(self.deps)
        return self.deps

    def __exit__(self, *exc) -> None:
        self._graph._tls.frames.pop()


#: the process-wide graph every incremental layer shares
GRAPH = DepGraph()

pf_cache.get_cache().reset_hooks.append(GRAPH.reset)
