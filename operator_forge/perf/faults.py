"""Deterministic fault injection — the chaos harness (PR 7).

Every execution subsystem (process-pool workers, the disk cache, the
serve job runner, the watch scanner) carries planted injection sites;
this registry decides, deterministically, which hits of which site
actually fire.  Faults are configured by ``OPERATOR_FORGE_FAULTS`` (or
programmatically via :func:`configure`) as nth-hit counters — never
wall-clock randomness — so a failing chaos run replays exactly:

.. code-block:: text

    spec  := entry ("," entry)*
    entry := kind "@" site [":" nth]        # nth defaults to 1

``kind`` names the failure to inject, ``site`` the planted location it
applies to (``*`` matches any site), and ``nth`` the 1-based hit of
that site on which it fires (one entry fires at most once; repeat the
entry with different counters to fire again).  Example::

    OPERATOR_FORGE_FAULTS=worker.crash@batch.group:2,cache.corrupt@disk:3,job.fail@serve.job:1

Registered kinds and the sites where they are planted:

===================  =====================  ================================
kind                 planted site           effect when fired
===================  =====================  ================================
``worker.crash``     any worker map site    pool child ``os._exit``\\ s hard
                     (``batch.group``, …)   before sealing its result
``task.hang``        any worker map site    pool child sleeps past any
                                            deadline (kill-at-deadline path)
``cache.corrupt``    ``disk``               one byte of the just-persisted
                                            entry is flipped
``cache.torn``       ``disk``               the just-persisted entry is
                                            truncated mid-blob (torn write)
``cache.zero``       ``disk``               the just-persisted entry is
                                            truncated to zero bytes
``job.fail``         ``serve.job``          a transient exception is raised
                                            before the job executes
``watch.vanish``     ``scan``               a scanned file vanishes between
                                            listing and stat (rename race)
``watch.scan_error`` ``scan.walk``          the whole snapshot walk raises
                                            a transient ``OSError``
``remote.unreachable`` ``remote``           every connection attempt of one
                                            remote-cache fetch is refused
                                            (dead server: degrade path)
``remote.corrupt``   ``remote``             the fetched remote payload has
                                            its last byte flipped (lying
                                            server: HMAC reject, recompute)
``remote.hang``      ``remote``             the remote fetch sleeps past the
                                            read deadline (hung server:
                                            deadline-then-degrade path)
``sched.preempt``    gocheck scheduler ops  the current goroutine yields to
                     (``chan.send``,        the seeded pick at that hit — an
                     ``chan.recv``,         alternate deterministic schedule;
                     ``chan.select``,       suite reports must not change
                     ``wg.wait``,
                     ``mutex.lock``,
                     ``workqueue.get``,
                     ``go.spawn``)
``envtest.conflict`` ``envtest.update`` /   the fake apiserver refuses the
                     ``envtest.patch``      write with an optimistic-lock
                                            conflict (requeue-on-conflict
                                            path; the retry converges)
``envtest.storm``    ``envtest.pump``       the reconcile pump injects a full
                                            resync — every live workload
                                            requeued (idempotence path)
``fleet.daemon_crash`` ``dispatch``         the fleet coordinator's dispatch
                                            connection is severed after the
                                            job was sent but before its
                                            response is read (daemon host
                                            death mid-run: re-dispatch path)
``fleet.heartbeat_lost`` ``lease``          one received heartbeat is dropped
                                            without refreshing the daemon's
                                            lease (lost packet: the lease
                                            ages toward suspect; the next
                                            beat recovers it)
``fleet.dispatch_hang`` ``route``           the dispatch to the routed daemon
                                            sleeps past the fleet dispatch
                                            deadline (hung daemon:
                                            deadline-then-re-dispatch path)
``fleet.partition``  ``link``               the daemon's fleet link drops its
                                            next beats WITHOUT closing the
                                            connection (severed network): the
                                            lease ages through suspect into
                                            eviction, and the rejoin goes
                                            through the stale-lease refusal
                                            then re-register path
``fleet.steal_kill`` ``steal``              the coordinator's dispatch
                                            connection is severed after a
                                            STOLEN submission was sent (the
                                            target died mid-steal, its tree
                                            half-hydrated: fence +
                                            re-dispatch path)
``flight.write_error`` ``capsule``          the flight-recorder capsule write
                                            raises (full/readonly disk): the
                                            recorder must count and carry
                                            on, never take the server down
===================  =====================  ================================

Hit counters are per-process: forked pool workers restart from zero
(an at-fork hook), and the parent ships its programmatic spec with
each task, so a worker observes the same configuration the parent
does.  Worker-directed kinds (``worker.crash`` / ``task.hang``) are
counted and planned in the *parent* at submission time — a retried
task is a fresh submission and does not replay an already-consumed
counter, which is what makes every injected fault recoverable.

The standing contract (enforced by bench.py's ``chaos`` section and
the commit-check chaos step): with any spec whose faults are
recoverable, final outputs are byte-identical to the fault-free
cache-off run — and with no spec configured, the planted sites cost
<1% of a cold codegen run (the fault-free fast path below).
"""

from __future__ import annotations

import os
import threading

ENV_VAR = "OPERATOR_FORGE_FAULTS"

#: every kind a spec may name; parse rejects anything else so a typo'd
#: chaos run fails loudly instead of silently injecting nothing
KINDS = (
    "worker.crash",
    "task.hang",
    "cache.corrupt",
    "cache.torn",
    "cache.zero",
    "job.fail",
    "watch.vanish",
    "watch.scan_error",
    "remote.unreachable",
    "remote.corrupt",
    "remote.hang",
    "sched.preempt",
    "envtest.conflict",
    "envtest.storm",
    "fleet.daemon_crash",
    "fleet.heartbeat_lost",
    "fleet.dispatch_hang",
    "fleet.partition",
    "fleet.steal_kill",
    "flight.write_error",
)


class FaultSpecError(ValueError):
    """A malformed ``OPERATOR_FORGE_FAULTS`` spec."""


def parse_spec(text: str) -> tuple:
    """Parse a spec string into ``(kind, site, nth)`` triples."""
    out = []
    for raw_entry in text.split(","):
        entry = raw_entry.strip()
        if not entry:
            continue
        kind, sep, rest = entry.partition("@")
        kind = kind.strip()
        if not sep or not rest.strip():
            raise FaultSpecError(
                f"fault entry {entry!r} must look like kind@site[:nth]"
            )
        if kind not in KINDS:
            raise FaultSpecError(
                f"unknown fault kind {kind!r}; known: " + ", ".join(KINDS)
            )
        site, sep, nth_text = rest.partition(":")
        site = site.strip()
        if not site:
            raise FaultSpecError(f"fault entry {entry!r} has an empty site")
        if sep:
            try:
                nth = int(nth_text.strip())
            except ValueError:
                raise FaultSpecError(
                    f"fault entry {entry!r}: nth must be an integer"
                ) from None
            if nth < 1:
                raise FaultSpecError(
                    f"fault entry {entry!r}: nth must be >= 1"
                )
        else:
            nth = 1
        out.append((kind, site, nth))
    return tuple(out)


_lock = threading.Lock()
_fork_child = [False]  # pool children never report unfired entries
_forced = None  # programmatic spec override (None: follow the env var)
# raw-text cache: the fault-free fast path is one env read + one string
# compare per planted-site hit, no parsing and no lock
_raw = [None]
_active = [()]
_hits: dict = {}
_fired: list = []


def _current() -> tuple:
    raw = _forced if _forced is not None else os.environ.get(ENV_VAR, "")
    if raw == _raw[0]:
        return _active[0]
    with _lock:
        if raw != _raw[0]:
            _active[0] = parse_spec(raw) if raw.strip() else ()
            _hits.clear()
            _fired.clear()
            _raw[0] = raw
    return _active[0]


def configure(spec=None) -> None:
    """Programmatic spec override (``None`` restores env selection).
    Validates eagerly and always resets the hit counters, so a test or
    bench leg starts every configuration from hit zero."""
    global _forced
    if spec is not None:
        parse_spec(spec)  # fail here, not at the first injection site
    with _lock:
        _forced = spec
        _raw[0] = None  # force re-parse (and a counter reset) next hit


def forced_spec():
    """The current programmatic override (shipped to pool workers)."""
    return _forced


def reset() -> None:
    """Reset hit counters and the fired log, keeping the spec."""
    with _lock:
        _hits.clear()
        _fired.clear()


def enabled() -> bool:
    return bool(_current())


def fire(site: str, *kinds) -> tuple:
    """Count one hit of ``site`` and return the subset of ``kinds``
    whose counters landed on this hit (usually empty).  One call is one
    hit however many kinds are probed, so sites with several possible
    failures stay deterministic."""
    active = _current()
    if not active:
        return ()
    out = []
    with _lock:
        count = _hits.get(site, 0) + 1
        _hits[site] = count
        for kind, spec_site, nth in active:
            if (
                kind in kinds
                and nth == count
                and (spec_site == site or spec_site == "*")
            ):
                out.append(kind)
                _fired.append((kind, site, count))
    if out:
        from . import metrics

        metrics.counter("faults.injected").inc(len(out))
    return tuple(out)


def should_fire(kind: str, site: str) -> bool:
    """Convenience wrapper for single-kind sites."""
    return bool(fire(site, kind))


def fired() -> tuple:
    """The ``(kind, site, nth)`` log of injected faults, in firing
    order — the determinism handle: same spec + same call sequence
    means the same log, byte for byte."""
    with _lock:
        return tuple(_fired)


def unfired() -> tuple:
    """Spec entries that have not fired (yet) in this process, in spec
    order.  Kinds are validated at parse, but sites are free strings
    (worker map sites are caller-named), so a typo'd or never-planted
    site cannot be rejected up front — it surfaces here instead."""
    active = _current()
    if not active:
        return ()
    log = fired()
    return tuple(
        (kind, site, nth)
        for kind, site, nth in active
        if not any(
            f_kind == kind and f_nth == nth
            and (site == "*" or f_site == site)
            for f_kind, f_site, f_nth in log
        )
    )


def _warn_unfired_at_exit() -> None:
    # the loud half of the determinism story: a spec entry naming a
    # never-planted site (or an nth above the site's traffic) parses
    # fine and then silently injects nothing — the exact trap a chaos
    # harness exists to avoid.  Report it on the REAL stderr (captured
    # job output must stay byte-identical) from the process that owns
    # the spec; forked pool children see a partial view (their counters
    # restart from zero) and stay quiet.
    if _fork_child[0]:
        return
    try:
        pending = unfired()
    except Exception:
        return  # a malformed env spec already failed loudly at parse
    if not pending:
        return
    import sys

    stream = sys.__stderr__ or sys.stderr
    entries = ",".join(f"{k}@{s}:{n}" for k, s, n in pending)
    print(
        f"operator-forge: configured fault(s) never fired: {entries} — "
        "check the site against the planted sites (see perf/faults.py) "
        "and the nth against the site's traffic",
        file=stream,
    )


import atexit  # noqa: E402

atexit.register(_warn_unfired_at_exit)


def _reset_after_fork() -> None:
    # a forked pool worker counts its own site hits from zero — the
    # parent's consumed counters must not leak into the child, or the
    # nth-hit semantics would depend on fork timing.  The lock is
    # re-created too: fork can land while another parent thread holds
    # it, and the child would inherit it locked forever
    global _lock
    _lock = threading.Lock()
    _fork_child[0] = True
    _hits.clear()
    _fired.clear()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_after_fork)
