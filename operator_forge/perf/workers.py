"""Execution backends for batch/serve fan-out (PR 3).

``OPERATOR_FORGE_WORKERS`` selects how independent job groups execute:

- ``thread`` (default) — a dedicated fan-out thread pool.  Deliberately
  NOT :data:`operator_forge.perf._pool`: group tasks themselves call
  :func:`~operator_forge.perf.parallel_map` (per-manifest inspection,
  per-file writes, per-package test runs), and submitting to the pool a
  task is already running on can starve it.  Two pools keep the waits
  acyclic.
- ``process`` — a persistent ``ProcessPoolExecutor`` forked from this
  process, so CPU-bound gocheck checking scales across cores instead of
  serializing on the GIL.  The parent pre-warms the gocheck stdlib
  manifest, symbol surfaces, and interpreter/compiler modules
  immediately before forking, so every worker inherits the warm state
  by copy-on-write; workers persist across calls, keeping their own
  content-addressed caches hot for the lifetime of the pool.

Results always collect in input order, so a successful ``process`` run
is observably equivalent to ``thread`` and to the serial loop — batch
byte-identity is proven by tests/test_serve_batch.py and enforced by
bench.py's ``batch.identity_by_cache_mode`` guard.

Worker coordination details:

- **signed-blob results** — worker return values round-trip through the
  same HMAC-signed pickle serialization the disk cache uses
  (:mod:`operator_forge.perf.cache`): the worker seals
  ``sign(key, pickle(value)) + pickle(value)`` and the parent verifies
  before unpickling, so a corrupted or substituted result surfaces as
  an authentication error instead of deserializing.  When tracing is
  on (``OPERATOR_FORGE_TRACE``) each sealed result also carries the
  worker's drained span-event buffer; the parent ingests it into its
  own ring, so one Chrome trace covers the whole process tree.
- **config shipping** — forked workers snapshot the parent's state at
  fork time only, so each task carries the parent's *current* cache
  mode/root overrides, gocheck interpreter mode, relevant env knobs,
  and cache-reset generation; the worker applies them before running.
  A parent-side ``perf.cache.reset()`` therefore takes effect in every
  worker at its next task.
- **fork hygiene** — executors do not survive ``fork()`` (the child
  inherits the object but not its threads), so an ``at_fork`` hook
  drops all pool singletons in the child; in-worker fan-out is forced
  back to ``thread`` to keep process trees flat.

Infrastructure failures (fork unavailable, broken pool, unpicklable
task) fall back to the thread backend; since every batch job is
deterministic and idempotent this changes wall-clock, never output.
"""

from __future__ import annotations

import os
import pickle
import threading

from . import n_jobs
from . import cache as pf_cache
from . import spans

_BACKENDS = ("thread", "process")
DEFAULT_BACKEND = "thread"

_forced = None


def backend() -> str:
    """The selected backend: programmatic override, else
    ``OPERATOR_FORGE_WORKERS``, else ``thread``."""
    if _forced is not None:
        return _forced
    raw = os.environ.get("OPERATOR_FORGE_WORKERS", DEFAULT_BACKEND)
    raw = raw.strip().lower()
    return raw if raw in _BACKENDS else DEFAULT_BACKEND


def set_backend(value=None) -> None:
    """Programmatic override (``None`` restores env-driven selection)."""
    global _forced
    if value is not None and value not in _BACKENDS:
        raise ValueError(
            f"unknown workers backend {value!r}; known: {_BACKENDS}"
        )
    _forced = value


# -- cache-reset propagation ---------------------------------------------
#
# Persistent workers keep their forked mem caches; a parent-side
# pf_cache.reset() must reach them or identity legs could replay stale
# state.  The parent bumps a generation on every reset and ships it with
# each task; a worker seeing a new generation resets its own caches.

_reset_gen = [0]


def _bump_reset_gen() -> None:
    _reset_gen[0] += 1


pf_cache.get_cache().reset_hooks.append(_bump_reset_gen)

_worker_seen_gen = [0]

# env knobs a task's behavior may read; shipped per task because workers
# fork once and would otherwise see stale values
_SHIPPED_ENV = (
    "OPERATOR_FORGE_CACHE",
    "OPERATOR_FORGE_CACHE_DIR",
    "OPERATOR_FORGE_JOBS",
    "OPERATOR_FORGE_GOCHECK",
    "OPERATOR_FORGE_PROFILE",
    "OPERATOR_FORGE_TRACE",
    "OPERATOR_FORGE_TRACE_EVENTS",
)


def _task_config() -> dict:
    from ..gocheck import compiler

    cache = pf_cache.get_cache()
    return {
        "cache_mode": cache._mode_override,
        "cache_root": cache._root_override,
        "gocheck_mode": compiler._forced,
        "env": {k: os.environ.get(k) for k in _SHIPPED_ENV},
        # the programmatic tracing override (cmd_trace, tests) — env
        # shipping alone would miss it, and a worker forked mid-trace
        # would otherwise keep its fork-time state forever
        "trace": spans._trace_forced,
        "gen": _reset_gen[0],
    }


def _apply_config(cfg: dict) -> None:
    from ..gocheck import compiler

    for key, value in cfg["env"].items():
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = value
    # in-worker fan-out must not fork grandchildren: pin the env knob
    # AND drop any inherited set_backend() override (which would
    # otherwise shadow the env)
    os.environ["OPERATOR_FORGE_WORKERS"] = "thread"
    set_backend("thread")
    # spans caches the enable state (no per-call env reads); the shipped
    # OPERATOR_FORGE_PROFILE / OPERATOR_FORGE_TRACE values and the
    # parent's programmatic tracing override take effect here (the
    # enable_tracing call refreshes).  Workers never write the trace
    # file themselves — their events ship back in each sealed result
    spans.suppress_trace_export(True)
    spans.enable_tracing(cfg["trace"])
    pf_cache.configure(cfg["cache_mode"], cfg["cache_root"])
    compiler.set_mode(cfg["gocheck_mode"])
    if cfg["gen"] != _worker_seen_gen[0]:
        _worker_seen_gen[0] = cfg["gen"]
        pf_cache.reset()


# -- signed-blob result round trip ---------------------------------------


def _seal(value) -> tuple:
    blob = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
    key = pf_cache._load_hmac_key()
    if key is None:  # no writable home: unauthenticated, flagged as such
        return ("raw", blob)
    return ("sealed", pf_cache._sign(key, blob) + blob)


def _unseal(wrapped: tuple):
    import hmac

    kind, data = wrapped
    if kind == "sealed":
        key = pf_cache._load_hmac_key()
        if key is None or len(data) <= pf_cache._SIG_BYTES:
            raise RuntimeError("worker result failed authentication")
        signature = data[: pf_cache._SIG_BYTES]
        data = data[pf_cache._SIG_BYTES:]
        if not hmac.compare_digest(signature, pf_cache._sign(key, data)):
            raise RuntimeError("worker result failed authentication")
    return pickle.loads(data)


def _trace_payload() -> list:
    """The worker's buffered trace events, drained for shipping.  A
    fresh worker's ring starts empty (spans clears it after fork), so
    every drain ships exactly the events produced since the previous
    task — the parent merges them into one timeline, distinguished by
    the worker's pid in each event."""
    if not spans.trace_enabled():
        return []
    return spans.drain_events()


def _sealed_call(cfg: dict, fn, item) -> tuple:
    """Worker-side task wrapper: apply the parent's shipped config,
    run, seal the outcome (plus the worker's drained trace-event
    buffer).  Task exceptions are sealed as values (not raised through
    the executor), so anything that DOES raise out of a future is, by
    construction, an infrastructure failure."""
    _apply_config(cfg)
    try:
        return _seal(("ok", fn(item), _trace_payload()))
    except BaseException as exc:
        events = _trace_payload()
        try:
            return _seal(("err", exc, events))
        except Exception:  # the exception itself didn't pickle
            return _seal(("err", RuntimeError(
                f"{type(exc).__name__}: {exc}"
            ), events))


class _TaskFailure(Exception):
    """Parent-side wrapper distinguishing a task's own exception from
    pool infrastructure errors; map_ordered unwraps and re-raises the
    cause instead of falling back to threads."""

    def __init__(self, cause):
        super().__init__(str(cause))
        self.cause = cause


# -- pre-warm -------------------------------------------------------------


def warm_gocheck() -> None:
    """Load the gocheck surfaces every checking job needs — the stdlib
    dependency manifest, the symbol surfaces the type layer consults,
    and the parser/interpreter/compiler modules.  Called in the parent
    immediately before the process pool forks, so workers inherit the
    warm state by copy-on-write instead of each paying it again."""
    from ..gocheck import compiler, interp, parser, world  # noqa: F401
    from ..gocheck.manifest import MANIFEST  # noqa: F401  (assembles it)
    from ..gocheck.stdmanifest import symbol_surface

    for path in (
        "fmt", "strings", "context", "errors", "time", "os",
        "sigs.k8s.io/controller-runtime",
        "k8s.io/apimachinery/pkg/apis/meta/v1/unstructured",
    ):
        symbol_surface(path)


# -- the pools ------------------------------------------------------------

_pool_lock = threading.Lock()
_fan_pools: dict = {}  # max_workers -> shared fan-out ThreadPoolExecutor
_proc_pool = None
_proc_size = 0


def _forget_pools_after_fork() -> None:
    # a forked child inherits the executor objects but not their
    # threads/processes; using one would hang forever
    global _proc_pool, _proc_size
    _fan_pools.clear()
    _proc_pool = None
    _proc_size = 0


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_forget_pools_after_fork)


def _shutdown_pools() -> None:
    # orderly teardown; letting interpreter finalization collect a live
    # ProcessPoolExecutor prints spurious weakref tracebacks
    global _proc_pool
    with _pool_lock:
        for pool in _fan_pools.values():
            pool.shutdown(wait=False)
        _fan_pools.clear()
        if _proc_pool is not None:
            _proc_pool.shutdown(wait=True)
            _proc_pool = None


import atexit  # noqa: E402

atexit.register(_shutdown_pools)


def _thread_pool(jobs: int):
    """One fan-out pool per width, never shut down mid-run — like
    perf._executor, concurrent callers with different widths must not
    tear down each other's executor."""
    from concurrent.futures import ThreadPoolExecutor

    with _pool_lock:
        pool = _fan_pools.get(jobs)
        if pool is None:
            pool = _fan_pools[jobs] = ThreadPoolExecutor(
                max_workers=jobs, thread_name_prefix="operator-forge-fan"
            )
        return pool


def _process_pool():
    """The persistent worker-process pool, sized by ``n_jobs()`` (not
    by any one call's item count, so varying batch shapes keep reusing
    the same warm workers)."""
    from concurrent.futures import ProcessPoolExecutor
    import multiprocessing

    global _proc_pool, _proc_size
    jobs = n_jobs()
    with _pool_lock:
        if _proc_pool is None or _proc_size != jobs:
            if _proc_pool is not None:
                _proc_pool.shutdown(wait=False)
            # fork (not spawn): workers inherit warm module/caches state
            # and the loaded sys.modules task functions pickle against
            ctx = multiprocessing.get_context("fork")
            warm_gocheck()
            _proc_pool = ProcessPoolExecutor(
                max_workers=jobs, mp_context=ctx
            )
            _proc_size = jobs
        return _proc_pool


def _discard_process_pool() -> None:
    global _proc_pool, _proc_size
    with _pool_lock:
        if _proc_pool is not None:
            _proc_pool.shutdown(wait=False)
        _proc_pool = None
        _proc_size = 0


def _infra_errors() -> tuple:
    from concurrent.futures.process import BrokenProcessPool

    # _sealed_call seals task exceptions as values, so anything raised
    # out of a future is infrastructure: a dead pool, or a task/result
    # that could not cross the pickle boundary at all.  Task-level
    # exceptions surface as _TaskFailure and re-raise as themselves.
    return (
        BrokenProcessPool, pickle.PicklingError, AttributeError,
        ImportError, EOFError, BrokenPipeError,
    )


def _thread_map(fn, items, jobs: int) -> list:
    pool = _thread_pool(jobs)
    futures = [pool.submit(fn, item) for item in items]
    return [future.result() for future in futures]


def _process_map(pool, fn, items) -> list:
    from . import metrics

    cfg = _task_config()
    queue_depth = metrics.gauge("workers.queue_depth")
    metrics.counter("workers.tasks_submitted").inc(len(items))
    queue_depth.add(len(items))
    done = 0
    try:
        futures = [
            pool.submit(_sealed_call, cfg, fn, item) for item in items
        ]
        out = []
        for future in futures:
            kind, payload, events = _unseal(future.result())
            done += 1
            queue_depth.add(-1)  # live backlog, not batch size
            metrics.counter("workers.tasks_completed").inc()
            # merge the worker's timeline into the parent's ring: one
            # Chrome trace then covers serial, thread, and process runs
            spans.ingest_events(events)
            if kind == "err":
                raise _TaskFailure(payload)
            out.append(payload)
        return out
    finally:
        # a task/infra error abandons the remaining futures; the gauge
        # must not leak their depth
        queue_depth.add(-(len(items) - done))


def map_ordered(fn, items) -> list:
    """Ordered map over ``items`` through the selected backend.

    ``fn`` must be a module-level callable and ``items`` picklable when
    the ``process`` backend is active (they cross the fork boundary);
    the ``thread``/serial paths have no such requirement.  One job (or
    one item) short-circuits to the plain serial loop.
    """
    items = list(items)
    jobs = min(n_jobs(), len(items))
    if jobs <= 1 or len(items) <= 1:
        return [fn(item) for item in items]
    if backend() == "process":
        try:
            pool = _process_pool()
        except Exception:
            # fork unsupported or worker startup failed; nothing ran
            # yet, so threads take the whole map
            return _thread_map(fn, items, jobs)
        try:
            return _process_map(pool, fn, items)
        except _TaskFailure as failure:
            raise failure.cause  # the task's own error, verbatim
        except _infra_errors():
            # the pool died or the task didn't pickle: jobs are
            # deterministic and idempotent, so re-running on threads
            # yields the identical result, just without multicore
            # scaling
            _discard_process_pool()
            return _thread_map(fn, items, jobs)
    return _thread_map(fn, items, jobs)
