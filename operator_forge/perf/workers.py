"""Execution backends for batch/serve fan-out (PR 3).

``OPERATOR_FORGE_WORKERS`` selects how independent job groups execute:

- ``thread`` (default) — a dedicated fan-out thread pool.  Deliberately
  NOT :data:`operator_forge.perf._pool`: group tasks themselves call
  :func:`~operator_forge.perf.parallel_map` (per-manifest inspection,
  per-file writes, per-package test runs), and submitting to the pool a
  task is already running on can starve it.  Two pools keep the waits
  acyclic.
- ``process`` — a persistent ``ProcessPoolExecutor`` forked from this
  process, so CPU-bound gocheck checking scales across cores instead of
  serializing on the GIL.  The parent pre-warms the gocheck stdlib
  manifest, symbol surfaces, and interpreter/compiler modules
  immediately before forking, so every worker inherits the warm state
  by copy-on-write; workers persist across calls, keeping their own
  content-addressed caches hot for the lifetime of the pool.

Results always collect in input order, so a successful ``process`` run
is observably equivalent to ``thread`` and to the serial loop — batch
byte-identity is proven by tests/test_serve_batch.py and enforced by
bench.py's ``batch.identity_by_cache_mode`` guard.

Worker coordination details:

- **signed-blob results** — worker return values round-trip through the
  same HMAC-signed pickle serialization the disk cache uses
  (:mod:`operator_forge.perf.cache`): the worker seals
  ``sign(key, pickle(value)) + pickle(value)`` and the parent verifies
  before unpickling, so a corrupted or substituted result surfaces as
  an authentication error instead of deserializing.  When tracing is
  on (``OPERATOR_FORGE_TRACE``) each sealed result also carries the
  worker's drained span-event buffer; the parent ingests it into its
  own ring, so one Chrome trace covers the whole process tree.
- **config shipping** — forked workers snapshot the parent's state at
  fork time only, so each task carries the parent's *current* cache
  mode/root overrides, gocheck interpreter mode, relevant env knobs,
  and cache-reset generation; the worker applies them before running.
  A parent-side ``perf.cache.reset()`` therefore takes effect in every
  worker at its next task.
- **fork hygiene** — executors do not survive ``fork()`` (the child
  inherits the object but not its threads), so an ``at_fork`` hook
  drops all pool singletons in the child; in-worker fan-out is forced
  back to ``thread`` to keep process trees flat.

Self-healing (PR 7): the process backend no longer degrades silently.
Each map round submits the pending tasks, and anything that comes back
broken — a dead pool (``BrokenProcessPool`` after a worker crash), a
task that blows the ``OPERATOR_FORGE_TASK_TIMEOUT`` deadline (the hung
pool processes are killed), or a result that cannot cross the pickle
boundary — marks the uncollected tasks failed and triggers a bounded
deterministic retry: the pool is respawned and only the failed tasks
re-run (``worker.retries`` / ``worker.respawns`` / ``worker.timeouts``
metrics).  After ``OPERATOR_FORGE_TASK_RETRIES`` retries the surviving
tasks are quarantined to in-thread execution (``worker.quarantined``)
and the degradation is recorded: a one-shot warning on the real stderr
(bypassing job capture, so output bytes never change), a
``worker.degraded`` counter, a ``workers.degraded`` gauge, and the
:func:`pool_state` surface serve ``stats`` reports.  Because every
task is deterministic and idempotent, recovery changes wall-clock,
never output — the chaos harness (:mod:`operator_forge.perf.faults`)
proves it by injecting ``worker.crash`` / ``task.hang`` at the
submission sites and asserting byte-identity with the fault-free run.
"""

from __future__ import annotations

import os
import pickle
import sys
import threading
import time

from . import env_number, n_jobs
from . import cache as pf_cache
from . import faults
from . import spans

_BACKENDS = ("thread", "process")
DEFAULT_BACKEND = "thread"
#: bounded deterministic retry for broken/hung/crashed rounds
DEFAULT_TASK_RETRIES = 2
#: deterministic backoff step between retry rounds (seconds, no jitter)
_BACKOFF_S = 0.05

_forced = None


def task_timeout() -> float:
    """Per-task deadline in seconds (``OPERATOR_FORGE_TASK_TIMEOUT``;
    0 or unset disables).  Applied while collecting each process-pool
    result; a task that exceeds it is killed with its pool and
    retried."""
    return env_number("OPERATOR_FORGE_TASK_TIMEOUT", 0.0)


def task_retries() -> int:
    """How many retry rounds a failing map gets before the surviving
    tasks are quarantined to in-thread execution
    (``OPERATOR_FORGE_TASK_RETRIES``, default 2)."""
    return env_number(
        "OPERATOR_FORGE_TASK_RETRIES", DEFAULT_TASK_RETRIES, cast=int
    )


def _hang_seconds() -> float:
    """How long an injected ``task.hang`` sleeps — long enough that an
    unkilled hang is obvious, short enough that a deadline-less test
    run eventually finishes (``OPERATOR_FORGE_FAULT_HANG_S``)."""
    return env_number("OPERATOR_FORGE_FAULT_HANG_S", 30.0)


# -- degradation accounting ----------------------------------------------
#
# The old behavior — any infra failure silently falls back to threads —
# hid dead pools behind unexplained slowness.  Degradation is now a
# recorded event: metrics, a gauge, a pool_state() surface for serve
# `stats`, and a one-shot human warning.

_degraded = {"active": False, "reason": ""}
_warned_once = [False]


def _degrade(reason: str) -> None:
    from . import flight, metrics

    _degraded["active"] = True
    _degraded["reason"] = reason
    metrics.counter("worker.degraded").inc()
    # every degradation (poison-task quarantine included) is a flight
    # anomaly: the ring around the moment the pool died is exactly
    # what a post-mortem needs
    flight.anomaly("worker.degraded", {"reason": reason})
    # conftest's metrics.reset() drops registrations, so (re)register
    # lazily at the moment the gauge becomes meaningful
    metrics.register_gauge(
        "workers.degraded", lambda: 1 if _degraded["active"] else 0
    )
    if not _warned_once[0]:
        _warned_once[0] = True
        # the REAL stderr: inside a captured batch/serve job the routed
        # sys.stderr would fold this warning into the job's output and
        # break byte-identity with a non-degraded run
        stream = sys.__stderr__ or sys.stderr
        print(
            "operator-forge: process pool degraded to threads: "
            f"{reason} (this warning prints once)",
            file=stream,
        )


def pool_state() -> dict:
    """The execution-backend surface serve ``stats`` reports: the
    selected backend, whether the pool has degraded, and why.  The
    degraded flag is sticky — it records that this process fell back
    at least once — until :func:`reset_degraded` clears it."""
    return {
        "backend": backend(),
        "degraded": _degraded["active"],
        "degraded_reason": _degraded["reason"],
    }


def reset_degraded() -> None:
    """Clear the sticky degradation record (tests, or an operator
    after remediating the cause); the one-shot stderr warning stays
    one-shot per process."""
    _degraded["active"] = False
    _degraded["reason"] = ""


def backend() -> str:
    """The selected backend: programmatic override, else
    ``OPERATOR_FORGE_WORKERS``, else ``thread``."""
    if _forced is not None:
        return _forced
    raw = os.environ.get("OPERATOR_FORGE_WORKERS", DEFAULT_BACKEND)
    raw = raw.strip().lower()
    return raw if raw in _BACKENDS else DEFAULT_BACKEND


def set_backend(value=None) -> None:
    """Programmatic override (``None`` restores env-driven selection)."""
    global _forced
    if value is not None and value not in _BACKENDS:
        raise ValueError(
            f"unknown workers backend {value!r}; known: {_BACKENDS}"
        )
    _forced = value


# -- cache-reset propagation ---------------------------------------------
#
# Persistent workers keep their forked mem caches; a parent-side
# pf_cache.reset() must reach them or identity legs could replay stale
# state.  The parent bumps a generation on every reset and ships it with
# each task; a worker seeing a new generation resets its own caches.

_reset_gen = [0]


def _bump_reset_gen() -> None:
    _reset_gen[0] += 1


pf_cache.get_cache().reset_hooks.append(_bump_reset_gen)

_worker_seen_gen = [0]

# env knobs a task's behavior may read; shipped per task because workers
# fork once and would otherwise see stale values
_SHIPPED_ENV = (
    "OPERATOR_FORGE_CACHE",
    "OPERATOR_FORGE_CACHE_DIR",
    "OPERATOR_FORGE_JOBS",
    "OPERATOR_FORGE_GOCHECK",
    "OPERATOR_FORGE_GOCHECK_PROMOTE",
    "OPERATOR_FORGE_RENDER",
    "OPERATOR_FORGE_PROFILE",
    "OPERATOR_FORGE_TRACE",
    "OPERATOR_FORGE_TRACE_EVENTS",
    "OPERATOR_FORGE_FAULTS",
    "OPERATOR_FORGE_FAULT_HANG_S",
    "OPERATOR_FORGE_TASK_TIMEOUT",
    "OPERATOR_FORGE_TASK_RETRIES",
    "OPERATOR_FORGE_JOB_RETRIES",
    "OPERATOR_FORGE_REMOTE_CACHE",
    "OPERATOR_FORGE_REMOTE_TIMEOUT",
    "OPERATOR_FORGE_REMOTE_RETRIES",
    "OPERATOR_FORGE_REMOTE_QUEUE",
)


def _task_config() -> dict:
    from ..gocheck import compiler

    cache = pf_cache.get_cache()
    return {
        "cache_mode": cache._mode_override,
        "cache_root": cache._root_override,
        "gocheck_mode": compiler._forced,
        "gocheck_promote": compiler._forced_promote,
        "render_mode": _render_forced(),
        "env": {k: os.environ.get(k) for k in _SHIPPED_ENV},
        # the programmatic tracing override (cmd_trace, tests) — env
        # shipping alone would miss it, and a worker forked mid-trace
        # would otherwise keep its fork-time state forever
        "trace": spans._trace_forced,
        # the submitting thread's trace context: a traced request's
        # pool tasks emit inside its segment (the worker suffixes its
        # pid so two children's span counters cannot collide)
        "trace_ctx": spans.current_context(),
        # the programmatic fault-spec override (bench legs, tests) —
        # env shipping alone would miss it
        "faults": faults.forced_spec(),
        # the programmatic remote-cache address override, same reason
        "remote": _remote_forced(),
        # live buffer overlays (PR 17): a process worker must see the
        # same unsaved bytes the parent's content keys were computed
        # from, or the thread/process identity matrix would split.
        # None (no store loaded / store empty) ships nothing
        "overlays": _overlay_snapshot(),
        "gen": _reset_gen[0],
    }


def _render_forced():
    # lazy: the render tier only matters once scaffolding has loaded it
    import sys

    render = sys.modules.get("operator_forge.scaffold.render")
    return None if render is None else render._forced


def _remote_forced():
    # lazy: the remote module only loads once something configures it
    import sys

    remote = sys.modules.get("operator_forge.perf.remote")
    return remote._forced_addr if remote is not None else None


def _overlay_snapshot():
    # lazy: the overlay store only matters once the editor tier (or a
    # test) has loaded it — a batch-only process pays nothing
    import sys

    overlay = sys.modules.get("operator_forge.perf.overlay")
    return (
        overlay.snapshot_for_shipping() if overlay is not None else None
    )


def _apply_config(cfg: dict) -> None:
    from ..gocheck import compiler

    for key, value in cfg["env"].items():
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = value
    # in-worker fan-out must not fork grandchildren: pin the env knob
    # AND drop any inherited set_backend() override (which would
    # otherwise shadow the env)
    os.environ["OPERATOR_FORGE_WORKERS"] = "thread"
    set_backend("thread")
    # spans caches the enable state (no per-call env reads); the shipped
    # OPERATOR_FORGE_PROFILE / OPERATOR_FORGE_TRACE values and the
    # parent's programmatic tracing override take effect here (the
    # enable_tracing call refreshes).  Workers never write the trace
    # file themselves — their events ship back in each sealed result
    spans.suppress_trace_export(True)
    spans.enable_tracing(cfg["trace"])
    ctx = cfg.get("trace_ctx")
    if ctx is not None:
        trace, seg, base = ctx
        spans.adopt_context((trace, f"{seg}.p{os.getpid()}", base))
    else:
        spans.adopt_context(None)
    pf_cache.configure(cfg["cache_mode"], cfg["cache_root"])
    compiler.set_mode(cfg["gocheck_mode"])
    compiler.set_promote_after(cfg.get("gocheck_promote"))
    if cfg.get("render_mode") != _render_forced():
        # ship the parent's programmatic render-mode override (bench
        # identity legs, tests) — env shipping alone would miss it
        from ..scaffold import render

        render.set_mode(cfg.get("render_mode"))
    if cfg["faults"] != faults.forced_spec():
        # only on change: configure() resets the worker's hit counters,
        # and a per-task reset would re-fire every :1 fault forever
        faults.configure(cfg["faults"])
    if cfg["remote"] != _remote_forced():
        # only on change, same reason: configure() clears the sticky
        # degraded state and the per-run negative memo
        from . import remote

        remote.configure(cfg["remote"])
    overlays = cfg.get("overlays")
    if overlays:
        from . import overlay

        overlay.adopt(overlays)
    else:
        # clear any previous task's overlays without importing the
        # store into a worker that never saw one
        import sys as _sys

        overlay = _sys.modules.get("operator_forge.perf.overlay")
        if overlay is not None and overlay.count():
            overlay.adopt({})
    if cfg["gen"] != _worker_seen_gen[0]:
        _worker_seen_gen[0] = cfg["gen"]
        pf_cache.reset()


# -- signed-blob result round trip ---------------------------------------


def _seal(value) -> tuple:
    blob = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
    key = pf_cache._load_hmac_key()
    if key is None:  # no writable home: unauthenticated, flagged as such
        return ("raw", blob)
    return ("sealed", pf_cache._sign(key, blob) + blob)


def _unseal(wrapped: tuple):
    import hmac

    kind, data = wrapped
    if kind == "sealed":
        key = pf_cache._load_hmac_key()
        if key is None or len(data) <= pf_cache._SIG_BYTES:
            raise RuntimeError("worker result failed authentication")
        signature = data[: pf_cache._SIG_BYTES]
        data = data[pf_cache._SIG_BYTES:]
        if not hmac.compare_digest(signature, pf_cache._sign(key, data)):
            raise RuntimeError("worker result failed authentication")
    return pickle.loads(data)


def _trace_payload() -> list:
    """The worker's buffered trace events, drained for shipping.  A
    fresh worker's ring starts empty (spans clears it after fork), so
    every drain ships exactly the events produced since the previous
    task — the parent merges them into one timeline, distinguished by
    the worker's pid in each event."""
    if not spans.trace_enabled():
        return []
    return spans.drain_events()


# worker-side counter baseline: a forked child inherits the parent's
# registry values by copy-on-write, so shipping raw values would
# re-count the parent's own history — each task ships only the delta
# since the previous shipment (or the fork)
_shipped_counters: dict = {}


def _baseline_counters_after_fork() -> None:
    from . import metrics

    # after-fork hooks run in registration (= import) order, so this
    # can run BEFORE metrics' own lock-reset hook — and the inherited
    # registry lock may be held by a parent thread that doesn't exist
    # in the child.  Replace it first (idempotent; metrics' hook just
    # makes another fresh lock) instead of acquiring it and deadlocking
    metrics._new_lock_after_fork()
    _shipped_counters.clear()
    _shipped_counters.update(metrics.counters_snapshot())


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_baseline_counters_after_fork)


def _counter_payload() -> dict:
    """Counter increments this worker produced since its last shipment
    — merged into the parent's registry on collection, so worker-side
    events (a quarantined cache entry, a retried job) show up in serve
    ``stats`` and the bench chaos accounting instead of dying with the
    child's registry."""
    from . import metrics

    compiler = sys.modules.get("operator_forge.gocheck.compiler")
    if compiler is not None:
        # reconcile the compiler's lock-free registry-hit tally before
        # snapshotting, so compile.reused deltas ship with this task
        compiler.flush_counters()
    current = metrics.counters_snapshot()
    deltas = {}
    for name, value in current.items():
        previous = _shipped_counters.get(name, 0)
        if value > previous:
            deltas[name] = value - previous
    _shipped_counters.clear()
    _shipped_counters.update(current)
    return deltas


def _sealed_call(cfg: dict, fn, item, inject=()) -> tuple:
    """Worker-side task wrapper: apply the parent's shipped config,
    run, seal the outcome (plus the worker's drained trace-event
    buffer).  Task exceptions are sealed as values (not raised through
    the executor), so anything that DOES raise out of a future is, by
    construction, an infrastructure failure.

    ``inject`` is the chaos harness's per-task plan, decided in the
    parent at submission time (a retried task is a fresh submission, so
    a consumed fault never re-fires): ``worker.crash`` dies hard before
    any work or seal, ``task.hang`` sleeps past any deadline."""
    _apply_config(cfg)
    for kind in inject:
        if kind == "worker.crash":
            os._exit(23)  # a hard child death: no seal, no result
        if kind == "task.hang":
            time.sleep(_hang_seconds())
    try:
        outcome = ("ok", fn(item))
    except BaseException as exc:
        outcome = ("err", exc)
    # drained exactly once, AFTER the task ran: _trace_payload and
    # _counter_payload consume their baselines, so draining them inside
    # a seal attempt that then fails to pickle would ship a second,
    # empty drain on the err path — the task's spans and counter
    # increments would silently never reach the parent
    events = _trace_payload()
    counters = _counter_payload()
    if outcome[0] == "ok":
        try:
            return _seal(("ok", outcome[1], events, counters))
        except BaseException as exc:
            # the RESULT didn't pickle.  That is not the task's own
            # error (the task succeeded) and not a pool failure either:
            # ship it as its own kind so the parent quarantines the
            # task to threads — where the result never has to cross a
            # pickle boundary and the map can still succeed
            outcome = ("unsealable", exc)
    kind = outcome[0]  # "err" or "unsealable" from here on
    try:
        return _seal((kind, outcome[1], events, counters))
    except Exception:  # the exception itself didn't pickle
        return _seal((kind, RuntimeError(
            f"{type(outcome[1]).__name__}: {outcome[1]}"
        ), events, counters))


class _TaskFailure(Exception):
    """Parent-side wrapper distinguishing a task's own exception from
    pool infrastructure errors; map_ordered unwraps and re-raises the
    cause instead of falling back to threads."""

    def __init__(self, cause):
        super().__init__(str(cause))
        self.cause = cause


# -- pre-warm -------------------------------------------------------------


def warm_gocheck() -> None:
    """Load the gocheck surfaces every checking job needs — the stdlib
    dependency manifest, the symbol surfaces the type layer consults,
    and the parser/interpreter/compiler modules.  Called in the parent
    immediately before the process pool forks, so workers inherit the
    warm state by copy-on-write instead of each paying it again."""
    from ..gocheck import compiler, interp, parser, world  # noqa: F401
    from ..gocheck.manifest import MANIFEST  # noqa: F401  (assembles it)
    from ..gocheck.stdmanifest import symbol_surface

    for path in (
        "fmt", "strings", "context", "errors", "time", "os",
        "sigs.k8s.io/controller-runtime",
        "k8s.io/apimachinery/pkg/apis/meta/v1/unstructured",
    ):
        symbol_surface(path)


# -- the pools ------------------------------------------------------------

_pool_lock = threading.Lock()
_fan_pools: dict = {}  # max_workers -> shared fan-out ThreadPoolExecutor
_proc_pool = None
_proc_size = 0


#: strong references to discarded executors — ``(pool, manager
#: thread)`` pairs held until each manager thread has exited.  CPython
#: 3.10's ProcessPoolExecutor registers a weakref callback that
#: acquires the manager thread's shutdown_lock; the manager holds that
#: lock around its wakeup-pipe clear, which it re-enters on every
#: poll.  If the executor is garbage-collected while the manager is
#: inside that critical section (GC can run on any thread, including
#: the manager itself mid-clear), the callback deadlocks against the
#: held lock and wedges every later joiner — including interpreter
#: exit.  Holding a reference until the thread is done means the
#: callback can never fire while the lock can be held.  The thread is
#: captured eagerly because ``shutdown()`` nulls the executor's
#: ``_executor_manager_thread`` attribute immediately — so
#: :func:`_retire_pool` must run BEFORE the pool's ``shutdown()``.
_retired_pools: list = []

#: child-side keep-alive: a forked worker inherits copies of the
#: parent's executors AND (possibly) a shutdown_lock the parent's
#: manager thread held at fork time — locked forever in the child.
#: Dropping those copies would let the child's GC fire the weakref
#: callback and wedge on that dead lock, so they are kept reachable
#: for the child's lifetime instead.
_inherited_pools: list = []


def _retire_pool(pool) -> None:
    thread = getattr(pool, "_executor_manager_thread", None)
    if thread is not None and thread.is_alive():
        _retired_pools.append((pool, thread))
    _retired_pools[:] = [
        (p, t) for p, t in _retired_pools if t.is_alive()
    ]


def _forget_pools_after_fork() -> None:
    # a forked child inherits the executor objects but not their
    # threads/processes; using one would hang forever.  The lock is
    # re-created too: fork can land while another parent thread holds
    # it, and the child would inherit it locked forever
    global _proc_pool, _proc_size, _pool_lock, _active_maps_lock
    _pool_lock = threading.Lock()
    # the fair-dispatch accounting is per-process: a child forked while
    # a parent map was in flight must not inherit its count (or a
    # possibly-held lock)
    _active_maps_lock = threading.Lock()
    _active_maps[0] = 0
    _fan_pools.clear()
    if _proc_pool is not None:
        _inherited_pools.append(_proc_pool)
    _inherited_pools.extend(p for p, _t in _retired_pools)
    _retired_pools.clear()
    _proc_pool = None
    _proc_size = 0


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_forget_pools_after_fork)


def _shutdown_pools() -> None:
    # orderly teardown; letting interpreter finalization collect a live
    # ProcessPoolExecutor prints spurious weakref tracebacks.  The wait
    # is bounded: a worker hung in a task (no deadline configured) must
    # not wedge process exit — after the grace period it is terminated,
    # which also unblocks concurrent.futures' own atexit join
    global _proc_pool
    with _pool_lock:
        for pool in _fan_pools.values():
            pool.shutdown(wait=False)
        _fan_pools.clear()
        pool, _proc_pool = _proc_pool, None
        if pool is not None:
            _retire_pool(pool)  # under _pool_lock, before shutdown()
    if pool is None:
        return
    # capture the children BEFORE shutdown(): it nulls pool._processes,
    # which would make the bounded join below a silent no-op — and the
    # hung-worker wedge this exists to prevent would be back
    procs = list((getattr(pool, "_processes", None) or {}).values())
    pool.shutdown(wait=False)
    deadline = time.monotonic() + 5.0
    for proc in procs:
        try:
            proc.join(max(0.0, deadline - time.monotonic()))
        except Exception:
            pass
    for proc in procs:
        try:
            if proc.is_alive():
                proc.terminate()
        except Exception:
            pass


import atexit  # noqa: E402

atexit.register(_shutdown_pools)


def _thread_pool(jobs: int):
    """One fan-out pool per width, never shut down mid-run — like
    perf._executor, concurrent callers with different widths must not
    tear down each other's executor."""
    from concurrent.futures import ThreadPoolExecutor

    with _pool_lock:
        pool = _fan_pools.get(jobs)
        if pool is None:
            pool = _fan_pools[jobs] = ThreadPoolExecutor(
                max_workers=jobs, thread_name_prefix="operator-forge-fan"
            )
        return pool


def _process_pool():
    """The persistent worker-process pool, sized by ``n_jobs()`` (not
    by any one call's item count, so varying batch shapes keep reusing
    the same warm workers)."""
    from concurrent.futures import ProcessPoolExecutor
    import multiprocessing

    global _proc_pool, _proc_size
    jobs = n_jobs()
    with _pool_lock:
        if _proc_pool is None or _proc_size != jobs:
            if _proc_pool is not None:
                _retire_pool(_proc_pool)  # before shutdown() nulls it
                _proc_pool.shutdown(wait=False)
            # fork (not spawn): workers inherit warm module/caches state
            # and the loaded sys.modules task functions pickle against
            ctx = multiprocessing.get_context("fork")
            warm_gocheck()
            _proc_pool = ProcessPoolExecutor(
                max_workers=jobs, mp_context=ctx
            )
            _proc_size = jobs
        return _proc_pool


def _discard_process_pool() -> None:
    global _proc_pool, _proc_size
    with _pool_lock:
        if _proc_pool is not None:
            _retire_pool(_proc_pool)  # before shutdown() nulls it
            _proc_pool.shutdown(wait=False)
        _proc_pool = None
        _proc_size = 0


def _kill_process_pool() -> None:
    """Terminate the pool's worker processes and discard the pool.  A
    hung task never returns, so ``shutdown(wait=False)`` alone would
    leave its process running (and holding memory) forever — the
    deadline path needs a hard kill before the respawn."""
    global _proc_pool, _proc_size
    with _pool_lock:
        pool = _proc_pool
        _proc_pool = None
        _proc_size = 0
        if pool is not None:
            _retire_pool(pool)  # under _pool_lock, before shutdown()
    if pool is None:
        return
    for proc in list((getattr(pool, "_processes", None) or {}).values()):
        try:
            proc.terminate()
        except Exception:
            pass
    try:
        pool.shutdown(wait=False)
    except Exception:
        pass


def _infra_errors() -> tuple:
    from concurrent.futures.process import BrokenProcessPool

    # _sealed_call seals task exceptions as values, so anything raised
    # out of a future is infrastructure: a dead pool, or a task/result
    # that could not cross the pickle boundary at all.  Task-level
    # exceptions surface as _TaskFailure and re-raise as themselves.
    return (
        BrokenProcessPool, pickle.PicklingError, AttributeError,
        ImportError, EOFError, BrokenPipeError,
    )


#: the subset of infra failures (by :func:`_collect_round`'s recorded
#: type name) that are deterministic properties of the task or its
#: payload — serialization and import/attribute lookup at the pickle
#: boundary.  They fail identically on every respawn-and-rerun, unlike
#: pool deaths (BrokenProcessPool/EOFError/BrokenPipeError) and blown
#: deadlines, which retries exist for.
_NON_RETRYABLE_INFRA = ("PicklingError", "AttributeError", "ImportError")


# -- fair dispatch (PR 10) -------------------------------------------------
#
# Daemon sessions run concurrent maps over the SAME executor tier.  An
# unbounded submit of a 64-group batch would occupy every pool slot
# before a sibling session's map gets one (executor queues are FIFO),
# so when maps overlap each submits in fair-share waves: at most
# ceil(width / active_maps) futures in flight per map.  A lone map is
# unchanged — one submit, no waves.

_active_maps_lock = threading.Lock()
_active_maps = [0]


def _enter_map() -> int:
    from . import metrics

    with _active_maps_lock:
        _active_maps[0] += 1
        active = _active_maps[0]
    metrics.gauge("workers.active_maps").set(active)
    return active


def _exit_map() -> None:
    from . import metrics

    with _active_maps_lock:
        _active_maps[0] -= 1
        active = _active_maps[0]
    metrics.gauge("workers.active_maps").set(active)


def _thread_map(fn, items, jobs: int) -> list:
    # distributed tracing: the submitting thread's adopted trace
    # context travels onto the pool threads, so a traced request's
    # fan-out spans stay inside its segment (no context = no wrap)
    fn = spans.context_bound(fn)
    pool = _thread_pool(jobs)
    active = _enter_map()
    try:
        if active <= 1 or len(items) <= 1:
            futures = [pool.submit(fn, item) for item in items]
            return [future.result() for future in futures]
        # fair-share waves: concurrent maps (daemon sessions) each keep
        # at most their share of the pool in flight, so a wide batch
        # cannot monopolize the FIFO submission queue.  Results still
        # collect in input order; output is unchanged.
        share = max(1, (jobs + active - 1) // active)
        out: list = []
        for start in range(0, len(items), share):
            wave = [
                pool.submit(fn, item)
                for item in items[start:start + share]
            ]
            out.extend(future.result() for future in wave)
        return out
    finally:
        _exit_map()


def _deadline_map(fn, items, deadline: float) -> list:
    """Serial in-process execution with the per-task deadline kept: one
    daemon thread per task, joined against the deadline.  A thread
    cannot be killed, but a daemon one cannot wedge process exit either
    — the task is abandoned and the deadline surfaces as
    ``TimeoutError`` instead of the caller blocking forever on a task
    that already proved it hangs."""
    out = []
    for item in items:
        box: dict = {}

        def run(_box=box, _item=item):
            try:
                _box["out"] = fn(_item)
            except BaseException as exc:  # re-raised on the caller
                _box["exc"] = exc

        thread = threading.Thread(
            target=run, daemon=True, name="quarantined-task"
        )
        thread.start()
        thread.join(deadline)
        if thread.is_alive():
            raise TimeoutError(
                "quarantined task exceeded OPERATOR_FORGE_TASK_TIMEOUT "
                f"({deadline:g}s) in-thread"
            )
        if "exc" in box:
            raise box["exc"]
        out.append(box["out"])
    return out


def _collect_round(pool, fn, pending, site: str, deadline: float):
    """Submit one round of ``(index, item)`` tasks and collect in
    order.  Returns ``(completed, failed, task_error, broken_reason,
    unsealable)``: ``completed`` maps index -> payload, ``failed``
    lists the tasks to retry, ``task_error`` is a task's own
    (deterministic) exception — never retried — ``broken_reason`` says
    what killed the round, and ``unsealable`` lists ``(index, item,
    exc)`` tasks that SUCCEEDED in the child but whose result could not
    cross the pickle boundary (quarantine-bound: a pool re-run fails
    identically)."""
    from concurrent.futures import TimeoutError as FuturesTimeout

    from . import metrics

    cfg = _task_config()
    queue_depth = metrics.gauge("workers.queue_depth")
    metrics.counter("workers.tasks_submitted").inc(len(pending))
    queue_depth.add(len(pending))
    try:
        futures = [
            (
                index,
                item,
                pool.submit(
                    _sealed_call, cfg, fn, item,
                    faults.fire(site, "worker.crash", "task.hang"),
                ),
            )
            for index, item in pending
        ]
    except Exception as exc:
        # submission itself failed (the pool broke between creation
        # and submit): nothing ran, everything stays pending
        queue_depth.add(-len(pending))
        _discard_process_pool()
        return (
            {}, list(pending), None, f"submit: {type(exc).__name__}", []
        )
    completed: dict = {}
    failed: list = []
    unsealable: list = []
    task_error = None
    broken = None
    processed = 0
    try:
        for index, item, future in futures:
            if broken is not None or task_error is not None:
                # the pool is gone (or a task raised): the rest of the
                # round cannot be trusted to complete — but a future
                # that finished BEFORE the break still holds a good
                # sealed result; harvest it instead of re-running its
                # task next round
                harvested = False
                if task_error is None and future.done():
                    try:
                        kind, payload, events, counters = _unseal(
                            future.result(0)
                        )
                        if kind != "unsealable":
                            # see the main collection path: the
                            # in-thread re-run is authoritative
                            spans.ingest_events(events)
                            metrics.ingest_counters(counters)
                        if kind == "err":
                            task_error = _TaskFailure(payload)
                        elif kind == "unsealable":
                            unsealable.append((index, item, payload))
                        else:
                            completed[index] = payload
                            metrics.counter(
                                "workers.tasks_completed"
                            ).inc()
                        harvested = True
                    except Exception:
                        pass  # broken future: falls through to failed
                if not harvested:
                    failed.append((index, item))
                processed += 1
                queue_depth.add(-1)
                continue
            try:
                kind, payload, events, counters = _unseal(
                    future.result(deadline if deadline > 0 else None)
                )
                # merge the worker's timeline into the parent's ring:
                # one Chrome trace covers serial/thread/process runs.
                # Not for an unsealable result: its task re-runs
                # in-thread as the authoritative execution, so
                # ingesting the child's shipment too would double-count
                # the task's counters and duplicate its spans
                if kind != "unsealable":
                    spans.ingest_events(events)
                    metrics.ingest_counters(counters)
                if kind == "err":
                    task_error = _TaskFailure(payload)
                elif kind == "unsealable":
                    unsealable.append((index, item, payload))
                else:
                    completed[index] = payload
                    metrics.counter("workers.tasks_completed").inc()
            except FuturesTimeout:
                metrics.counter("worker.timeouts").inc()
                _kill_process_pool()  # a hung child must die, not linger
                failed.append((index, item))
                broken = "task deadline exceeded"
            except _infra_errors() as exc:
                _discard_process_pool()
                failed.append((index, item))
                broken = type(exc).__name__
            processed += 1
            queue_depth.add(-1)
    finally:
        # an unexpected raise (e.g. result authentication failure) must
        # not leak the unprocessed futures' depth
        queue_depth.add(-(len(futures) - processed))
    return completed, failed, task_error, broken, unsealable


def _process_map_resilient(fn, items, jobs: int, site: str) -> list:
    """The self-healing process-pool driver: submit, collect, and on
    infra failure (dead pool, blown deadline, unpicklable result)
    respawn the pool and retry only the failed tasks — bounded and
    deterministic.  Tasks that survive every retry are quarantined to
    in-thread execution; either way the caller gets the full result
    list in input order."""
    from . import metrics

    results: dict = {}
    pending = list(enumerate(items))
    retries = task_retries()
    deadline = task_timeout()
    attempt = 0
    broken = None
    # did any round actually run tasks and break?  Only then can a
    # hanger be hiding among the survivors (even a pickle-boundary
    # round may conceal one behind the first recorded breakage); a
    # pool that never started leaves every task unsuspected
    ran_and_broke = False
    while pending:
        try:
            pool = _process_pool()
        except Exception as exc:
            # fork unsupported or worker startup failed; nothing ran
            # yet, so the thread fallback below takes the whole map
            _degrade(f"pool start failed: {type(exc).__name__}: {exc}")
            break
        completed, failed, task_error, broken, unsealable = (
            _collect_round(pool, fn, pending, site, deadline)
        )
        results.update(completed)
        if task_error is not None:
            # the task's own exception, verbatim: deterministic jobs
            # fail identically on retry, so surface it immediately
            raise task_error.cause
        if unsealable:
            # the task SUCCEEDED in the child but its result cannot
            # cross the pickle boundary — a deterministic property of
            # the output, so a pool re-run fails identically.  Run it
            # in-thread, where the result never has to pickle; the task
            # provably ran to completion in the child, so it is not a
            # hang suspect and needs no deadline
            sample = unsealable[0][2]
            metrics.counter("worker.quarantined").inc(len(unsealable))
            _degrade(
                f"{len(unsealable)} task(s) returned results that "
                "cannot cross the pickle boundary "
                f"({type(sample).__name__}: {sample}); quarantined to "
                "in-thread execution"
            )
            outputs = _thread_map(
                fn, [item for _index, item, _exc in unsealable],
                max(1, min(jobs, len(unsealable))),
            )
            for (index, _item, _exc), output in zip(unsealable, outputs):
                results[index] = output
        if not failed:
            pending = []
            break
        pending = failed
        ran_and_broke = True
        if broken in _NON_RETRYABLE_INFRA:
            # serialization / import-lookup failures at the pickle
            # boundary are deterministic properties of the task or its
            # payload: every respawn-and-rerun fails identically, so
            # burning the retry budget (pool forks, backoff sleeps,
            # full re-execution) is pure waste — quarantine now
            metrics.counter("worker.quarantined").inc(len(pending))
            _degrade(
                f"{len(pending)} task(s) failed at the pickle boundary "
                f"({broken}); quarantined to in-thread execution"
            )
            break
        attempt += 1
        if attempt > retries:
            metrics.counter("worker.quarantined").inc(len(pending))
            _degrade(
                f"{len(pending)} task(s) unrecovered after {retries} "
                f"retr{'y' if retries == 1 else 'ies'} ({broken}); "
                "quarantined to in-thread execution"
            )
            break
        metrics.counter("worker.retries").inc(len(failed))
        metrics.counter("worker.respawns").inc()
        time.sleep(_BACKOFF_S * attempt)  # deterministic, no jitter
    if pending:
        # poison-task quarantine / degraded fallback: the survivors run
        # on threads in this process — deterministic and idempotent, so
        # output is identical, just without multicore scaling.  When a
        # round actually ran and broke, a deadline (if configured) is
        # kept — regardless of what broke the final round: a crashing
        # sibling can report the round as BrokenProcessPool while a
        # survivor is still the hanger, and an unbounded fallback would
        # wedge this thread forever, the exact dead loop the deadline
        # exists to prevent — so a hang surfaces as TimeoutError.  A
        # pool that never STARTED is different: no task ever ran, none
        # is suspect, and the serial deadline map would silently turn
        # an N-way batch into 1-way — that path keeps the parallel
        # thread fallback (the thread backend's own semantics, which
        # never applies the per-task deadline)
        if deadline > 0 and ran_and_broke:
            outputs = _deadline_map(
                fn, [item for _index, item in pending], deadline
            )
        else:
            outputs = _thread_map(
                fn, [item for _index, item in pending],
                max(1, min(jobs, len(pending))),
            )
        for (index, _item), output in zip(pending, outputs):
            results[index] = output
    return [results[index] for index in range(len(items))]


def map_ordered(fn, items, site: str = "task") -> list:
    """Ordered map over ``items`` through the selected backend.

    ``fn`` must be a module-level callable and ``items`` picklable when
    the ``process`` backend is active (they cross the fork boundary);
    the ``thread``/serial paths have no such requirement.  One job (or
    one item) short-circuits to the plain serial loop.  ``site`` names
    this map's fault-injection site (see
    :mod:`operator_forge.perf.faults`); worker-directed faults are
    planned per submission in the parent, so they only apply to the
    ``process`` backend.
    """
    items = list(items)
    jobs = min(n_jobs(), len(items))
    if jobs <= 1 or len(items) <= 1:
        return [fn(item) for item in items]
    if backend() == "process":
        return _process_map_resilient(fn, items, jobs, site)
    return _thread_map(fn, items, jobs)
