"""Shared remote artifact cache: content-addressed cache server plus
the read-through/write-behind client tier (PR 9).

The disk cache (:mod:`operator_forge.perf.cache`) is content-addressed
and HMAC-signed, but it is one process tree's private store: a second
worker, a CI shard, or a freshly spawned process starts stone cold and
pays the full recompute the local tiers eliminated.  This module adds
the go-build-cache/Bazel-style remote tier:

- **server** — ``operator-forge cache-server --listen <addr>`` runs a
  small content-addressed store speaking a length-prefixed
  get/put-by-(stage, key) binary protocol over a unix socket or TCP.
  It is backed by the existing disk-store layout
  (``<root>/<stage>/<key[:2]>/<key>.pkl``) including the LRU
  ``_maybe_gc`` pruning, serves N concurrent clients (thread per
  connection), and treats every blob as *opaque signed bytes*: like a
  Bazel remote CAS it never unpickles and never needs the signing key
  — client-side HMAC verification is the trust boundary.
- **client** — with ``OPERATOR_FORGE_REMOTE_CACHE=<addr>`` set, the
  local :class:`~operator_forge.perf.cache.ContentCache` becomes a
  three-tier read-through hierarchy (mem → disk → remote): a remote
  hit is HMAC-verified with the *local* key before it is ever
  unpickled (a blob signed by any other key is rejected, counted, and
  recomputed — the PR 7 quarantine rule: unauthenticated bytes are
  never unpickled) and then populates the local tiers; puts go through
  a bounded write-behind queue (batched uploads off the hot path,
  drop-with-counter on backlog, flushed at exit); and a per-run
  negative-lookup memo caps each missing key at one round trip.

The tier inherits the PR 7 robustness contract end to end: connect and
read deadlines (``OPERATOR_FORGE_REMOTE_TIMEOUT``), a bounded
deterministic retry budget (``OPERATOR_FORGE_REMOTE_RETRIES``), and a
sticky one-shot-warned degrade-to-local (``cache.remote_degraded``
gauge) once the budget is exhausted — a dead, slow, or lying server
can only ever cost latency, never correctness.  The planted fault
sites (``remote.unreachable`` / ``remote.corrupt`` / ``remote.hang``
at site ``remote``, see :mod:`operator_forge.perf.faults`) let the
chaos harness prove it deterministically.

Wire protocol (version 1)::

    frame    := u32_be(len(body)) body          # len bounded by MAX_FRAME
    request  := op(1) [u8 len stage] [u8 len key] [payload]
    op       := "G" (get) | "P" (put, payload = signed blob) | "H" (ping)
    response := status(1) [payload]
    status   := "H" (hit, payload = signed blob) | "M" (miss)
              | "O" (put stored) | "P" (pong) | "E" (error, payload = msg)

A frame announcing more than ``MAX_FRAME`` bytes is rejected and the
connection closed (the oversized-payload guard); a torn or short frame
is a protocol error, never a partial read silently treated as data.
"""

from __future__ import annotations

import collections
import os
import socket
import struct
import tempfile
import threading
import time

from . import env_number
from . import cache as pf_cache
from . import faults
from .netaddr import bind_listener, bound_address, connect_stream
from .netaddr import parse_listen  # noqa: F401  (re-export: PR 9 surface)

ENV_ADDR = "OPERATOR_FORGE_REMOTE_CACHE"

#: hard ceiling on one frame body — an announced length above this is a
#: protocol violation (oversized payload), not a large entry
MAX_FRAME = 64 * 1024 * 1024
#: write-behind upload batch size: one drained slice per flusher wake
_PUT_BATCH = 32
#: deterministic backoff step between retry attempts (seconds)
_BACKOFF_S = 0.05

DEFAULT_TIMEOUT_S = 2.0
DEFAULT_RETRIES = 1
DEFAULT_QUEUE_DEPTH = 256
DEFAULT_IDLE_S = 300.0

_OPS = (b"G", b"P", b"H")


def timeout_s() -> float:
    """Connect/read deadline per remote round trip
    (``OPERATOR_FORGE_REMOTE_TIMEOUT``, seconds, default 2.0)."""
    return env_number(
        "OPERATOR_FORGE_REMOTE_TIMEOUT", DEFAULT_TIMEOUT_S, minimum=0.05
    )


def retries() -> int:
    """Bounded deterministic retry budget per round trip
    (``OPERATOR_FORGE_REMOTE_RETRIES``, default 1)."""
    return env_number(
        "OPERATOR_FORGE_REMOTE_RETRIES", DEFAULT_RETRIES, cast=int
    )


def queue_depth() -> int:
    """Write-behind queue bound (``OPERATOR_FORGE_REMOTE_QUEUE``,
    default 256 pending uploads; overflow drops with a counter)."""
    return env_number(
        "OPERATOR_FORGE_REMOTE_QUEUE", DEFAULT_QUEUE_DEPTH,
        cast=int, minimum=1,
    )


def idle_timeout_s() -> float:
    """Server-side idle read deadline per connection
    (``OPERATOR_FORGE_CACHE_SERVER_IDLE_S``, default 300s; <= 0
    disables).  A client that connects and goes silent previously held
    its handler thread forever — a slow but unbounded leak under
    connection churn; past the deadline the server answers the
    standard ``E`` response and closes that one connection.  The
    default is generous: a healthy client's requests are milliseconds
    apart, and a client whose pooled connection is idle-closed simply
    reconnects on its next round trip (the bounded-retry path)."""
    return env_number(
        "OPERATOR_FORGE_CACHE_SERVER_IDLE_S", DEFAULT_IDLE_S,
        minimum=None,
    )


# -- framing ---------------------------------------------------------------


def _recv_exact(sock, n: int) -> bytes:
    """Read exactly ``n`` bytes; a connection that ends early raises
    ``ConnectionError`` (a torn frame is an error, never data)."""
    chunks = []
    remaining = n
    while remaining > 0:
        chunk = sock.recv(min(remaining, 1 << 16))
        if not chunk:
            raise ConnectionError("connection closed mid-frame")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def _send_frame(sock, body: bytes) -> None:
    sock.sendall(struct.pack("!I", len(body)) + body)


def _recv_frame(sock) -> bytes:
    header = _recv_exact(sock, 4)
    (length,) = struct.unpack("!I", header)
    if length == 0 or length > MAX_FRAME:
        raise ProtocolError(f"frame length {length} outside (0, MAX_FRAME]")
    return _recv_exact(sock, length)


class ProtocolError(Exception):
    """A malformed, oversized, or torn protocol frame."""


def _valid_stage(stage: str) -> bool:
    if not stage or len(stage) > 128:
        return False
    if not all(c.isalnum() or c in "._-" for c in stage):
        return False
    # the stage becomes one path component under the store root: "."
    # and ".." would escape it (path traversal on a network-facing
    # server), and the quarantine dir is not addressable as a namespace
    # (gc deliberately skips it — a planted entry would never be
    # evicted or accounted)
    return stage not in (".", "..", pf_cache.QUARANTINE_DIRNAME)


def _valid_key(key: str) -> bool:
    if not key or len(key) > 128:
        return False
    return all(c in "0123456789abcdef" for c in key)


def _pack_entry(op: bytes, stage: str, key: str, payload: bytes = b"") -> bytes:
    stage_b = stage.encode("utf-8")
    key_b = key.encode("ascii")
    return (
        op + bytes([len(stage_b)]) + stage_b + bytes([len(key_b)]) + key_b
        + payload
    )


def _unpack_entry(body: bytes):
    """``(op, stage, key, payload)`` from a request body; raises
    :class:`ProtocolError` on any truncation or bad field."""
    if not body:
        raise ProtocolError("empty frame")
    op = body[:1]
    if op not in _OPS:
        raise ProtocolError(f"unknown op {op!r}")
    if op == b"H":
        return op, "", "", b""
    try:
        i = 1
        stage_len = body[i]
        i += 1
        stage = body[i:i + stage_len].decode("utf-8")
        if len(body) < i + stage_len + 1:
            raise ProtocolError("short frame: truncated stage/key")
        i += stage_len
        key_len = body[i]
        i += 1
        key = body[i:i + key_len].decode("ascii")
        if len(key) != key_len:
            raise ProtocolError("short frame: truncated key")
        i += key_len
        payload = body[i:]
    except (IndexError, UnicodeDecodeError) as exc:
        raise ProtocolError(f"malformed frame: {exc}") from None
    if not _valid_stage(stage):
        raise ProtocolError(f"invalid stage {stage!r}")
    if not _valid_key(key):
        raise ProtocolError(f"invalid key {key!r}")
    return op, stage, key, payload


# -- server ----------------------------------------------------------------


class CacheServer:
    """A content-addressed cache server over the disk-store layout.

    Blobs are stored and served as the opaque HMAC-signed bytes the
    clients produce; the server itself never unpickles (and does not
    need the signing key — verification is client-side, like a Bazel
    remote CAS).  The store honors the same LRU ceiling as the local
    disk tier (``OPERATOR_FORGE_CACHE_MAX_MB`` via
    :meth:`ContentCache._maybe_gc`), so a long-lived server prunes
    least-recently-fetched entries instead of growing forever."""

    def __init__(self, listen: str, root: str | None = None):
        self.spec = parse_listen(listen)
        self.store = pf_cache.ContentCache()
        self.store.configure(
            mode="disk",
            root=root
            or os.environ.get("OPERATOR_FORGE_CACHE_DIR")
            or pf_cache.DEFAULT_DIR,
        )
        self._listener = None
        self._accept_thread = None
        self._conns: set = set()
        self._lock = threading.Lock()
        self._closing = False

    # the actual bound address (resolves TCP port 0)
    def address(self) -> str:
        return bound_address(self.spec, self._listener)

    def start(self) -> None:
        """Bind and serve in a background accept thread (embedded use:
        tests, bench).  The CLI uses :meth:`serve_forever` instead."""
        self._bind()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="operator-forge-cache-server",
        )
        self._accept_thread.start()

    def _bind(self) -> None:
        self._listener = bind_listener(self.spec, backlog=64)

    def serve_forever(self) -> None:
        """Blocking accept loop (the CLI path); :meth:`stop` from a
        signal handler breaks it."""
        if self._listener is None:
            self._bind()
        self._accept_loop()

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed: shutting down
            with self._lock:
                if self._closing:
                    conn.close()
                    return
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="operator-forge-cache-conn",
            ).start()

    def stop(self) -> None:
        self._closing = True
        try:
            # closing an fd does NOT wake a thread parked in accept()
            # on Linux — shutdown the listening socket first so the
            # embedded accept thread unblocks and exits (join below)
            self._listener.shutdown(socket.SHUT_RDWR)
        except (OSError, AttributeError):
            pass
        try:
            self._listener.close()
        except (OSError, AttributeError):
            pass
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self.spec[0] == "unix":
            try:
                os.unlink(self.spec[1])
            except OSError:
                pass
        thread = self._accept_thread
        if thread is not None and thread.is_alive():
            thread.join(2.0)

    # -- per-connection protocol ---------------------------------------

    def _serve_conn(self, conn) -> None:
        from . import metrics

        idle = idle_timeout_s()
        if idle > 0:
            # the idle read deadline: a silent client must not hold
            # this handler thread forever (it also bounds a peer
            # trickling one frame byte-by-byte)
            try:
                conn.settimeout(idle)
            except OSError:
                pass
        try:
            while not self._closing:
                try:
                    body = _recv_frame(conn)
                except socket.timeout:
                    # idle past the deadline: answer once with the
                    # standard error response, close THIS connection
                    metrics.counter("cache_server.idle_closed").inc()
                    self._respond_error(
                        conn,
                        f"idle connection closed after {idle:g}s "
                        "without a complete frame",
                    )
                    return
                except ConnectionError:
                    return  # clean EOF or torn frame: drop the conn
                except ProtocolError as exc:
                    # oversized/zero-length announcement: answer once,
                    # then close — the byte stream can no longer be
                    # trusted to frame correctly
                    self._respond_error(conn, str(exc))
                    return
                try:
                    op, stage, key, payload = _unpack_entry(body)
                except ProtocolError as exc:
                    self._respond_error(conn, str(exc))
                    return
                if op == b"H":
                    _send_frame(conn, b"P")
                    continue
                if op == b"G":
                    metrics.counter("cache_server.gets").inc()
                    data = self._read(stage, key)
                    if data is None:
                        _send_frame(conn, b"M")
                    else:
                        metrics.counter("cache_server.hits").inc()
                        _send_frame(conn, b"H" + data)
                    continue
                # op == b"P"
                metrics.counter("cache_server.puts").inc()
                self._write(stage, key, payload)
                _send_frame(conn, b"O")
        except OSError:
            pass  # client went away mid-write; nothing to clean up
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _respond_error(self, conn, message: str) -> None:
        try:
            _send_frame(conn, b"E" + message.encode("utf-8", "replace"))
        except OSError:
            pass

    def _read(self, stage: str, key: str):
        path = self.store._disk_path(stage, key)
        try:
            with open(path, "rb") as handle:
                data = handle.read()
        except OSError:
            return None
        try:
            # LRU freshness, same reason as the local disk tier: Get
            # marks the entry used so eviction stays least-recently-USED
            os.utime(path)
        except OSError:
            pass
        return data

    def _write(self, stage: str, key: str, data: bytes) -> None:
        path = self.store._disk_path(stage, key)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
            with os.fdopen(fd, "wb") as handle:
                handle.write(data)
            os.replace(tmp, path)
        except OSError:
            return  # best-effort store, like the local disk tier
        self.store._maybe_gc(len(data))


def serve_cache(listen: str, root=None, max_mb=None) -> int:
    """The ``operator-forge cache-server`` entry point: bind, print one
    status line on stderr, and serve until SIGTERM/SIGINT."""
    import signal
    import sys

    if max_mb is not None:
        # the store's LRU ceiling reads the env knob; a CLI override is
        # just a process-local env pin
        os.environ["OPERATOR_FORGE_CACHE_MAX_MB"] = str(max_mb)
    server = CacheServer(listen, root=root)
    server._bind()
    print(
        f"cache-server: listening on {server.address()} "
        f"(store {server.store.root()})",
        file=sys.stderr, flush=True,
    )
    stopped = []

    def _stop(signum=None, frame=None):
        if not stopped:
            stopped.append(True)
            server.stop()

    try:
        signal.signal(signal.SIGTERM, _stop)
        signal.signal(signal.SIGINT, _stop)
    except ValueError:
        pass  # not the main thread (embedded): stop() is the handle
    server.serve_forever()
    return 0


# -- client ----------------------------------------------------------------
#
# Process-global client state, fork-reset like every other perf
# singleton: a forked pool child drops the inherited connection and
# write-behind queue (the parent owns and flushes its own) and lazily
# reconnects on first use.

_forced_addr = None  # programmatic override ("" disables, None = env)
_lock = threading.Lock()
_conn = [None]          # the synchronous GET connection
_negative: set = set()  # (stage, key) pairs known absent this run
_queue = collections.deque()
_queue_cond = threading.Condition()
_inflight = [0]
_flusher = [None]
_degraded = {"active": False, "reason": ""}
_warned_once = [False]
_hooked = [False]


def configure(addr=None) -> None:
    """Programmatic address override (``None`` restores env selection,
    ``""`` disables).  Clears the negative memo and the degraded state
    so a test or bench leg starts each configuration fresh."""
    global _forced_addr
    with _lock:
        _forced_addr = addr
        _close_conn_locked()
    with _queue_cond:
        _queue.clear()
        _queue_cond.notify_all()
    _negative.clear()
    reset_degraded()


def _addr_text():
    if _forced_addr is not None:
        return _forced_addr or None
    raw = os.environ.get(ENV_ADDR, "").strip()
    return raw or None


def configured() -> bool:
    return _addr_text() is not None


def active() -> bool:
    """Whether the remote tier participates in cache lookups right
    now: an address is configured, the client has not degraded, and a
    local signing key exists (without one, nothing fetched could ever
    be verified, and nothing stored could be signed)."""
    if _degraded["active"]:
        return False
    if _addr_text() is None:
        return False
    return pf_cache._load_hmac_key() is not None


def state() -> dict:
    """The remote-tier surface serve ``stats`` reports."""
    with _queue_cond:
        pending = len(_queue) + _inflight[0]
    return {
        "configured": configured(),
        "addr": _addr_text(),
        "active": active(),
        "degraded": _degraded["active"],
        "degraded_reason": _degraded["reason"],
        "queue_pending": pending,
    }


def reset_degraded() -> None:
    """Clear the sticky degrade-to-local record (tests, or an operator
    after reviving the server); the one-shot warning stays one-shot."""
    _degraded["active"] = False
    _degraded["reason"] = ""


def _degrade(reason: str) -> None:
    from . import metrics

    import sys

    _degraded["active"] = True
    _degraded["reason"] = reason
    # lazily (re)registered: conftest's metrics.reset() drops the
    # registration, so bind it when it first becomes meaningful
    metrics.register_gauge(
        "cache.remote_degraded", lambda: 1 if _degraded["active"] else 0
    )
    metrics.counter("cache.remote_degrade_events").inc()
    if not _warned_once[0]:
        _warned_once[0] = True
        # the REAL stderr: captured job output must stay byte-identical
        # to a run with a healthy remote
        stream = sys.__stderr__ or sys.stderr
        print(
            "operator-forge: remote cache degraded to local tiers: "
            f"{reason} (this warning prints once)",
            file=stream,
        )


def _ensure_reset_hook() -> None:
    # the negative-lookup memo is per-run: a ContentCache.reset() (the
    # "new run" boundary every bench leg and test uses) clears it
    if not _hooked[0]:
        _hooked[0] = True
        pf_cache.get_cache().reset_hooks.append(_negative.clear)


def _close_conn_locked() -> None:
    conn = _conn[0]
    _conn[0] = None
    if conn is not None:
        try:
            conn.close()
        except OSError:
            pass


def _connect():
    addr = _addr_text()
    if addr is None:
        # deconfigured between the caller's active() check and here (a
        # test or bench leg flipping configuration): a plain transport
        # failure, handled by the normal retry/drop paths
        raise ConnectionError("remote cache not configured")
    return connect_stream(addr, timeout=timeout_s())


def _roundtrip_locked(body: bytes):
    """One request/response on the shared GET connection (caller holds
    ``_lock``); raises on any transport or protocol failure."""
    if _conn[0] is None:
        _conn[0] = _connect()
    sock = _conn[0]
    try:
        _send_frame(sock, body)
        return _recv_frame(sock)
    except BaseException:
        _close_conn_locked()
        raise


def _request(body: bytes, injected=()):
    """A bounded-deterministic-retry round trip.  Returns the response
    body, or ``None`` after the retry budget is exhausted (the caller
    degrades).  ``injected`` carries this call's chaos plan."""
    from . import metrics

    budget = retries() + 1
    hang_pending = "remote.hang" in injected
    for attempt in range(budget):
        if attempt:
            time.sleep(_BACKOFF_S * attempt)  # deterministic, no jitter
        try:
            if "remote.unreachable" in injected:
                raise ConnectionRefusedError(
                    "injected fault: remote.unreachable"
                )
            if hang_pending:
                # a hung server: the read deadline trips.  The sleep is
                # paid once (bounded by the configured timeout); the
                # remaining attempts fail fast so the injected hang
                # deterministically exhausts the budget
                hang_pending = False
                time.sleep(timeout_s())
                raise socket.timeout("injected fault: remote.hang")
            if "remote.hang" in injected:
                raise socket.timeout("injected fault: remote.hang")
            with _lock:
                response = _roundtrip_locked(body)
        except (OSError, ProtocolError) as exc:
            metrics.counter("cache.remote_errors").inc()
            last = f"{type(exc).__name__}: {exc}"
            continue
        if response[:1] == b"E":
            # the server answered but rejected the request (protocol
            # error taxonomy): not retryable, and not worth degrading
            # the whole tier over one entry
            metrics.counter("cache.remote_errors").inc()
            return None
        return response
    _degrade(
        f"{budget} attempt(s) failed ({last}); continuing on local tiers"
    )
    return None


def fetch(stage: str, key: str):
    """Read-through fetch: the verified *pickle* bytes for
    ``(stage, key)`` — signature already stripped — or ``None`` on
    miss/corruption/degrade.  Never unpickles; never raises."""
    from . import metrics

    if not active():
        return None
    _ensure_reset_hook()
    if (stage, key) in _negative:
        return None
    signing_key = pf_cache._load_hmac_key()
    injected = faults.fire(
        "remote", "remote.unreachable", "remote.corrupt", "remote.hang"
    )
    # a span around the round trip: the remote tier's latency joins a
    # traced request's timeline (and, inside a daemon handling a
    # distributed-trace request, its segment) — the cache server
    # itself stays span-free, its whole visible cost IS this round
    # trip.  One attr lookup when telemetry is off.
    from . import spans

    with spans.span("remote.get", args={"stage": stage}):
        response = _request(_pack_entry(b"G", stage, key), injected)
    if response is None:
        return None
    status, payload = response[:1], response[1:]
    if status == b"M":
        metrics.counter("cache.remote_misses").inc()
        _negative.add((stage, key))
        return None
    if status != b"H":
        metrics.counter("cache.remote_errors").inc()
        return None
    if "remote.corrupt" in injected and payload:
        # deterministic stand-in for a lying/bit-rotted server: flip
        # the last byte so verification must reject it
        payload = payload[:-1] + bytes([payload[-1] ^ 0xFF])
    import hmac as _hmac

    if len(payload) <= pf_cache._SIG_BYTES or not _hmac.compare_digest(
        payload[: pf_cache._SIG_BYTES],
        pf_cache._sign(signing_key, payload[pf_cache._SIG_BYTES:]),
    ):
        # wrong key, tampered, or truncated: rejected BEFORE unpickling
        # (the quarantine rule), counted globally and per namespace,
        # and memoized so the bad entry costs one round trip per run
        metrics.counter("cache.remote_corrupt").inc()
        pf_cache.get_cache()._count(stage, "remote_corrupt")
        _negative.add((stage, key))
        return None
    metrics.counter("cache.remote_hits").inc()
    return payload[pf_cache._SIG_BYTES:]


# -- write-behind ----------------------------------------------------------


def enqueue_put(stage: str, key: str, blob: bytes) -> None:
    """Queue an upload; never blocks the hot path.  The HMAC signing
    happens in the flusher thread (the local disk tier already signed
    its own copy — re-hashing a multi-MB blob here would put the
    redundant work back on the path the queue exists to keep clear).
    On backlog (``OPERATOR_FORGE_REMOTE_QUEUE`` deep) the NEW entry is
    dropped with a counter — a slow server sheds uploads, it does not
    stall puts."""
    from . import metrics

    if not active():
        return
    if len(blob) + pf_cache._SIG_BYTES + 256 > MAX_FRAME:
        metrics.counter("cache.remote_queue_dropped").inc()
        return
    _ensure_reset_hook()
    with _queue_cond:
        if len(_queue) >= queue_depth():
            metrics.counter("cache.remote_queue_dropped").inc()
            return
        _queue.append((stage, key, blob))
        # a remote put supersedes any recorded miss for the key (the
        # local tiers will answer first anyway, but keep the memo
        # honest for the next process-wide reset boundary)
        _negative.discard((stage, key))
        _queue_cond.notify()
    _ensure_flusher()


def _ensure_flusher() -> None:
    thread = _flusher[0]
    if thread is not None and thread.is_alive():
        return
    with _queue_cond:
        thread = _flusher[0]
        if thread is not None and thread.is_alive():
            return
        thread = threading.Thread(
            target=_flush_loop, daemon=True,
            name="operator-forge-remote-flusher",
        )
        _flusher[0] = thread
    thread.start()


def _flush_loop() -> None:
    from . import metrics

    sock = None
    while True:
        with _queue_cond:
            while not _queue:
                _queue_cond.wait(0.25)
            batch = [
                _queue.popleft()
                for _ in range(min(len(_queue), _PUT_BATCH))
            ]
            _inflight[0] += len(batch)
        try:
            if not active():
                metrics.counter("cache.remote_queue_dropped").inc(
                    len(batch)
                )
                continue
            for stage, key, blob in batch:
                if not active():
                    # configuration flipped mid-batch: shed, don't warn
                    metrics.counter("cache.remote_queue_dropped").inc()
                    continue
                # signed here, off the hot path (active() guarantees a
                # key exists; a concurrent flip is a normal send error)
                signing_key = pf_cache._load_hmac_key()
                if signing_key is None:
                    metrics.counter("cache.remote_queue_dropped").inc()
                    continue
                data = pf_cache._sign(signing_key, blob) + blob
                sent = False
                budget = retries() + 1
                from . import spans

                # the flusher runs decoupled from any request, so the
                # span is untagged — it lands in the flight ring (and a
                # trace-wrapped process's timeline), attributing
                # write-behind latency without joining a segment
                with spans.span("remote.put", args={"stage": stage}):
                    for attempt in range(budget):
                        if attempt:
                            time.sleep(_BACKOFF_S * attempt)
                        try:
                            if sock is None:
                                sock = _connect()
                            _send_frame(
                                sock, _pack_entry(b"P", stage, key, data)
                            )
                            response = _recv_frame(sock)
                        except (OSError, ProtocolError) as exc:
                            metrics.counter("cache.remote_errors").inc()
                            last = f"{type(exc).__name__}: {exc}"
                            if sock is not None:
                                try:
                                    sock.close()
                                except OSError:
                                    pass
                                sock = None
                            continue
                        if response[:1] == b"O":
                            metrics.counter("cache.remote_puts").inc()
                            sent = True
                        else:
                            metrics.counter("cache.remote_errors").inc()
                        break
                if not sent and sock is None:
                    # transport-level exhaustion: the tier degrades and
                    # the remaining backlog drains as drops
                    _degrade(
                        f"write-behind upload failed ({last}); "
                        "continuing on local tiers"
                    )
                    metrics.counter("cache.remote_queue_dropped").inc()
        finally:
            with _queue_cond:
                _inflight[0] -= len(batch)
                _queue_cond.notify_all()


def flush(deadline_s=None) -> bool:
    """Drain the write-behind queue (bounded wait); returns whether it
    fully drained.  Called at process exit so a short-lived CLI's warm
    artifacts actually reach the shared tier."""
    if deadline_s is None:
        deadline_s = max(1.0, 2 * timeout_s())
    if _degraded["active"] or _addr_text() is None:
        return not _queue
    _ensure_flusher()
    end = time.monotonic() + deadline_s
    with _queue_cond:
        while _queue or _inflight[0]:
            remaining = end - time.monotonic()
            if remaining <= 0:
                return False
            _queue_cond.wait(min(remaining, 0.25))
    return True


def _flush_at_exit() -> None:
    try:
        if _queue or _inflight[0]:
            flush()
    except Exception:
        pass  # exit paths never raise over a best-effort drain


import atexit  # noqa: E402

atexit.register(_flush_at_exit)


def _reset_after_fork() -> None:
    # a forked pool child inherits the parent's connection (sharing it
    # would interleave two processes' frames on one stream) and queue
    # (the parent flushes its own); drop both, re-create the locks
    # (fork can land while a parent thread holds one), and let the
    # child lazily reconnect.  The degraded flag is inherited: if the
    # parent already proved the server dead, children skip re-proving.
    global _lock, _queue_cond
    _lock = threading.Lock()
    _queue_cond = threading.Condition()
    _conn[0] = None
    _queue.clear()
    _inflight[0] = 0
    _flusher[0] = None


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_after_fork)
