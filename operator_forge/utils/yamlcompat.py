"""libyaml-backed safe_load/safe_dump with pure-Python fallback.

PyYAML's pure-Python emitter dominates generation profiles (a third of
`create api` wall time goes to serializing CRD YAML); the C variants cut
that roughly 5x.  Mirrors yamldoc/load.py, which already prefers the C
parser for manifest loading.
"""

from __future__ import annotations

import yaml as _yaml

_SAFE_LOADER = getattr(_yaml, "CSafeLoader", _yaml.SafeLoader)
_SAFE_DUMPER = getattr(_yaml, "CSafeDumper", _yaml.SafeDumper)

# error type passthrough so callers can except pyyaml.YAMLError
YAMLError = _yaml.YAMLError


def safe_load(stream):
    return _yaml.load(stream, Loader=_SAFE_LOADER)


def safe_load_all(stream):
    return _yaml.load_all(stream, Loader=_SAFE_LOADER)


def safe_dump(data, stream=None, **kwargs):
    return _yaml.dump(data, stream, Dumper=_SAFE_DUMPER, **kwargs)
