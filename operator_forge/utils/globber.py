"""File-glob expansion with double-star support.

Reference: internal/utils/files.go:32-104.  Behavioral contract:
- a plain path (no glob chars) must exist, otherwise it is an error
  ("file ... defined in spec.resources cannot be found");
- a single-star glob with zero matches is an error;
- ``**`` recurses through directories (matches files at any depth);
- results are deduplicated, directories matched by a pattern are walked so
  their files are included.
"""

from __future__ import annotations

import glob as _glob
import os


class GlobError(Exception):
    """Raised when a resource path or glob cannot be resolved."""


def _walk_all(path: str) -> list[str]:
    """Return path plus, when it is a directory, everything beneath it."""
    if not os.path.isdir(path):
        return [path]
    hits = [path]
    for root, dirs, files in os.walk(path):
        for name in sorted(dirs) + sorted(files):
            hits.append(os.path.join(root, name))
    return hits


def glob_files(pattern: str) -> list[str]:
    """Expand ``pattern`` into matching paths (files and directories)."""
    if "**" not in pattern:
        if "*" not in pattern and not os.path.exists(pattern):
            raise GlobError(
                f"file {pattern} defined in spec.resources cannot be found"
            )
        matches = sorted(_glob.glob(pattern))
        if not matches:
            raise GlobError(
                f"unable to find any files from glob pattern {pattern}"
            )
        return matches

    # double-star: expand segment by segment, walking matched directories
    segments = pattern.split("**")
    matches = [""]
    for segment in segments:
        hits: list[str] = []
        seen: set[str] = set()
        for match in matches:
            for path in sorted(_glob.glob(match + segment)):
                for hit in _walk_all(path):
                    if hit not in seen:
                        seen.add(hit)
                        hits.append(hit)
        matches = hits
    return matches


def glob_manifest_files(pattern: str) -> list[str]:
    """Like :func:`glob_files` but keeps only regular files.

    Manifest expansion (reference internal/workload/v1/manifests/manifest.go:
    32-53) only loads file content, so directories picked up by a double-star
    walk are filtered here.
    """
    return [p for p in glob_files(pattern) if os.path.isfile(p)]
