"""Name-casing helpers.

Reference: internal/utils/names.go:12-43.  Behavioral contract:
- ``to_pascal_case("my-app") == "MyApp"`` (kebab-case -> Go identifier)
- ``to_file_name("my-app") == "my_app"`` (kebab-case -> snake_case filename)
- ``to_package_name("my-app") == "myapp"`` (kebab-case -> go package name)
- ``to_title``/``title_words`` mirror Go's deprecated ``strings.Title``:
  uppercase the first letter of every word, leaving the rest of each word
  untouched (NOT Python's ``str.title()``, which lowercases the tail).
"""

from __future__ import annotations

import functools

# every helper is memoized: they are pure string->string maps called once
# per field per template, and real configs reuse a small set of names


def _memo_str(fn):
    """``lru_cache`` that only caches exact-``str`` arguments.

    ``str`` subclasses hash and compare equal to their plain value, so a
    vanilla ``lru_cache`` would serve a cached plain result to (or cache a
    result from) an instrumented string such as the render-lowering probes
    in ``scaffold/render.py`` — silently erasing the instrumentation.
    Subclass inputs bypass the cache and run the raw function instead.
    """
    cached = functools.lru_cache(maxsize=None)(fn)

    @functools.wraps(fn)
    def wrapper(*args):
        if all(type(a) is str for a in args):
            return cached(*args)
        return fn(*args)

    wrapper.cache_clear = cached.cache_clear
    wrapper.cache_info = cached.cache_info
    return wrapper


@_memo_str
def to_title(s: str) -> str:
    """Uppercase the first letter of each space/punctuation-separated word.

    Mirrors Go ``strings.Title`` semantics used throughout the reference for
    identifier derivation (e.g. internal/workload/v1/markers/markers.go:185).
    Word boundaries are any non-letter, non-digit characters; the remainder of
    each word is preserved as-is.
    """
    out = []
    prev_is_word = False
    for ch in s:
        if ch.isalpha():
            out.append(ch.upper() if not prev_is_word else ch)
            prev_is_word = True
        elif ch.isdigit():
            out.append(ch)
            prev_is_word = True
        else:
            out.append(ch)
            prev_is_word = False
    return "".join(out)


@_memo_str
def title_words(s: str, seps: str = ".-_ :") -> str:
    """Title-case ``s`` and drop the separator characters.

    Used to build Go identifiers out of dotted marker paths, e.g.
    ``"webstore.really.long.path" -> "WebstoreReallyLongPath"``.
    """
    result = to_title(s)
    for sep in seps:
        result = result.replace(sep, "")
    return result


@_memo_str
def to_pascal_case(name: str) -> str:
    """kebab-case -> PascalCase (reference internal/utils/names.go:12-31)."""
    out = []
    make_upper = True
    for letter in name:
        if make_upper:
            out.append(letter.upper())
            make_upper = False
        elif letter == "-":
            make_upper = True
        else:
            out.append(letter)
    return "".join(out)


@_memo_str
def to_file_name(name: str) -> str:
    """kebab-case -> snake_case (reference internal/utils/names.go:33-37)."""
    return name.replace("-", "_").lower()


@_memo_str
def to_package_name(name: str) -> str:
    """kebab-case -> flat lowercase (reference internal/utils/names.go:39-43)."""
    return name.replace("-", "").lower()
