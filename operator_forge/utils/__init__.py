"""Shared utilities (reference: internal/utils)."""

from .names import (  # noqa: F401
    to_title,
    title_words,
    to_pascal_case,
    to_file_name,
    to_package_name,
)
from .globber import glob_files  # noqa: F401
