"""One daemon client connection: framing, queueing, and capture.

A :class:`Session` owns exactly one accepted socket.  Its reader thread
speaks newline-JSON — the same one-object-per-line protocol the stdio
``serve`` loop reads, so a client can pipe the identical request stream
at either transport — and enqueues parsed requests into the session's
bounded queue for the owner's fair scheduler to dispatch.  The owner is
whichever socket server accepted the connection — the multi-client
daemon (:mod:`operator_forge.serve.daemon`) or the fleet coordinator
(:mod:`operator_forge.serve.fleet`); both provide the same
``_enqueue(session, req)`` / ``_reader_finished(session)`` admission
surface, so one session implementation serves both listeners.  Responses are
written back one JSON line each, serialized by a per-session lock so a
streaming op's cycle lines can never interleave with a sibling
request's answer.

Protocol robustness on the socket path:

- **bad JSON / non-object requests** answer ``bad_request`` and the
  connection continues (the stdio rule);
- **oversized lines** (over :data:`MAX_LINE` bytes) answer
  ``bad_request`` and close THIS connection only — a peer that cannot
  frame its requests can no longer be trusted on a byte stream, but
  sibling sessions and the listener are untouched;
- **torn lines** (EOF with no trailing newline) are dropped — a torn
  frame is never treated as data;
- **admission rejections** (session queue or the daemon's global queue
  full) answer immediately from the reader thread with the ``busy``
  taxonomy kind plus a ``retry_after`` hint, so backpressure is a
  protocol answer, never unbounded buffering;
- **mid-request disconnect**: a failed response write marks the
  session dead and raises the shared
  :class:`~operator_forge.serve.server._AbandonedRequest`, so the
  in-flight handler unwinds at its next emit, the abandonment is
  counted (``serve.requests_abandoned``), and the queued remainder is
  discarded.

Output capture needs nothing session-specific: job stdout/stderr is
routed per-thread by the runner's ``_ThreadRouter``, and each session
has at most one request in flight (the scheduler dispatches the next
one only after the current answer is written), so a dispatcher thread's
capture buffers are naturally per-session.
"""

from __future__ import annotations

import json
import threading
import time

from ..perf import flight, metrics
from .server import _AbandonedRequest, _count_error, _error

#: hard ceiling on one request line — an 8 MiB JSON object is far past
#: any real batch manifest; beyond it the peer is mis-framing
MAX_LINE = 8 * 1024 * 1024

#: the retry_after hint (seconds) carried by request-level busy
#: rejections (a queue slot frees as soon as one request dispatches)
RETRY_AFTER_S = 0.05

#: the hint for CONNECTION-level rejections (daemon at its client
#: cap): a session slot frees only when some client finishes, so the
#: suggested backoff is an order of magnitude longer
CONNECT_RETRY_AFTER_S = 0.5


class Session:
    """One accepted daemon connection (reader thread + response lock +
    bounded request queue)."""

    def __init__(self, daemon, conn, session_id: str):
        # the owner: a ForgeDaemon or a FleetCoordinator (both provide
        # _enqueue/_reader_finished); the historical attribute name is
        # kept — every call site reads session.daemon
        self.daemon = daemon
        self.conn = conn
        self.id = session_id
        #: serializes every protocol write on this connection — shared
        #: with dispatch_request as its out_lock
        self.out_lock = threading.Lock()
        #: pending (request, enqueue_monotonic) pairs, appended by the
        #: reader thread, popped by the scheduler under the daemon lock
        self.queue: list = []
        #: a request from this session is currently dispatching; the
        #: scheduler skips busy sessions so responses stay ordered
        self.busy = False
        #: the transport is dead (write failed / oversized close);
        #: set-once, observed by respond and the scheduler
        self.dead = threading.Event()
        #: the in-flight request's abandonment Event (shared with
        #: dispatch_request) so a disconnect can cancel it mid-stream
        self.current_abandoned = None
        #: the in-flight request's supersede identity
        #: (:func:`~operator_forge.serve.jobs.supersede_key`) — set by
        #: the scheduler under the daemon lock so the reader thread's
        #: admission path can match a newer same-buffer request
        self.current_key = None
        #: the in-flight request's supersede Event (observed by the
        #: dispatcher's sliced join); ``None`` when the current request
        #: is not in-flight-abandonable
        self.current_superseded = None
        #: reader thread saw EOF — no further requests will arrive
        self.read_done = False
        self.requests_total = 0
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"daemon-session-{session_id}",
        )

    def start(self) -> None:
        self._reader.start()

    # -- response path --------------------------------------------------

    def respond_locked(self, payload: dict) -> None:
        """Write one protocol line (caller holds ``out_lock``).  On a
        dead transport raises ``_AbandonedRequest`` so the shared
        dispatcher counts the abandonment and unwinds streaming ops."""
        _count_error(payload)
        if self.dead.is_set():
            raise _AbandonedRequest()
        try:
            self.conn.sendall(
                (json.dumps(payload) + "\n").encode("utf-8")
            )
        except OSError:
            self._mark_dead()
            raise _AbandonedRequest() from None

    def respond(self, payload: dict) -> None:
        with self.out_lock:
            self.respond_locked(payload)

    def _mark_dead(self) -> None:
        self.dead.set()
        abandoned = self.current_abandoned
        if abandoned is not None:
            # cancel the in-flight request too: a quiet-tree watch has
            # no next emit to fail at, so the poll must observe this
            abandoned.set()
            # only a MID-REQUEST death is an anomaly worth a capsule —
            # a clean EOF with nothing in flight is just a goodbye
            flight.anomaly(
                "session.disconnect", {"session": self.id}
            )

    # -- reader ----------------------------------------------------------

    def _read_loop(self) -> None:
        try:
            stream = self.conn.makefile(
                "r", encoding="utf-8", errors="replace"
            )
            while not self.dead.is_set():
                line = stream.readline(MAX_LINE + 1)
                if not line:
                    return  # clean EOF: no more requests
                if len(line) > MAX_LINE:
                    # the peer is mis-framing: answer once, close this
                    # connection — siblings and the listener live on
                    self._answer_error(
                        f"request line exceeds {MAX_LINE} bytes"
                    )
                    self._mark_dead()
                    return
                if not line.endswith("\n"):
                    return  # torn line at EOF: never treated as data
                line = line.strip()
                if not line:
                    continue
                try:
                    req = json.loads(line)
                except json.JSONDecodeError as exc:
                    self._answer_error(f"invalid JSON: {exc}")
                    continue
                if not isinstance(req, dict):
                    self._answer_error("request must be a JSON object")
                    continue
                self.daemon._enqueue(self, req)
        except (OSError, ValueError):
            self._mark_dead()  # connection reset / closed under us
        finally:
            self.read_done = True
            self.daemon._reader_finished(self)

    def _answer_error(self, message: str) -> None:
        try:
            self.respond(_error(message))
        except _AbandonedRequest:
            pass

    def reject_busy(self, req: dict, reason: str) -> None:
        """Answer an admission rejection immediately (reader thread):
        the PR 7 taxonomy's ``busy`` kind plus a retry_after hint."""
        metrics.counter("daemon.busy_rejections").inc()
        flight.anomaly("serve.busy", {
            "session": self.id, "reason": reason,
        })
        payload = _error(reason, req.get("id"), kind="busy")
        payload["retry_after"] = RETRY_AFTER_S
        try:
            self.respond(payload)
        except _AbandonedRequest:
            pass

    def reject_superseded(self, req: dict) -> None:
        """Answer a queued request a newer same-buffer request just
        made stale (PR 17): the ``superseded`` taxonomy kind, counted
        under ``editor.superseded``.  Not a failure — no retry hint
        (the newer request's answer is the one to await), no anomaly
        capsule, and never an SLO deadline miss (the request never
        dispatched)."""
        metrics.counter("editor.superseded").inc()
        payload = _error(
            "superseded by a newer request for the same buffer",
            req.get("id"), kind="superseded",
        )
        try:
            self.respond(payload)
        except _AbandonedRequest:
            pass

    # -- bookkeeping -----------------------------------------------------

    def queue_depth(self) -> int:
        return len(self.queue)

    def pop_request(self):
        """(request, queue-wait seconds) — caller holds the daemon
        scheduler lock."""
        req, enqueued = self.queue.pop(0)
        return req, time.monotonic() - enqueued

    def state(self) -> dict:
        """The per-session surface serve ``stats`` reports."""
        return {
            "queue_depth": len(self.queue),
            "in_flight": self.busy,
            "requests": self.requests_total,
        }

    def close(self) -> None:
        self.dead.set()
        import socket as _socket

        try:
            # a plain close() defers the real close while the reader
            # thread's makefile holds an io-ref on the socket; shutdown
            # forces EOF to the peer (and unblocks our own reader) now
            self.conn.shutdown(_socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.conn.close()
        except OSError:
            pass
