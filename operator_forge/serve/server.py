"""``operator-forge serve`` — a persistent request loop.

Keeps one resident process hot: the argument parser, the gocheck stdlib
manifest, the closure-compiled interpreter bodies, and every
content-addressed cache survive across requests, so request N+1 starts
where a one-shot CLI invocation would have to re-prime from zero.

Protocol: one JSON object per stdin line, one JSON response per stdout
line (always exactly one, flushed; job/batch stdout is captured into
the response, never interleaved with the protocol stream):

- ``{"op": "ping"}`` — liveness + version;
- ``{"op": "job", "job": {<job spec>}}`` (or the spec inlined with a
  ``command`` key) — run one init/create-api/vet/lint/test job;
- ``{"op": "batch", "jobs": [<specs...>]}`` — run a batch through the
  orchestrator (grouped, fanned out, input-order results);
- ``{"op": "watch", "jobs": [<specs...>], "cycles": N}`` — the edit
  loop: run the jobs, then poll their input trees (``interval``
  seconds, default 0.5) and re-run the minimal set on every change.
  A *streaming* op: each cycle emits its own response line
  (``"op": "watch"``, per-cycle ``graph`` reuse counts), and a final
  ``{"op": "watch", "done": true, "cycles": N}`` line closes the
  request;
- ``{"op": "overlay", "path": P, "content": TEXT}`` — register an
  in-memory buffer overlay (PR 17, the gopls ``didChange`` analogue):
  until cleared (``"clear": true``) every content key, dependency-graph
  node, and read site sees TEXT as if the file had those bytes on
  disk, so a vet of unsaved content is byte-identical to a save+vet;
- ``{"op": "subscribe", "jobs": [<specs...>]}`` — push diagnostics
  (the gopls ``publishDiagnostics`` analogue): streams one line per
  converged minimal re-run, with overlay edits waking the loop
  immediately; ``cycles`` omitted means "until disconnect/drain";
- ``{"op": "stats"}`` — per-namespace cache hit/miss counters with
  ratios (stable key order, incl. quarantine footprint and remote-hit
  attribution), the dependency graph's cumulative
  dirty/reused/recomputed counters, the metrics registry
  (counters/gauges + p50/p99 latency histograms for serve jobs and
  watch cycles), the graph's recorded invalidation provenance, the
  remote-cache tier state (address, degraded flag, write-behind
  backlog), and the span table the per-request ``serve:*`` spans
  feed;
- ``{"op": "explain", "path": <root>, "changed": [...]}`` — the
  invalidation-provenance report: for each changed file, the
  deterministic chain of artifacts its edit dirties (derived
  structurally — byte-identical across cache modes and worker
  backends).  With ``changed`` omitted, the last ``watch`` cycle's
  recorded change set answers "why did the last cycle recompute?";
- ``{"op": "trace-dump"}`` — the flight recorder's on-demand surface:
  the live trace-event ring plus the bounded anomaly log (see
  :mod:`operator_forge.perf.flight`), from a running process with no
  kill and no pre-arranged ``trace`` wrapper;
- ``{"op": "shutdown"}`` — acknowledge and exit 0 (EOF does the same).

Malformed lines answer ``{"ok": false, "error": ..., "error_kind":
...}`` and the loop continues; a request's ``id`` is echoed in its
response so pipelined clients can correlate.  Relative job paths
resolve against the server's working directory.

Distributed tracing (PR 15): a request may carry ``"trace": {"id":
<trace id>, "parent": <span id>}`` — the handler's spans are then
recorded inside that trace's segment and shipped back on the response
as ``trace_events`` (the final line, for streaming ops), so a traced
client merges every server's work into one connected timeline.
:class:`~operator_forge.serve.daemon.DaemonClient` stamps and ingests
this automatically for ``job``/``batch``/``watch`` when the client
process is tracing.

Robustness (PR 7):

- **error taxonomy** — every error response carries ``error_kind``
  (``bad_request`` / ``timeout`` / ``infra`` / ``internal``), and each
  is counted in the metrics registry as ``serve.errors.<kind>`` —
  surfaced by ``stats`` so operators see *what class* of failures a
  resident server has absorbed, not just that it kept answering;
- **per-request deadlines** — with ``OPERATOR_FORGE_SERVE_TIMEOUT``
  set (seconds), a request that exceeds it is answered with a
  ``timeout`` error and abandoned.  Abandonment is output suppression
  plus unwind-at-next-emit, not thread cancellation: a streaming
  handler (``watch``) unwinds at its next cycle, but a non-streaming
  one (``job``/``batch``) runs to completion detached and may still
  be writing its output tree — treat a timeout answer as "outcome
  unknown", not "not executed", and don't immediately re-submit the
  same job over the same output dir.  The detached handler also still
  shares this process's worker pool and global cache/config state: if
  one of its tasks later blows the task deadline it kills the shared
  pool, breaking a live handler's round mid-collection (the live
  request still recovers through the workers layer's retry path, at
  retry cost and possibly a degraded record) — so a serve deadline
  paired with a much longer task deadline is a misconfiguration;
  keep ``OPERATOR_FORGE_TASK_TIMEOUT`` at or below
  ``OPERATOR_FORGE_SERVE_TIMEOUT`` when both are set;
- **graceful shutdown** — SIGTERM/SIGINT (or
  :func:`request_shutdown`) drains: the in-flight request finishes and
  is answered, a final ``{"op": "shutdown", "drained": true}`` line is
  emitted, and the loop exits 0 without taking further work;
- the ``stats`` op additionally reports the worker-pool state
  (``workers``: backend, degraded flag, reason).

Multi-client transport (PR 10): the same protocol is served to N
concurrent socket clients by ``operator-forge daemon``
(:mod:`operator_forge.serve.daemon`) — per-connection sessions
(:mod:`operator_forge.serve.session`) multiplex over this module's
shared :func:`dispatch_request` machinery, so the deadline, taxonomy
(including the daemon-only ``busy`` admission rejections), and the
SIGTERM/SIGINT drain implementation live once and cannot drift between
the stdio and socket transports.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

from .. import __version__
from ..perf import env_number, flight, metrics, spans
from ..perf.depgraph import GRAPH
from .batch import run_batch
from .jobs import BatchManifestError, jobs_from_specs, specs_from_request
from .runner import run_job

#: error taxonomy: why did a request fail?
#: - ``bad_request`` — the client sent something unusable (bad JSON,
#:   unknown op, invalid manifest/params)
#: - ``busy`` — admission control rejected the request (a daemon
#:   session's queue, or the global admission queue, is full); the
#:   response carries a ``retry_after`` hint in seconds
#: - ``superseded`` — a newer request from the same session made this
#:   one stale (an editor's next keystroke for the same buffer): the
#:   old request is answered without burning a dispatcher slot and the
#:   client should simply await the newer request's answer.  NOT a
#:   failure of the server — no SLO deadline miss is charged
#: - ``timeout`` — the per-request deadline expired
#: - ``infra`` — the execution substrate failed (dead process pool,
#:   pickle transport, I/O)
#: - ``internal`` — an unclassified server-side bug
ERROR_KINDS = (
    "bad_request", "busy", "superseded", "timeout", "infra", "internal",
)


class _AbandonedRequest(Exception):
    """Raised inside a deadline-abandoned handler's emit to unwind it
    (streaming ops like ``watch`` would otherwise run forever after
    their client already got the timeout answer)."""


_drain = threading.Event()

#: callbacks run once when a drain begins — the socket daemon registers
#: one that closes its listener (breaking the blocked ``accept``) and
#: wakes its scheduler, so the SIGTERM/SIGINT machinery lives ONCE here
#: and both transports (stdio serve, socket daemon) share it
_drain_callbacks: list = []


def on_drain(callback) -> None:
    """Register a callback to run when a drain begins (idempotent per
    drain: callbacks fire only on the first :func:`request_shutdown`).
    Callbacks may run in signal-handler context — keep them tiny and
    non-blocking (closing a socket, setting an event)."""
    if callback not in _drain_callbacks:
        _drain_callbacks.append(callback)


def remove_drain_callback(callback) -> None:
    try:
        _drain_callbacks.remove(callback)
    except ValueError:
        pass


def draining() -> bool:
    """Whether a drain has been requested (shared by both transports)."""
    return _drain.is_set()


class _DrainSignal(BaseException):
    """Raised *from the signal handler* to break an idle loop out of
    its blocking stdin read.  After a Python-level handler returns,
    the interrupted ``read`` syscall is transparently restarted (PEP
    475), so merely setting the drain flag would leave an idle server
    blocked — unkillable by SIGTERM/SIGINT — until the next request
    line arrives.  ``BaseException`` so the loop's per-request
    ``except Exception`` catch-alls can't swallow it."""


#: is a request currently being dispatched/answered?  Written only by
#: the loop's main thread; read by the signal handler (which runs on
#: that same thread, between bytecodes) to decide whether raising
#: :class:`_DrainSignal` would abort in-flight work.
_busy = [False]


def request_shutdown(signum=None, frame=None) -> None:
    """Begin a graceful shutdown: the loop finishes (drains) the
    in-flight request, answers it, emits a final drained-shutdown
    line, and exits 0.  Installed as the SIGTERM/SIGINT handler by
    :func:`serve_loop`; safe to call programmatically from any
    thread.  As a *signal handler* on an idle loop it additionally
    raises to interrupt the blocking read — only on the first signal
    (a repeated SIGTERM during the drained exit must not break the
    final protocol line mid-write) and only when no request is in
    flight (aborting one would violate the drain promise)."""
    already = _drain.is_set()
    _drain.set()
    if not already:
        for callback in list(_drain_callbacks):
            try:
                callback()
            except Exception:
                pass  # a drain must never die in a notification hook
    if signum is not None and not already and not _busy[0]:
        raise _DrainSignal()


def request_timeout() -> float:
    """Per-request deadline in seconds (``OPERATOR_FORGE_SERVE_TIMEOUT``;
    0 or unset disables)."""
    return env_number("OPERATOR_FORGE_SERVE_TIMEOUT", 0.0)


def _classify(exc: BaseException) -> str:
    """Map an escaped exception onto the error taxonomy."""
    from concurrent.futures.process import BrokenProcessPool

    if isinstance(exc, TimeoutError):
        return "timeout"
    if isinstance(
        exc,
        (BrokenProcessPool, BrokenPipeError, ConnectionError,
         EOFError, OSError, MemoryError),
    ):
        return "infra"
    return "internal"


# extra top-level keys merged into every ``stats`` response — the
# daemon registers its session/queue surface, the fleet coordinator its
# member table.  The registry itself lives in perf.metrics so the SAME
# surfaces appear in `operator-forge stats` / `fleet-status`; these
# aliases keep the serve-layer spelling both transports already use.
register_stats_source = metrics.register_stats_source
unregister_stats_source = metrics.unregister_stats_source


# -- server telemetry lifecycle --------------------------------------------
#
# Spans enablement and the flight recorder are PROCESS-global; a
# process can host several servers at once (a FleetCoordinator plus
# embedded ForgeDaemons — the test and bench topology).  Teardown is
# therefore refcounted: the first boot turns the always-on ring and
# recorder on, and only the LAST teardown turns them off — a daemon
# stopping must not dark the still-running coordinator's black box.

_telemetry_lock = threading.Lock()
_telemetry_refs = [0]


def retain_server_telemetry() -> None:
    """One server booted: per-request spans are part of the stats
    contract, the event ring is the flight recorder's black box and
    the source distributed-trace segments drain from."""
    with _telemetry_lock:
        _telemetry_refs[0] += 1
    spans.enable(True)
    spans.enable_tracing(True)
    flight.arm()


def release_server_telemetry() -> None:
    """One server drained: persist ITS black box and (env-configured)
    timeline now — a drained server must not depend on unwinding out
    of the outermost ``main()`` to write either — and release the
    process-global state only when no sibling server remains."""
    with _telemetry_lock:
        _telemetry_refs[0] = max(0, _telemetry_refs[0] - 1)
        last = _telemetry_refs[0] == 0
    if last:
        flight.disarm(final=True)
    else:
        flight.flush(final=True)
    spans.export_env_trace(announce=False)
    if last:
        spans.use_env()


def _count_error(payload: dict) -> None:
    """Account an error response by taxonomy kind — shared by every
    transport's respond path so ``serve.errors.<kind>`` counters cover
    stdio and socket sessions alike."""
    if payload.get("ok") is False and "error_kind" in payload:
        metrics.counter(
            "serve.errors." + str(payload["error_kind"])
        ).inc()


def _error(message: str, req_id=None, kind: str = "bad_request") -> dict:
    if kind not in ERROR_KINDS:
        # the taxonomy is closed — clients and the serve.errors.<kind>
        # counters key on it — so a drifted kind is itself an
        # unclassified server-side bug
        kind = "internal"
    out = {"ok": False, "error": message, "error_kind": kind}
    if req_id is not None:
        out["id"] = req_id
    return out


def _handle(req: dict, base_dir: str, emit=None, abandoned=None) -> tuple:
    """Dispatch one request; returns (response dict, keep_going).
    ``emit`` delivers the intermediate lines of streaming ops (watch);
    ``abandoned`` (an Event) tells a long-polling op its client already
    received a deadline answer, so it must stop instead of waiting for
    its next emit to unwind it — a quiet-tree watch may never emit."""
    op = req.get("op") or ("job" if "command" in req else None)
    req_id = req.get("id")
    if op == "ping":
        return ({"ok": True, "op": "ping", "version": __version__}, True)
    if op == "shutdown":
        return ({"ok": True, "op": "shutdown"}, False)
    if op == "stats":
        import sys as _sys

        from ..perf import remote, workers

        compiler = _sys.modules.get("operator_forge.gocheck.compiler")
        if compiler is not None:
            compiler.flush_counters()  # compile.reused is tallied lazily
        payload = {
            "ok": True, "op": "stats",
            "artifact": metrics.artifact_report(),
            "cache": metrics.cache_report(),
            "editor": metrics.editor_report(),
            "graph": GRAPH.counters(),
            "metrics": metrics.snapshot(),
            "provenance": {
                "last_invalidation": GRAPH.last_invalidation(),
                "recorded": GRAPH.provenance(),
            },
            "remote": remote.state(),
            "slo": metrics.slo_report(),
            "spans": spans.snapshot(),
            "tiers": metrics.tier_report(),
            "workers": workers.pool_state(),
        }
        payload.update(metrics.stats_sources())
        return (payload, True)
    if op == "trace-dump":
        # the flight recorder's on-demand surface: the live trace ring
        # plus the bounded anomaly log, from a running serve/daemon/
        # fleet process — a post-mortem that needs no kill and no
        # pre-arranged `trace` wrapper
        return ({"ok": True, "op": "trace-dump", **flight.dump()}, True)
    if op == "explain":
        import os as _os

        from ..gocheck.explain import explain_report, explain_summary
        from . import watch as watch_mod

        root = req.get("path") or base_dir
        if not _os.path.isabs(root):
            root = _os.path.normpath(_os.path.join(base_dir, root))
        changed = req.get("changed")
        removed = req.get("removed") or []
        if changed is not None or "removed" in req:
            # an explicit change set — a removed-only request counts
            if not _os.path.isdir(root):
                return (_error(
                    f"explain: {root} is not a directory", req_id), True)
            changed = changed or []
            # one shared import map: summary and report each need it
            from ..gocheck.explain import package_imports

            imports = package_imports(root)
            return (
                {"ok": True, "op": "explain",
                 "path": req.get("path") or root,
                 "changes": explain_summary(
                     root, changed, removed, imports=imports),
                 "report": explain_report(
                     root, changed, removed, imports=imports)},
                True,
            )
        # no explicit change set: answer for the last watch cycle,
        # deriving each file against the watch root it was recorded
        # under (rels are relative to THAT root, not the request path)
        roots, changes, report = watch_mod.last_cycle_explain()
        if not roots:
            return (_error(
                "explain: no change set — pass \"changed\": [...] "
                "or run a watch cycle first", req_id), True)
        return (
            {"ok": True, "op": "explain", "roots": roots,
             "changes": changes, "report": report},
            True,
        )
    if op == "watch":
        from .watch import watch_loop

        jobs = jobs_from_specs(req.get("jobs"), base_dir)
        cycles = req.get("cycles", 1)
        if not isinstance(cycles, int) or cycles < 1:
            return (_error("watch: cycles must be a positive integer",
                           req_id), True)

        def emit_cycle(payload: dict) -> None:
            payload["ok"] = bool(payload["ok"])
            if req_id is not None:
                payload["id"] = req_id
            if emit is not None:
                emit(payload)

        try:
            interval = float(req.get("interval", 0.5))
        except (TypeError, ValueError):
            return (_error("watch: interval must be a number", req_id),
                    True)
        if not (0 < interval < float("inf")):  # rejects NaN too
            # a zero/negative interval would make drain_aware_poll a
            # zero-sleep busy loop (its deadline is already expired on
            # every call), and NaN would raise out of time.sleep
            # mid-watch — both answer as bad_request instead
            return (_error("watch: interval must be a positive number",
                           req_id), True)

        def drain_aware_poll() -> bool:
            # a shutdown signal landing while this (busy) op runs only
            # sets the drain flag — raising would abort in-flight work
            # — so the watch must observe it itself between polls, or a
            # quiet tree would keep the server unkillable forever.  The
            # same goes for deadline abandonment: unwind-at-next-emit
            # never fires while the tree stays quiet, so the flag is
            # polled here too or every timed-out watch would leave a
            # permanent background poller.  The sleep is chunked so
            # stop latency stays bounded however long the client's
            # interval is
            deadline = time.monotonic() + interval
            while not _drain.is_set():
                if abandoned is not None and abandoned.is_set():
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return True
                time.sleep(min(0.1, remaining))
            return False

        ran = watch_loop(
            jobs, emit_cycle, cycles=cycles, interval=interval,
            poll=drain_aware_poll,
        )
        return ({"ok": True, "op": "watch", "done": True,
                 "cycles": ran}, True)
    if op == "overlay":
        # the editor's unsaved buffer (gopls didChange analogue): the
        # registered content flows through every content key and read
        # site as if the file had those bytes on disk
        from ..perf import overlay as pf_overlay

        path = req.get("path")
        if not isinstance(path, str) or not path:
            return (_error("overlay: path is required", req_id), True)
        if not os.path.isabs(path):
            path = os.path.normpath(os.path.join(base_dir, path))
        if req.get("clear"):
            cleared = pf_overlay.clear_overlay(path)
            return ({"ok": True, "op": "overlay", "path": path,
                     "cleared": cleared,
                     "overlays": pf_overlay.count()}, True)
        content = req.get("content")
        if not isinstance(content, str):
            return (_error(
                "overlay: content must be a string "
                "(or pass \"clear\": true)", req_id), True)
        if not os.path.isfile(path):
            # overlays target existing files: an overlay for a path
            # that is not on disk would make tree walks and content
            # keys disagree about the project's file set
            return (_error(
                f"overlay: {path} does not exist on disk", req_id),
                True)
        info = pf_overlay.set_overlay(
            path, content, owner=req.get("_owner"),
        )
        metrics.counter("editor.overlay_sets").inc()
        return ({"ok": True, "op": "overlay", "path": path,
                 **info}, True)
    if op == "subscribe":
        # push diagnostics (gopls publishDiagnostics analogue): stream
        # one line per converged minimal re-run, with overlay edits
        # waking the loop immediately instead of waiting out the poll
        # interval.  `cycles` is optional — omitted means "until the
        # client disconnects or the server drains"
        from ..perf import overlay as pf_overlay
        from .watch import watch_loop

        jobs = jobs_from_specs(req.get("jobs"), base_dir)
        cycles = req.get("cycles")
        if cycles is not None and (
            not isinstance(cycles, int) or cycles < 1
        ):
            return (_error(
                "subscribe: cycles must be a positive integer",
                req_id), True)
        try:
            interval = float(req.get("interval", 0.5))
        except (TypeError, ValueError):
            return (_error("subscribe: interval must be a number",
                           req_id), True)
        if not (0 < interval < float("inf")):
            return (_error(
                "subscribe: interval must be a positive number",
                req_id), True)

        def emit_push(payload: dict) -> None:
            payload["op"] = "subscribe"
            payload["ok"] = bool(payload["ok"])
            if req_id is not None:
                payload["id"] = req_id
            metrics.histogram("editor.push_cycle.seconds").observe(
                payload.get("seconds", 0.0)
            )
            if emit is not None:
                emit(payload)

        # the generation edge is captured ONCE, before the first
        # cycle runs, and only advanced to values wait_change actually
        # returned: an overlay op landing while a cycle runs (or
        # between the cycle's emit and the next poll) still reads as
        # newer-than-seen, so the wake fires on the very next poll
        # instead of being silently absorbed until the interval expires
        seen = [pf_overlay.generation()]

        def push_poll() -> bool:
            # like the watch op's drain-aware poll, but additionally
            # parked on the overlay generation: a `overlay` op from
            # any session wakes this immediately, so the next cycle's
            # diagnostics push the moment the edit lands
            deadline = time.monotonic() + interval
            while not _drain.is_set():
                if abandoned is not None and abandoned.is_set():
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return True
                cur = pf_overlay.wait_change(
                    seen[0], min(0.1, remaining)
                )
                if cur != seen[0]:
                    seen[0] = cur
                    return True
            return False

        ran = watch_loop(
            jobs, emit_push, cycles=cycles, interval=interval,
            poll=push_poll,
        )
        return ({"ok": True, "op": "subscribe", "done": True,
                 "cycles": ran}, True)
    if op == "fence":
        # the fleet coordinator's zombie fence (PR 14): on the daemon
        # transport this request's `roots`+`reset` are write-locked by
        # the cross-session path locks BEFORE this handler runs, so by
        # the time we execute, no in-flight (or deadline-abandoned
        # zombie) request can still be writing any of these trees —
        # and the reset of a dead re-dispatch attempt's fresh output
        # roots happens race-free on the daemon that owns them.  On
        # the stdio transport requests are serial, so the property is
        # trivial.  Deletion is CONTAINED: only roots this process
        # observed being created from absence (the fenceable-root
        # registry) may be reset — no other serve op can delete
        # anything, and the fence must not hand arbitrary clients
        # rmtree of pre-existing trees.
        import shutil as _shutil

        from .runner import is_fenceable_root

        roots = req.get("roots")
        reset = req.get("reset") or []
        if not isinstance(roots, list) or not isinstance(reset, list):
            return (_error(
                "fence: roots and reset must be lists of paths",
                req_id), True)
        removed = 0
        skipped = 0
        for root in reset:
            path = str(root)
            if not os.path.isabs(path):
                path = os.path.normpath(os.path.join(base_dir, path))
            if not os.path.isdir(path):
                continue  # nothing to reset
            if not is_fenceable_root(path):
                skipped += 1
                continue
            _shutil.rmtree(path, ignore_errors=True)
            removed += 1
        return ({"ok": True, "op": "fence", "reset": removed,
                 "skipped": skipped}, True)
    if op == "job":
        from .runner import record_fenceable_roots

        jobs = jobs_from_specs(specs_from_request(req), base_dir)
        record_fenceable_roots([
            root for root in jobs[0].writes()
            if not os.path.isdir(root)
        ])
        result = run_job(jobs[0]).to_dict()
        result["op"] = "job"
        return (result, True)
    if op == "batch":
        specs = req.get("jobs")
        jobs = jobs_from_specs(specs, base_dir)
        started = time.perf_counter()
        results = run_batch(jobs)
        return (
            {
                "ok": all(r.ok for r in results),
                "op": "batch",
                "results": [r.to_dict() for r in results],
                "cached": sum(1 for r in results if r.cached),
                "seconds": round(time.perf_counter() - started, 4),
            },
            True,
        )
    return (_error(f"unknown op {op!r}", req_id), True)


def dispatch_request(req: dict, base_dir: str, out_lock,
                     respond_locked, deadline: float,
                     abandoned=None, on_settled=None,
                     superseded=None) -> bool:
    """Dispatch ONE parsed request through the shared machinery —
    deadline boxing, the error taxonomy, id echo, ``seconds`` stamping,
    streaming-emit abandonment — and answer it via ``respond_locked``
    (called with ``out_lock`` held; it must write exactly one protocol
    line and may raise :class:`_AbandonedRequest` when its transport is
    gone).  Returns ``keep_going`` (``False`` for the shutdown op).

    Both transports call this: the stdio loop with its stdout writer,
    each daemon session with its socket writer — so the PR 7 behaviors
    (timeout answers, ``serve.errors.<kind>`` accounting, late-emit
    suppression) cannot drift between them.  ``abandoned`` optionally
    supplies the request's cancellation Event (a daemon session passes
    one it can set when the client disconnects mid-request).

    ``superseded`` (PR 17) optionally supplies an Event a newer
    same-buffer request sets: the handler is then always deadline-boxed
    (even with no deadline configured) and, should the event fire while
    the work is still running, the request is abandoned and answered
    with the ``superseded`` taxonomy kind — crucially WITHOUT charging
    an SLO deadline miss or recording a ``request.deadline`` anomaly
    (stale editor work is not a server failure).  Only passed for
    requests :func:`operator_forge.serve.jobs.supersede_key` declared
    in-flight-abandonable (pure read-only vets).

    ``on_settled`` is called EXACTLY ONCE when the handler's side
    effects are actually over: on normal completion, on error — or,
    for a deadline-abandoned request, when the detached handler thread
    finally finishes, which may be long after the timeout answer went
    out.  The daemon hangs its cross-session path-lock release here,
    so a zombie writer keeps its trees locked and no sibling session
    can interleave writes with it."""
    settle_lock = threading.Lock()
    settled = [False]

    def settle() -> None:
        if on_settled is None:
            return
        with settle_lock:
            if settled[0]:
                return
            settled[0] = True
        on_settled()

    handed_off = [False]
    try:
        return _dispatch_inner(
            req, base_dir, out_lock, respond_locked, deadline,
            abandoned, settle, handed_off, superseded,
        )
    except _AbandonedRequest:
        # the transport died mid-request (client disconnect): the work
        # was abandoned cleanly — counted, never answered.  The trace
        # shipping bucket is freed too (there is no one to ship to,
        # and an orphaned bucket would squat a FIFO slot)
        tctx = spans.parse_trace_field(req)
        if tctx is not None:
            spans.drain_trace(tctx[0])
        metrics.counter("serve.requests_abandoned").inc()
        return True
    finally:
        # every path settles: directly here, unless settlement was
        # handed to a deadline-boxed handler thread (whose own finally
        # fires when the handler truly finishes, detached or not)
        if not handed_off[0]:
            settle()


def _slo_tenants(req: dict, base_dir: str) -> tuple:
    """The per-tenant SLO labels a request's jobs would be charged to
    (the ``serve.job.<tree-hash>`` project-namespace keys) — used to
    attribute a deadline miss to its tenant(s).  Parsed only on the
    timeout path, so the cost rides an already-lost request."""
    specs = specs_from_request(req)
    if specs is None:
        return ()
    try:
        jobs = jobs_from_specs(specs, base_dir)
    except (BatchManifestError, TypeError, ValueError):
        return ()
    from .runner import _scope_label

    return tuple(sorted({
        _scope_label((job.target(),)) for job in jobs
    }))


def _dispatch_inner(req, base_dir, out_lock, respond_locked,
                    deadline, abandoned, settle, handed_off,
                    superseded=None):
    op = req.get("op") or ("job" if "command" in req else "?")
    req_id = req.get("id")
    started = time.perf_counter()
    if abandoned is None:
        abandoned = threading.Event()
    # distributed tracing: a request carrying a trace context adopts it
    # for the handler's lifetime (spans tag + namespace under a fresh
    # segment, parented onto the caller's span id) and ships the
    # drained segment back on the response — the socket-boundary
    # analogue of the workers' sealed-result drain
    tctx = spans.parse_trace_field(req)

    def respond(payload: dict) -> None:
        with out_lock:
            respond_locked(payload)

    def guarded_emit(payload: dict, _flag=abandoned) -> None:
        # a deadline-abandoned (or disconnected) handler must not
        # interleave its late stream lines into the protocol; the flag
        # check and the write share out_lock with the timeout response,
        # so either the emit lands whole before the abandonment or not
        # at all.  Raising (instead of silently dropping) unwinds
        # streaming handlers — a watch loop would otherwise keep
        # polling and running jobs forever after its client already got
        # the timeout answer (or went away)
        with out_lock:
            if _flag.is_set():
                raise _AbandonedRequest()
            respond_locked(payload)

    def ship_trace(payload: dict) -> dict:
        # EVERY final answer drains the request's shipping bucket —
        # error and timeout answers included.  An undrained bucket
        # would sit in spans._trace_buckets until FIFO eviction, and
        # enough failed traced requests could evict a LIVE request's
        # bucket (its response would then ship an empty segment); a
        # timeout answer shipping the partial segment is also honest
        # data (the client sees what ran before the abandonment)
        if tctx is not None and spans.trace_enabled():
            payload["trace_events"] = spans.drain_trace(tctx[0])
        return payload

    def dispatch():
        import contextlib

        segment = (
            spans.remote_segment(tctx[0], tctx[1], "serve")
            if tctx is not None and spans.trace_enabled()
            else contextlib.nullcontext()
        )
        with segment:
            # the admission marker: even a request the server never
            # finishes (SIGKILL mid-run) is visible in the flight ring
            spans.instant(
                f"serve.request:{op}",
                args={"req": req_id} if req_id is not None else None,
            )
            with spans.span(f"serve:{op}"):
                return _handle(req, base_dir, emit=guarded_emit,
                               abandoned=abandoned)

    try:
        if deadline > 0 or superseded is not None:
            box: dict = {}

            def run_boxed(_box=box, _dispatch=dispatch):
                try:
                    _box["out"] = _dispatch()
                except BaseException as exc:
                    _box["exc"] = exc
                finally:
                    # the handler's side effects end HERE — possibly
                    # long after a timeout answer abandoned it.  An
                    # ABANDONED traced handler's post-timeout spans
                    # re-created a shipping bucket nobody will ever
                    # answer with: free it now that the spans truly
                    # stopped (never on the normal path — the main
                    # thread ships the bucket after joining us)
                    if abandoned.is_set() and tctx is not None:
                        spans.drain_trace(tctx[0])
                    settle()

            worker = threading.Thread(
                target=run_boxed, daemon=True, name="serve-request",
            )
            worker.start()
            handed_off[0] = True
            # the join is sliced so a supersede lands in ~50ms instead
            # of waiting out the full deadline (with no supersede Event
            # the slicing is behaviorally identical to one long join)
            expires = (
                time.monotonic() + deadline if deadline > 0 else None
            )
            timed_out = False
            while worker.is_alive():
                if superseded is not None and superseded.is_set():
                    # a newer same-buffer request made this one stale:
                    # abandon it (output suppression, unwind-at-next-
                    # emit — same mechanism as the deadline) and answer
                    # with the superseded kind.  NOT a deadline miss:
                    # no SLO charge, no request.deadline anomaly — the
                    # server did nothing wrong, the work just aged out
                    with out_lock:
                        alive = worker.is_alive()
                        if alive:
                            abandoned.set()
                    if not alive:
                        break  # finished first: answer the real result
                    metrics.counter("editor.superseded_inflight").inc()
                    respond(ship_trace(_error(
                        "superseded by a newer request for the "
                        "same buffer", req_id, kind="superseded",
                    )))
                    return True
                slice_s = 0.05
                if expires is not None:
                    remaining = expires - time.monotonic()
                    if remaining <= 0:
                        timed_out = True
                        break
                    slice_s = min(slice_s, remaining)
                worker.join(slice_s)
            if timed_out and worker.is_alive():
                # the handler keeps running detached until its next
                # emit unwinds it; its response (and any late stream
                # lines) are dropped.  The flag is set under out_lock
                # so no emit is mid-write when the timeout answer goes
                # out
                with out_lock:
                    abandoned.set()
                metrics.counter("serve.requests_abandoned").inc()
                # SLO accounting + flight capture: the miss is charged
                # to the tenant(s) the request was serving, and the
                # ring around the abandonment is snapshotted
                tenants = _slo_tenants(req, base_dir)
                for tenant in tenants:
                    metrics.count_deadline_miss(tenant)
                flight.anomaly("request.deadline", {
                    "op": op, "id": req_id,
                    "deadline_s": deadline,
                    "tenants": list(tenants),
                })
                respond(ship_trace(_error(
                    f"deadline exceeded after {deadline:g}s",
                    req_id, kind="timeout",
                )))
                return True
            if "exc" in box:
                raise box["exc"]
            response, keep_going = box["out"]
        else:
            response, keep_going = dispatch()
    except _AbandonedRequest:
        raise  # the transport is gone: counted by dispatch_request
    except BatchManifestError as exc:
        respond(ship_trace(_error(str(exc), req_id)))
        return True
    except Exception as exc:  # must not kill the serving loop
        kind = _classify(exc)
        label = "internal error" if kind == "internal" else (
            f"{kind} error"
        )
        respond(ship_trace(
            _error(f"{label}: {exc}", req_id, kind=kind)
        ))
        return True
    if req_id is not None:
        # the request id wins over a job spec's defaulted id
        response["id"] = req_id
    response.setdefault(
        "seconds", round(time.perf_counter() - started, 4)
    )
    # ship the request's span segment home: exactly the events tagged
    # with this trace (concurrent requests keep theirs), including any
    # pool-worker events already ingested under the same trace id
    respond(ship_trace(response))
    return keep_going


def serve_loop(in_stream=None, out_stream=None) -> int:
    """Serve requests until shutdown/EOF/drain.  Streams default to
    stdin/stdout (the ``operator-forge serve`` entry point)."""
    in_stream = in_stream if in_stream is not None else sys.stdin
    out_stream = out_stream if out_stream is not None else sys.stdout
    base_dir = os.getcwd()
    # spans + the always-on event ring + the flight recorder, for the
    # loop's lifetime (refcounted: see retain_server_telemetry)
    retain_server_telemetry()
    _drain.clear()
    installed = []

    # one writer at a time: with a deadline configured the handler runs
    # on its own thread, and its stream emits must serialize against
    # the main thread's timeout response or the line-oriented protocol
    # could interleave
    out_lock = threading.Lock()

    def _respond_locked(payload: dict) -> None:
        # every error response is accounted by kind — the serve.errors
        # taxonomy the stats op surfaces
        _count_error(payload)
        out_stream.write(json.dumps(payload) + "\n")
        out_stream.flush()

    def respond(payload: dict) -> None:
        with out_lock:
            _respond_locked(payload)

    def drained_exit() -> int:
        respond({"ok": True, "op": "shutdown", "drained": True})
        return 0

    deadline = request_timeout()
    _busy[0] = False
    lines = iter(in_stream)
    try:
        # handlers are installed inside this try: from the first
        # installed signal on, a SIGTERM/SIGINT can raise _DrainSignal,
        # and raising it anywhere outside the except below would crash
        # the loop with a traceback instead of the drained exit 0 the
        # protocol promises
        if threading.current_thread() is threading.main_thread():
            import signal

            for signum in (signal.SIGTERM, signal.SIGINT):
                try:
                    installed.append(
                        (signum, signal.signal(signum, request_shutdown))
                    )
                except (ValueError, OSError):  # pragma: no cover
                    pass
        while True:
            # every iteration — including the error/timeout `continue`
            # paths below — re-checks the drain flag BEFORE blocking on
            # the next read: a signal that landed mid-request (busy, so
            # the handler didn't raise) must drain here, not sit parked
            # behind a read that may never see another line
            if _drain.is_set():
                return drained_exit()
            line = next(lines, None)
            if line is None:  # EOF
                break
            if _drain.is_set():  # shutdown arrived during the read
                return drained_exit()
            # dispatch-through-respond runs busy: a shutdown signal
            # landing there only sets the drain flag and the request
            # finishes (drain is checked at the top of the next
            # iteration).  Only an idle read blocked in ``in_stream``
            # is interrupted, via the handler's _DrainSignal (caught
            # below)
            _busy[0] = True
            try:
                line = line.strip()
                if not line:
                    continue
                try:
                    req = json.loads(line)
                except json.JSONDecodeError as exc:
                    respond(_error(f"invalid JSON: {exc}"))
                    continue
                if not isinstance(req, dict):
                    respond(_error("request must be a JSON object"))
                    continue
                keep_going = dispatch_request(
                    req, base_dir, out_lock, _respond_locked, deadline
                )
                if not keep_going:
                    # disarm request_shutdown's idle raise before
                    # leaving: a signal landing in the teardown window
                    # (the outer finally restoring handlers) would
                    # otherwise raise _DrainSignal past the except
                    # below and crash the clean exit with a traceback.
                    # _busy is still True here, so the set itself is
                    # race-free
                    _drain.set()
                    return 0
            finally:
                _busy[0] = False
        drained = _drain.is_set()
        _drain.set()  # EOF: disarm the teardown window (see above)
        if drained:
            return drained_exit()
        return 0
    except _DrainSignal:
        # a shutdown signal broke the idle blocking read (the rare
        # window between reading a line and going busy drops that
        # just-read, not-yet-started request — drain only promises
        # finishing in-flight work)
        return drained_exit()
    finally:
        if installed:
            import signal

            for signum, previous in installed:
                try:
                    signal.signal(signum, previous)
                except (ValueError, OSError):  # pragma: no cover
                    pass
        # the drain-path export + refcounted global release: a
        # `trace`-wrapped (or env-traced) server writes its timeline
        # HERE, not only at the outermost main() exit
        release_server_telemetry()
