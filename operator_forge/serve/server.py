"""``operator-forge serve`` — a persistent request loop.

Keeps one resident process hot: the argument parser, the gocheck stdlib
manifest, the closure-compiled interpreter bodies, and every
content-addressed cache survive across requests, so request N+1 starts
where a one-shot CLI invocation would have to re-prime from zero.

Protocol: one JSON object per stdin line, one JSON response per stdout
line (always exactly one, flushed; job/batch stdout is captured into
the response, never interleaved with the protocol stream):

- ``{"op": "ping"}`` — liveness + version;
- ``{"op": "job", "job": {<job spec>}}`` (or the spec inlined with a
  ``command`` key) — run one init/create-api/vet/lint/test job;
- ``{"op": "batch", "jobs": [<specs...>]}`` — run a batch through the
  orchestrator (grouped, fanned out, input-order results);
- ``{"op": "watch", "jobs": [<specs...>], "cycles": N}`` — the edit
  loop: run the jobs, then poll their input trees (``interval``
  seconds, default 0.5) and re-run the minimal set on every change.
  The one *streaming* op: each cycle emits its own response line
  (``"op": "watch"``, per-cycle ``graph`` reuse counts), and a final
  ``{"op": "watch", "done": true, "cycles": N}`` line closes the
  request;
- ``{"op": "stats"}`` — per-namespace cache hit/miss counters with
  ratios (stable key order), the dependency graph's cumulative
  dirty/reused/recomputed counters, the metrics registry
  (counters/gauges + p50/p99 latency histograms for serve jobs and
  watch cycles), the graph's recorded invalidation provenance, and
  the span table the per-request ``serve:*`` spans feed;
- ``{"op": "explain", "path": <root>, "changed": [...]}`` — the
  invalidation-provenance report: for each changed file, the
  deterministic chain of artifacts its edit dirties (derived
  structurally — byte-identical across cache modes and worker
  backends).  With ``changed`` omitted, the last ``watch`` cycle's
  recorded change set answers "why did the last cycle recompute?";
- ``{"op": "shutdown"}`` — acknowledge and exit 0 (EOF does the same).

Malformed lines answer ``{"ok": false, "error": ...}`` and the loop
continues; a request's ``id`` is echoed in its response so pipelined
clients can correlate.  Relative job paths resolve against the server's
working directory.
"""

from __future__ import annotations

import json
import sys
import time

from .. import __version__
from ..perf import metrics, spans
from ..perf.depgraph import GRAPH
from .batch import run_batch
from .jobs import BatchManifestError, jobs_from_specs
from .runner import run_job


def _error(message: str, req_id=None) -> dict:
    out = {"ok": False, "error": message}
    if req_id is not None:
        out["id"] = req_id
    return out


def _handle(req: dict, base_dir: str, emit=None) -> tuple:
    """Dispatch one request; returns (response dict, keep_going).
    ``emit`` delivers the intermediate lines of streaming ops (watch)."""
    op = req.get("op") or ("job" if "command" in req else None)
    req_id = req.get("id")
    if op == "ping":
        return ({"ok": True, "op": "ping", "version": __version__}, True)
    if op == "shutdown":
        return ({"ok": True, "op": "shutdown"}, False)
    if op == "stats":
        return (
            {"ok": True, "op": "stats", "cache": metrics.cache_report(),
             "graph": GRAPH.counters(),
             "metrics": metrics.snapshot(),
             "provenance": {
                 "last_invalidation": GRAPH.last_invalidation(),
                 "recorded": GRAPH.provenance(),
             },
             "spans": spans.snapshot()},
            True,
        )
    if op == "explain":
        import os as _os

        from ..gocheck.explain import explain_report, explain_summary
        from . import watch as watch_mod

        root = req.get("path") or base_dir
        if not _os.path.isabs(root):
            root = _os.path.normpath(_os.path.join(base_dir, root))
        changed = req.get("changed")
        removed = req.get("removed") or []
        if changed is not None or "removed" in req:
            # an explicit change set — a removed-only request counts
            if not _os.path.isdir(root):
                return (_error(
                    f"explain: {root} is not a directory", req_id), True)
            changed = changed or []
            # one shared import map: summary and report each need it
            from ..gocheck.explain import package_imports

            imports = package_imports(root)
            return (
                {"ok": True, "op": "explain",
                 "path": req.get("path") or root,
                 "changes": explain_summary(
                     root, changed, removed, imports=imports),
                 "report": explain_report(
                     root, changed, removed, imports=imports)},
                True,
            )
        # no explicit change set: answer for the last watch cycle,
        # deriving each file against the watch root it was recorded
        # under (rels are relative to THAT root, not the request path)
        roots, changes, report = watch_mod.last_cycle_explain()
        if not roots:
            return (_error(
                "explain: no change set — pass \"changed\": [...] "
                "or run a watch cycle first", req_id), True)
        return (
            {"ok": True, "op": "explain", "roots": roots,
             "changes": changes, "report": report},
            True,
        )
    if op == "watch":
        from .watch import watch_loop

        jobs = jobs_from_specs(req.get("jobs"), base_dir)
        cycles = req.get("cycles", 1)
        if not isinstance(cycles, int) or cycles < 1:
            return (_error("watch: cycles must be a positive integer",
                           req_id), True)

        def emit_cycle(payload: dict) -> None:
            payload["ok"] = bool(payload["ok"])
            if req_id is not None:
                payload["id"] = req_id
            if emit is not None:
                emit(payload)

        ran = watch_loop(
            jobs, emit_cycle, cycles=cycles,
            interval=float(req.get("interval", 0.5)),
        )
        return ({"ok": True, "op": "watch", "done": True,
                 "cycles": ran}, True)
    if op == "job":
        spec = req.get("job") if "job" in req else {
            k: v for k, v in req.items() if k not in ("op",)
        }
        jobs = jobs_from_specs([spec], base_dir)
        result = run_job(jobs[0]).to_dict()
        result["op"] = "job"
        return (result, True)
    if op == "batch":
        specs = req.get("jobs")
        jobs = jobs_from_specs(specs, base_dir)
        started = time.perf_counter()
        results = run_batch(jobs)
        return (
            {
                "ok": all(r.ok for r in results),
                "op": "batch",
                "results": [r.to_dict() for r in results],
                "cached": sum(1 for r in results if r.cached),
                "seconds": round(time.perf_counter() - started, 4),
            },
            True,
        )
    return (_error(f"unknown op {op!r}", req_id), True)


def serve_loop(in_stream=None, out_stream=None) -> int:
    """Serve requests until shutdown/EOF.  Streams default to
    stdin/stdout (the ``operator-forge serve`` entry point)."""
    import os

    in_stream = in_stream if in_stream is not None else sys.stdin
    out_stream = out_stream if out_stream is not None else sys.stdout
    base_dir = os.getcwd()
    # per-request spans are part of the protocol (the `stats` op reports
    # them), so collection is on for the loop's lifetime regardless of
    # OPERATOR_FORGE_PROFILE
    spans.enable(True)

    def respond(payload: dict) -> None:
        out_stream.write(json.dumps(payload) + "\n")
        out_stream.flush()

    try:
        for line in in_stream:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except json.JSONDecodeError as exc:
                respond(_error(f"invalid JSON: {exc}"))
                continue
            if not isinstance(req, dict):
                respond(_error("request must be a JSON object"))
                continue
            op = req.get("op") or ("job" if "command" in req else "?")
            started = time.perf_counter()
            try:
                with spans.span(f"serve:{op}"):
                    response, keep_going = _handle(req, base_dir,
                                                   emit=respond)
            except BatchManifestError as exc:
                respond(_error(str(exc), req.get("id")))
                continue
            except Exception as exc:  # bad request must not kill the loop
                respond(_error(f"internal error: {exc}", req.get("id")))
                continue
            if req.get("id") is not None:
                # the request id wins over a job spec's defaulted id
                response["id"] = req.get("id")
            response.setdefault(
                "seconds", round(time.perf_counter() - started, 4)
            )
            respond(response)
            if not keep_going:
                return 0
        return 0
    finally:
        spans.use_env()
