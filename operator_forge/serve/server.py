"""``operator-forge serve`` — a persistent request loop.

Keeps one resident process hot: the argument parser, the gocheck stdlib
manifest, the closure-compiled interpreter bodies, and every
content-addressed cache survive across requests, so request N+1 starts
where a one-shot CLI invocation would have to re-prime from zero.

Protocol: one JSON object per stdin line, one JSON response per stdout
line (always exactly one, flushed; job/batch stdout is captured into
the response, never interleaved with the protocol stream):

- ``{"op": "ping"}`` — liveness + version;
- ``{"op": "job", "job": {<job spec>}}`` (or the spec inlined with a
  ``command`` key) — run one init/create-api/vet/lint/test job;
- ``{"op": "batch", "jobs": [<specs...>]}`` — run a batch through the
  orchestrator (grouped, fanned out, input-order results);
- ``{"op": "watch", "jobs": [<specs...>], "cycles": N}`` — the edit
  loop: run the jobs, then poll their input trees (``interval``
  seconds, default 0.5) and re-run the minimal set on every change.
  The one *streaming* op: each cycle emits its own response line
  (``"op": "watch"``, per-cycle ``graph`` reuse counts), and a final
  ``{"op": "watch", "done": true, "cycles": N}`` line closes the
  request;
- ``{"op": "stats"}`` — per-namespace cache hit/miss counters with
  ratios (stable key order), the dependency graph's cumulative
  dirty/reused/recomputed counters, and the span table the
  per-request ``serve:*`` spans feed;
- ``{"op": "shutdown"}`` — acknowledge and exit 0 (EOF does the same).

Malformed lines answer ``{"ok": false, "error": ...}`` and the loop
continues; a request's ``id`` is echoed in its response so pipelined
clients can correlate.  Relative job paths resolve against the server's
working directory.
"""

from __future__ import annotations

import json
import sys
import time

from .. import __version__
from ..perf import cache as pf_cache
from ..perf import spans
from ..perf.depgraph import GRAPH
from .batch import run_batch
from .jobs import BatchManifestError, jobs_from_specs
from .runner import run_job


def _error(message: str, req_id=None) -> dict:
    out = {"ok": False, "error": message}
    if req_id is not None:
        out["id"] = req_id
    return out


def _cache_report() -> dict:
    """Per-namespace hit/miss counters with hit ratios, stable key
    order (namespaces sorted; hits/misses/ratio fixed within)."""
    out: dict = {}
    snap = pf_cache.stats()
    for stage in sorted(snap):
        counts = snap[stage]
        hits = counts.get("hits", 0)
        misses = counts.get("misses", 0)
        total = hits + misses
        out[stage] = {
            "hits": hits,
            "misses": misses,
            "ratio": round(hits / total, 4) if total else 0.0,
        }
    return out


def _handle(req: dict, base_dir: str, emit=None) -> tuple:
    """Dispatch one request; returns (response dict, keep_going).
    ``emit`` delivers the intermediate lines of streaming ops (watch)."""
    op = req.get("op") or ("job" if "command" in req else None)
    req_id = req.get("id")
    if op == "ping":
        return ({"ok": True, "op": "ping", "version": __version__}, True)
    if op == "shutdown":
        return ({"ok": True, "op": "shutdown"}, False)
    if op == "stats":
        return (
            {"ok": True, "op": "stats", "cache": _cache_report(),
             "graph": GRAPH.counters(), "spans": spans.snapshot()},
            True,
        )
    if op == "watch":
        from .watch import watch_loop

        jobs = jobs_from_specs(req.get("jobs"), base_dir)
        cycles = req.get("cycles", 1)
        if not isinstance(cycles, int) or cycles < 1:
            return (_error("watch: cycles must be a positive integer",
                           req_id), True)

        def emit_cycle(payload: dict) -> None:
            payload["ok"] = bool(payload["ok"])
            if req_id is not None:
                payload["id"] = req_id
            if emit is not None:
                emit(payload)

        ran = watch_loop(
            jobs, emit_cycle, cycles=cycles,
            interval=float(req.get("interval", 0.5)),
        )
        return ({"ok": True, "op": "watch", "done": True,
                 "cycles": ran}, True)
    if op == "job":
        spec = req.get("job") if "job" in req else {
            k: v for k, v in req.items() if k not in ("op",)
        }
        jobs = jobs_from_specs([spec], base_dir)
        result = run_job(jobs[0]).to_dict()
        result["op"] = "job"
        return (result, True)
    if op == "batch":
        specs = req.get("jobs")
        jobs = jobs_from_specs(specs, base_dir)
        started = time.perf_counter()
        results = run_batch(jobs)
        return (
            {
                "ok": all(r.ok for r in results),
                "op": "batch",
                "results": [r.to_dict() for r in results],
                "cached": sum(1 for r in results if r.cached),
                "seconds": round(time.perf_counter() - started, 4),
            },
            True,
        )
    return (_error(f"unknown op {op!r}", req_id), True)


def serve_loop(in_stream=None, out_stream=None) -> int:
    """Serve requests until shutdown/EOF.  Streams default to
    stdin/stdout (the ``operator-forge serve`` entry point)."""
    import os

    in_stream = in_stream if in_stream is not None else sys.stdin
    out_stream = out_stream if out_stream is not None else sys.stdout
    base_dir = os.getcwd()
    # per-request spans are part of the protocol (the `stats` op reports
    # them), so collection is on for the loop's lifetime regardless of
    # OPERATOR_FORGE_PROFILE
    spans.enable(True)

    def respond(payload: dict) -> None:
        out_stream.write(json.dumps(payload) + "\n")
        out_stream.flush()

    try:
        for line in in_stream:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except json.JSONDecodeError as exc:
                respond(_error(f"invalid JSON: {exc}"))
                continue
            if not isinstance(req, dict):
                respond(_error("request must be a JSON object"))
                continue
            op = req.get("op") or ("job" if "command" in req else "?")
            started = time.perf_counter()
            try:
                with spans.span(f"serve:{op}"):
                    response, keep_going = _handle(req, base_dir,
                                                   emit=respond)
            except BatchManifestError as exc:
                respond(_error(str(exc), req.get("id")))
                continue
            except Exception as exc:  # bad request must not kill the loop
                respond(_error(f"internal error: {exc}", req.get("id")))
                continue
            if req.get("id") is not None:
                # the request id wins over a job spec's defaulted id
                response["id"] = req.get("id")
            response.setdefault(
                "seconds", round(time.perf_counter() - started, 4)
            )
            respond(response)
            if not keep_going:
                return 0
        return 0
    finally:
        spans.use_env()
