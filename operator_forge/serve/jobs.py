"""Job model for ``operator-forge batch`` and ``serve``.

A *job* is one CLI-equivalent request — ``init``, ``create-api``,
``vet``, ``lint``, or ``test`` — normalized from a manifest entry (or a
serve request) into the argv vector :func:`operator_forge.cli.main.main`
accepts.  ``lint`` is ``vet`` for machines: it runs the analyzer
framework (optionally a selected subset via ``analyzers: a,b``) and
always emits one JSON diagnostic object per line, so batch/serve
clients never parse human text.  Manifests are YAML (or JSON — a JSON
document is valid YAML):

.. code-block:: yaml

    jobs:
      - command: init
        workload_config: configs/store/workload.yaml
        output_dir: out/store
        repo: github.com/acme/store
      - command: create-api
        workload_config: configs/store/workload.yaml
        output_dir: out/store
      - command: vet
        path: out/store
      - command: test
        path: out/store
        e2e: false

Relative paths resolve against the manifest's directory (for serve
requests: the server's working directory).  Job ids default to
``job-<n>`` in input order and must be unique — results are reported
by id, in input order, regardless of execution backend.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from ..utils import yamlcompat as pyyaml


class BatchManifestError(Exception):
    """Raised for a malformed batch manifest or job spec."""


#: command name -> the spec keys it accepts beyond `command`/`id`
COMMANDS = {
    "init": ("workload_config", "output_dir", "repo"),
    "create-api": ("workload_config", "output_dir"),
    "vet": ("path",),
    "lint": ("path", "analyzers"),
    "test": ("path", "e2e", "run"),
}

_ALIASES = {"create api": "create-api", "create_api": "create-api"}


@dataclass
class Job:
    """One normalized batch/serve job."""

    index: int
    id: str
    command: str
    workload_config: str = ""
    output_dir: str = ""
    path: str = ""
    repo: str = ""
    e2e: bool = False
    run: str = ""
    analyzers: str = ""

    def target(self) -> str:
        """The directory this job is 'about' — its output dir for
        generation commands, its project path for checking commands."""
        root = self.output_dir if self.command in (
            "init", "create-api"
        ) else self.path
        return os.path.abspath(root)

    def reads(self) -> tuple:
        """Directories whose bytes this job's outcome depends on: the
        whole config directory (manifests live beside the workload
        config, referenced by globs) for generation, the project tree
        for checking."""
        if self.command in ("init", "create-api"):
            return (
                os.path.dirname(os.path.abspath(self.workload_config)),
            )
        return (os.path.abspath(self.path),)

    def writes(self) -> tuple:
        """Directories this job mutates (checking commands write
        nothing)."""
        if self.command in ("init", "create-api"):
            return (os.path.abspath(self.output_dir),)
        return ()

    def to_spec(self) -> dict:
        """The job as a serve-protocol spec mapping (paths already
        resolved) — how ``batch --addr`` ships a locally loaded
        manifest to a running daemon."""
        out = {"command": self.command, "id": self.id}
        for key in COMMANDS[self.command]:
            value = getattr(self, key.replace("-", "_"))
            if value:
                out[key] = value
        return out

    def argv(self) -> list:
        if self.command == "init":
            out = ["init", "--workload-config", self.workload_config,
                   "--output-dir", self.output_dir]
            if self.repo:
                out += ["--repo", self.repo]
            return out
        if self.command == "create-api":
            return ["create", "api", "--workload-config",
                    self.workload_config, "--output-dir", self.output_dir]
        if self.command == "vet":
            return ["vet", self.path]
        if self.command == "lint":
            # structured by design: lint exists so batch/serve clients
            # stop parsing human vet text
            out = ["vet", self.path, "--json"]
            if self.analyzers:
                out += ["--analyzers", self.analyzers]
            return out
        out = ["test", self.path]
        if self.e2e:
            out.append("--e2e")
        if self.run:
            out += ["--run", self.run]
        return out


@dataclass
class JobResult:
    """Outcome of one executed (or replayed) job."""

    id: str
    command: str
    rc: int
    stdout: str
    stderr: str
    seconds: float
    cached: bool = False
    index: int = field(default=-1, compare=False)

    @property
    def ok(self) -> bool:
        return self.rc == 0

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "command": self.command,
            "ok": self.ok,
            "rc": self.rc,
            "stdout": self.stdout,
            "stderr": self.stderr,
            "seconds": round(self.seconds, 4),
            "cached": self.cached,
        }


def _resolve(base_dir: str, value: str) -> str:
    if not value or os.path.isabs(value):
        return value
    return os.path.normpath(os.path.join(base_dir, value))


#: serve-request envelope keys that are protocol metadata, never part
#: of an inlined job spec — strip them in ONE place (a key added here
#: is honored by every transport's spec extraction at once)
ENVELOPE_KEYS = ("op", "trace")


def specs_from_request(req: dict):
    """The raw job-spec list a serve request carries: the ``job`` key
    (or the spec inlined beside ``op``) for the job op, the ``jobs``
    list for batch/watch, ``None`` for every other op.  Shared by the
    stdio/daemon/fleet dispatchers, the daemon's path-lock root
    derivation, and the SLO tenant attribution, so the envelope-key
    strip can't drift between them."""
    op = req.get("op") or ("job" if "command" in req else None)
    if op == "job":
        return [
            req.get("job") if "job" in req
            else {
                k: v for k, v in req.items()
                if k not in ENVELOPE_KEYS
            }
        ]
    if op in ("batch", "watch", "subscribe"):
        return req.get("jobs")
    return None


def supersede_key(req: dict, base_dir: str):
    """The coalescing identity of an editor-loop request, or ``None``
    when the request must never be superseded.

    Two requests from the *same session* with the same key describe the
    same buffer's state at different instants — only the newest matters,
    so the daemon answers the older one with the ``superseded`` taxonomy
    kind instead of burning a dispatcher slot on stale work:

    - ``("overlay", abspath)`` — overlay registrations for one path.
      Queue-supersede only: an in-flight overlay write has already
      mutated the store, so it is never abandoned mid-application.
    - ``("vet", command, abspath, analyzers)`` — a single read-only
      vet/lint job.  Safe to supersede both queued and in-flight (the
      work is pure; abandoning it loses nothing but stale diagnostics).

    Everything else — generation jobs, tests, batches, watches, fences
    — returns ``None``: superseding work with side effects or multiple
    targets would change observable state.
    """
    op = req.get("op") or ("job" if "command" in req else None)
    if op == "overlay":
        path = req.get("path")
        if not isinstance(path, str) or not path:
            return None
        return ("overlay", os.path.abspath(_resolve(base_dir, path)))
    if op != "job":
        return None
    specs = specs_from_request(req)
    if not specs or len(specs) != 1 or not isinstance(specs[0], dict):
        return None
    spec = specs[0]
    command = _ALIASES.get(
        str(spec.get("command", "")).strip(),
        str(spec.get("command", "")).strip(),
    )
    if command not in ("vet", "lint"):
        return None
    path = str(spec.get("path", ""))
    if not path:
        return None
    return (
        "vet", command,
        os.path.abspath(_resolve(base_dir, path)),
        str(spec.get("analyzers", "")),
    )


def jobs_from_specs(specs, base_dir: str) -> list:
    """Normalize a list of spec mappings into :class:`Job` objects,
    validating commands, required fields, and id uniqueness."""
    if not isinstance(specs, (list, tuple)) or not specs:
        raise BatchManifestError("manifest contains no jobs")
    jobs = []
    seen_ids: set = set()
    for i, spec in enumerate(specs):
        label = f"job {i + 1}"
        if not isinstance(spec, dict):
            raise BatchManifestError(f"{label}: expected a mapping")
        raw_cmd = str(spec.get("command", "")).strip()
        command = _ALIASES.get(raw_cmd, raw_cmd)
        if command not in COMMANDS:
            raise BatchManifestError(
                f"{label}: unknown command {raw_cmd!r}; known: "
                + ", ".join(sorted(COMMANDS))
            )
        allowed = COMMANDS[command] + ("command", "id")
        unknown = sorted(set(spec) - set(allowed))
        if unknown:
            raise BatchManifestError(
                f"{label} ({command}): unknown keys {unknown}; "
                f"allowed: {sorted(allowed)}"
            )
        job_id = str(spec.get("id") or f"job-{i + 1}")
        if job_id in seen_ids:
            raise BatchManifestError(f"duplicate job id {job_id!r}")
        seen_ids.add(job_id)
        job = Job(
            index=i,
            id=job_id,
            command=command,
            workload_config=_resolve(
                base_dir, str(spec.get("workload_config", ""))
            ),
            output_dir=_resolve(base_dir, str(spec.get("output_dir", ""))),
            path=_resolve(base_dir, str(spec.get("path", ""))),
            repo=str(spec.get("repo", "")),
            e2e=bool(spec.get("e2e", False)),
            run=str(spec.get("run", "")),
            analyzers=str(spec.get("analyzers", "")),
        )
        if command in ("init", "create-api"):
            if not job.workload_config or not job.output_dir:
                raise BatchManifestError(
                    f"{label} ({command}): workload_config and "
                    "output_dir are required"
                )
        elif not job.path:
            raise BatchManifestError(
                f"{label} ({command}): path is required"
            )
        jobs.append(job)
    return jobs


def specs_key(jobs) -> str:
    """Deterministic 16-hex identity of a normalized job list — the
    idempotency key the fleet coordinator tracks submissions by.  Two
    submissions of the same manifest (same commands, same resolved
    paths, same ids) share one key, so a coordinator that re-dispatches
    an in-flight submission after a daemon death is provably re-running
    *the same* work, and the content-keyed replay layer underneath
    guarantees the re-run is byte-identical."""
    import hashlib
    import json

    payload = json.dumps(
        [job.to_spec() for job in jobs], sort_keys=True,
        separators=(",", ":"),
    )
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()[:16]


def load_manifest(path: str) -> list:
    """Parse a manifest file into validated jobs (paths resolved
    against the manifest's directory)."""
    try:
        with open(path, encoding="utf-8") as handle:
            data = pyyaml.safe_load(handle.read())
    except OSError as exc:
        raise BatchManifestError(f"cannot read manifest: {exc}") from exc
    except pyyaml.YAMLError as exc:
        raise BatchManifestError(f"invalid manifest YAML: {exc}") from exc
    if isinstance(data, dict):
        specs = data.get("jobs")
    else:
        specs = data
    if not isinstance(specs, list):
        raise BatchManifestError(
            "manifest must be a list of jobs or a mapping with a "
            "'jobs' list"
        )
    return jobs_from_specs(specs, os.path.dirname(os.path.abspath(path)))
