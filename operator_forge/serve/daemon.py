"""``operator-forge daemon`` — the serve protocol for N clients.

The stdio ``serve`` loop keeps one resident process hot for ONE caller.
This module is the multi-client transport the reference toolchain
solves with long-lived daemons (``gopls -listen``, the Bazel server):
one hot process — warm ContentCache tiers, compiled interpreter
closures, the pre-forked worker pool — multiplexed across many editors
and CI shards over a unix or TCP socket.

Architecture:

- **listener** — ``daemon --listen <unix:/path|host:port>`` accepts up
  to ``OPERATOR_FORGE_DAEMON_CLIENTS`` concurrent connections; each
  becomes a :class:`~operator_forge.serve.session.Session` speaking the
  existing newline-JSON ping/job/batch/watch/stats/explain/shutdown
  protocol (a ``shutdown`` op from any client drains the whole daemon,
  like ``gopls`` exit / ``bazel shutdown``);
- **fair scheduler** — sessions own bounded request queues and a pool
  of dispatcher threads (``OPERATOR_FORGE_DAEMON_WORKERS``) serves them
  ROUND-ROBIN, one in-flight request per session, so a client that
  queued a 64-job batch cannot starve an editor's single vet: the next
  free dispatcher always takes the next *session's* request, not the
  next request of the busiest session.  Queue wait is observable
  (``daemon.queue_wait.seconds`` histogram, p50/p99 via ``stats``);
- **backpressure** — admission is bounded twice: per session
  (``OPERATOR_FORGE_DAEMON_SESSION_QUEUE``) and globally
  (``OPERATOR_FORGE_DAEMON_QUEUE``).  An over-budget request is
  answered immediately with the ``busy`` taxonomy kind and a
  ``retry_after`` hint — never buffered without bound;
- **cross-session safety** — requests that touch overlapping trees
  serialize through a read/write path-lock (two clients hammering the
  same project run their jobs one at a time, byte-identical to a
  serial run; readers of one tree still fan out), while requests over
  disjoint trees run concurrently.  Replay records are additionally
  partitioned per project (:func:`operator_forge.serve.runner`'s
  scoped namespaces layered on ContentCache);
- **cache budgets under load** — a maintenance tick
  (``OPERATOR_FORGE_DAEMON_IDLE_GC_S``) calls
  :meth:`ContentCache.enforce_budget` so a long-lived daemon honors
  ``OPERATOR_FORGE_CACHE_MAX_MB`` on BOTH resident tiers (mem LRU
  eviction + disk LRU gc) even when no write ever crosses the
  amortized on-write threshold;
- **drain** — SIGTERM/SIGINT run the same
  :func:`~operator_forge.serve.server.request_shutdown` machinery as
  stdio serve (it lives once): the listener closes, in-flight requests
  finish and are answered, every session gets a final ``{"op":
  "shutdown", "drained": true}`` line, and the process exits 0.

:class:`DaemonClient` is the client side — ``operator-forge connect``
relays stdin/stdout to a daemon, and ``batch --addr`` runs a manifest
through one.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

from ..perf import cache as pf_cache
from ..perf import env_number, faults, flight, metrics, n_jobs, spans
from ..perf import overlay as pf_overlay
from ..perf import remote as pf_remote
from ..perf.netaddr import bind_listener, bound_address, connect_stream
from ..perf.netaddr import parse_listen
from . import runner
from . import server
from .batch import _overlaps
from .jobs import (
    BatchManifestError,
    jobs_from_specs,
    specs_from_request,
    supersede_key,
)
from .server import dispatch_request, request_timeout
from .session import CONNECT_RETRY_AFTER_S, Session

DEFAULT_MAX_CLIENTS = 64
DEFAULT_SESSION_QUEUE = 16
DEFAULT_GLOBAL_QUEUE = 256
DEFAULT_IDLE_GC_S = 30.0


def max_clients() -> int:
    """Concurrent-connection ceiling (``OPERATOR_FORGE_DAEMON_CLIENTS``,
    default 64); a connection beyond it is answered ``busy`` and
    closed."""
    return env_number(
        "OPERATOR_FORGE_DAEMON_CLIENTS", DEFAULT_MAX_CLIENTS,
        cast=int, minimum=1,
    )


def session_queue_depth() -> int:
    """Per-session pending-request bound
    (``OPERATOR_FORGE_DAEMON_SESSION_QUEUE``, default 16)."""
    return env_number(
        "OPERATOR_FORGE_DAEMON_SESSION_QUEUE", DEFAULT_SESSION_QUEUE,
        cast=int, minimum=1,
    )


def global_queue_depth() -> int:
    """Daemon-wide admission bound across all sessions
    (``OPERATOR_FORGE_DAEMON_QUEUE``, default 256)."""
    return env_number(
        "OPERATOR_FORGE_DAEMON_QUEUE", DEFAULT_GLOBAL_QUEUE,
        cast=int, minimum=1,
    )


def daemon_workers() -> int:
    """Dispatcher-thread count (``OPERATOR_FORGE_DAEMON_WORKERS``;
    default: CPU-bound-ish, at least 2 so a long batch never blocks an
    editor's vet)."""
    return env_number(
        "OPERATOR_FORGE_DAEMON_WORKERS",
        max(2, min(8, n_jobs())), cast=int, minimum=1,
    )


def idle_gc_interval() -> float:
    """Seconds between cache-budget maintenance ticks
    (``OPERATOR_FORGE_DAEMON_IDLE_GC_S``, default 30; <= 0 disables)."""
    return env_number(
        "OPERATOR_FORGE_DAEMON_IDLE_GC_S", DEFAULT_IDLE_GC_S,
        minimum=None,
    )


def supersede_enabled() -> bool:
    """Whether the editor-loop supersede path is on
    (``OPERATOR_FORGE_DAEMON_SUPERSEDE``; default on — set ``0``/
    ``off``/``false`` to disable, which is also how bench measures the
    no-supersede counterfactual)."""
    value = os.environ.get(
        "OPERATOR_FORGE_DAEMON_SUPERSEDE", ""
    ).strip().lower()
    return value not in ("0", "off", "false", "no")


def editor_boost_enabled() -> bool:
    """Whether interactive requests get dispatch priority
    (``OPERATOR_FORGE_DAEMON_EDITOR_BOOST``; default on — set ``0``/
    ``off``/``false`` to disable).  With the boost on, dispatchers
    defer *starting* new batch work while an editor-tier request is in
    flight; batch work already running finishes normally, so an
    edit-one-file re-vet executes nearly uncontended instead of
    timesharing with every background batch client."""
    value = os.environ.get(
        "OPERATOR_FORGE_DAEMON_EDITOR_BOOST", ""
    ).strip().lower()
    return value not in ("0", "off", "false", "no")


def _interactive_request(req: dict, session) -> bool:
    """Whether *req* rides the editor tier: the ``overlay`` op itself,
    or short-lived work (a job) issued by a session that holds live
    overlays.  Long-running ops (watch/subscribe/batch) never count —
    marking a forever-subscription interactive would pause batch
    dispatch for the life of the subscription."""
    op = req.get("op") or ("job" if "command" in req else None)
    if op == "overlay":
        return True
    if op != "job":
        return False
    return pf_overlay.owned(session.id) > 0


def lock_timeout() -> float:
    """How long a dispatcher waits for conflicting trees to free
    before answering ``busy`` (``OPERATOR_FORGE_DAEMON_LOCK_S``,
    default 60).  Bounded so a long-lived holder (a watch over the
    same tree, a deadline-abandoned writer still running detached) can
    only ever cost a conflicting client a retry, never a permanently
    parked dispatcher thread."""
    return env_number(
        "OPERATOR_FORGE_DAEMON_LOCK_S", 60.0, minimum=0.1
    )


def _request_roots(req: dict, base_dir: str) -> tuple:
    """(reads, writes) directory sets a request will touch — the
    daemon's cross-session conflict key.  Unparseable specs lock
    nothing (dispatch answers ``bad_request`` anyway).  This parses
    the specs a second time (``_handle`` parses them again inside the
    dispatch) — deliberate: the roots are needed BEFORE dispatch to
    take the locks, and spec normalization is path arithmetic, far
    below one job's tree-state snapshot cost."""
    op = req.get("op") or ("job" if "command" in req else None)
    if op == "fence":
        # the fleet's zombie fence: its roots are WRITE-locked so the
        # request queues behind any in-flight (or abandoned-but-still-
        # running) request touching those trees, and its reset runs
        # only once they are quiet
        roots = req.get("roots")
        reset = req.get("reset") or []
        if not isinstance(roots, list) or not isinstance(reset, list):
            return (), ()
        try:
            return (), tuple(sorted({
                os.path.abspath(str(p)) for p in list(roots) + list(reset)
            }))
        except (TypeError, ValueError):
            return (), ()
    specs = specs_from_request(req)
    if specs is None:
        return (), ()
    try:
        jobs = jobs_from_specs(specs, base_dir)
    except (BatchManifestError, TypeError, ValueError):
        return (), ()
    reads: list = []
    writes: list = []
    for job in jobs:
        for root in job.reads():
            if root not in reads:
                reads.append(root)
        for root in job.writes():
            if root not in writes:
                writes.append(root)
    return tuple(reads), tuple(writes)


def _trie_node() -> dict:
    """One path-trie node: children by path component, plus four
    counts — readers/writers whose held root ends exactly here
    (``sr``/``sw``) and readers/writers anywhere in this subtree,
    self included (``tr``/``tw``)."""
    return {"c": {}, "sr": 0, "sw": 0, "tr": 0, "tw": 0}


class _PathLocks:
    """All-or-nothing read/write locks over directory roots (nested
    dirs overlap, like the batch scheduler's conflict rule): writers
    exclude everything overlapping, readers exclude only overlapping
    writers.  Acquisition is atomic over the whole root set, so two
    requests can never deadlock holding halves of each other's roots,
    and BOUNDED: a conflict that does not clear within the timeout
    returns ``None`` so the caller answers ``busy`` instead of parking
    a dispatcher thread forever behind a long-lived holder.

    Conflict detection is a component-wise path TRIE (PR 17): the old
    linear sweep compared every held root against every requested root
    on every acquire attempt — O(held × requested × path length), and
    every blocked waiter re-runs it on each 0.25s poll, so a busy
    daemon (hundreds of held roots at monorepo scale) paid a
    super-linear admission cost (ROADMAP item 4's suspect, confirmed
    by bench's ``editor.path_locks`` before/after probe).  The trie
    answers one root's conflict in O(path components): a held WRITE on
    any proper ancestor conflicts (``sw``), a held read on an ancestor
    conflicts with a write request (``sr``), and the requested root's
    own node aggregates everything held at-or-below it (``tw``/``tr``).
    Component-boundary semantics are exactly the linear sweep's
    :func:`~operator_forge.serve.batch._overlaps` rule —
    :meth:`_conflicts_linear` is kept as the executable reference
    (tests assert equivalence on randomized root sets; bench times
    both)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._held: list = []  # (root, is_write)
        self._trie = _trie_node()

    @staticmethod
    def _parts(root: str) -> list:
        # no empty-component filtering: "/" splits to ['', ''] and
        # "/x" to ['', 'x'], which diverge at depth 1 — matching
        # _overlaps("/", "/x") == False exactly
        return root.split(os.sep)

    def _trie_add(self, root: str, is_write: bool) -> None:
        sub = "tw" if is_write else "tr"
        node = self._trie
        node[sub] += 1
        for part in self._parts(root):
            child = node["c"].get(part)
            if child is None:
                child = node["c"][part] = _trie_node()
            child[sub] += 1
            node = child
        node["sw" if is_write else "sr"] += 1

    def _trie_remove(self, root: str, is_write: bool) -> None:
        sub = "tw" if is_write else "tr"
        node = self._trie
        node[sub] -= 1
        chain = []
        for part in self._parts(root):
            chain.append((node, part))
            node = node["c"][part]
            node[sub] -= 1
        node["sw" if is_write else "sr"] -= 1
        # prune empty branches so a long-lived daemon's trie tracks the
        # live held set, not every root ever locked
        for parent, part in reversed(chain):
            child = parent["c"][part]
            if child["c"] or child["tr"] or child["tw"]:
                break
            del parent["c"][part]

    def _conflict_one(self, root: str, is_write: bool) -> bool:
        node = self._trie
        for part in self._parts(root):
            # node covers a PROPER prefix of root here: any held
            # writer there excludes us; a held reader excludes writes
            if node["sw"] or (is_write and node["sr"]):
                return True
            node = node["c"].get(part)
            if node is None:
                return False  # no held root shares this prefix
        # root's own node: everything held at-or-below overlaps
        return bool(node["tw"] or (is_write and node["tr"]))

    def _conflicts(self, reads, writes) -> bool:
        for w in writes:
            if self._conflict_one(w, True):
                return True
        for r in reads:
            if self._conflict_one(r, False):
                return True
        return False

    def _conflicts_linear(self, reads, writes) -> bool:
        """The pre-trie reference sweep — kept for the equivalence
        tests and bench's before/after note, not called on the hot
        path."""
        for root, held_write in self._held:
            for w in writes:
                if _overlaps(root, w):
                    return True
            if held_write:
                for r in reads:
                    if _overlaps(root, r):
                        return True
        return False

    def acquire(self, reads, writes, timeout=None, cancelled=None):
        """A token on success; ``None`` when the conflict did not
        clear within ``timeout``, the request was ``cancelled`` (its
        client disconnected), or a drain began mid-wait."""
        reads = tuple(sorted(set(reads)))
        writes = tuple(sorted(set(writes)))
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        with self._cond:
            while self._conflicts(reads, writes):
                if cancelled is not None and cancelled.is_set():
                    return None
                if server.draining():
                    return None
                wait = 0.25
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    wait = min(wait, remaining)
                self._cond.wait(wait)
            for root in reads:
                self._held.append((root, False))
                self._trie_add(root, False)
            for root in writes:
                self._held.append((root, True))
                self._trie_add(root, True)
        return (reads, writes)

    def release(self, token) -> None:
        if token is None:
            return
        reads, writes = token
        with self._cond:
            for root in reads:
                self._held.remove((root, False))
                self._trie_remove(root, False)
            for root in writes:
                self._held.remove((root, True))
                self._trie_remove(root, True)
            self._cond.notify_all()


class ForgeDaemon:
    """The multi-client daemon: listener + sessions + fair scheduler.

    With ``fleet`` set (a coordinator address), the daemon additionally
    maintains a *fleet link*: one background connection to the
    coordinator that registers this daemon (address + capacity) and
    then heartbeats on a fraction of the fleet lease interval, carrying
    the coordinator's placement signal — in-flight count, queued
    requests, and the PR 7 ``workers.degraded`` flag.  The link is
    self-healing: a coordinator restart (or dropped connection) is
    re-registered with bounded deterministic backoff, and the daemon
    keeps serving its direct clients throughout — fleet membership is
    additive, never load-bearing for local correctness."""

    def __init__(self, listen: str, clients=None, fleet: str = None):
        self.spec = parse_listen(listen)
        self._max_clients = clients if clients else max_clients()
        self.fleet_addr = fleet
        self._fleet_thread = None
        self.base_dir = os.getcwd()
        self._listener = None
        self._accept_thread = None
        self._dispatchers: list = []
        self._maintenance = None
        self._stop_event = threading.Event()
        self._cond = threading.Condition()
        self._sessions: list = []
        self._queued = 0  # global pending count, guarded by _cond
        self._rr = 0      # round-robin cursor, guarded by _cond
        # editor-tier requests in flight, guarded by _cond: while
        # nonzero, dispatchers defer starting new batch work
        self._interactive = 0
        self._next_sid = 0
        self._locks = _PathLocks()
        self._stop_lock = threading.Lock()
        self._stopped = False
        self._stop_done = threading.Event()

    # -- lifecycle -------------------------------------------------------

    def address(self) -> str:
        return bound_address(self.spec, self._listener)

    def _bind(self) -> None:
        # the bounded accept timeout: neither close() nor shutdown()
        # reliably wakes a thread blocked in accept() (AF_UNIX on
        # Linux), so the accept loop wakes on its own to observe the
        # drain flag — worst-case drain latency is one poll
        self._listener = bind_listener(
            self.spec, backlog=min(128, self._max_clients * 2),
            accept_timeout=0.5,
        )

    def _boot(self) -> None:
        # per-request serve:* spans, the always-on event ring (the
        # flight recorder's black box + the distributed-trace segment
        # source), refcounted with any sibling in-process server
        server.retain_server_telemetry()
        server._drain.clear()
        self._stop_event.clear()
        server.on_drain(self._on_drain)
        server.register_stats_source("daemon", self._stats_payload)
        metrics.register_gauge(
            "daemon.active_sessions", lambda: len(self._sessions)
        )
        metrics.register_gauge(
            "daemon.queued_requests", lambda: self._queued
        )
        # concurrent clients on different trees share one ContentCache:
        # partition the replay namespaces per project
        runner.set_project_scoping(True)
        for i in range(daemon_workers()):
            thread = threading.Thread(
                target=self._dispatch_loop, daemon=True,
                name=f"daemon-dispatch-{i}",
            )
            thread.start()
            self._dispatchers.append(thread)
        if idle_gc_interval() > 0:
            self._maintenance = threading.Thread(
                target=self._maintenance_loop, daemon=True,
                name="daemon-maintenance",
            )
            self._maintenance.start()
        if self.fleet_addr:
            self._fleet_thread = threading.Thread(
                target=self._fleet_link_loop, daemon=True,
                name="daemon-fleet-link",
            )
            self._fleet_thread.start()

    def start(self) -> None:
        """Bind and accept on a background thread (tests, bench).  The
        CLI uses :meth:`serve_forever` instead."""
        if self._listener is None:
            self._bind()
        self._boot()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="daemon-accept",
        )
        self._accept_thread.start()

    def serve_forever(self) -> None:
        """Blocking accept loop on the calling thread (the CLI path);
        a drain — signal or a client's shutdown op — returns."""
        if self._listener is None:
            self._bind()
        self._boot()
        self._accept_loop()

    def _on_drain(self) -> None:
        # runs (possibly in signal-handler context) when a drain
        # begins: break the blocked accept and wake the scheduler so
        # dispatchers can retire.  Must stay tiny and non-blocking.
        try:
            # new connections are refused from here on; the accept
            # thread itself wakes via its bounded accept timeout
            # (neither close nor shutdown reliably interrupts a
            # blocked accept on AF_UNIX)
            self._listener.close()
        except (OSError, AttributeError):
            pass
        self._stop_event.set()
        # best-effort wake: this may run as a SIGNAL HANDLER on the
        # main thread, and the accept loop (same thread) may hold
        # _cond at that instant — a blocking acquire would
        # self-deadlock.  Dispatchers re-check the drain flag on a
        # bounded wait anyway, so a skipped notify only costs latency
        if self._cond.acquire(blocking=False):
            try:
                self._cond.notify_all()
            finally:
                self._cond.release()

    def _accept_loop(self) -> None:
        while not server.draining():
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue  # periodic wakeup: re-check the drain flag
            except OSError:
                return  # listener closed: draining
            conn.settimeout(None)  # sessions use blocking I/O
            if server.draining():
                try:
                    conn.close()
                except OSError:
                    pass
                return
            with self._cond:
                active = len(self._sessions)
            if active >= self._max_clients:
                # admission control at the connection level: answer
                # once (the busy taxonomy kind), close, keep listening
                metrics.counter("daemon.busy_rejections").inc()
                payload = server._error(
                    f"daemon at its {self._max_clients}-client "
                    "capacity", kind="busy",
                )
                payload["retry_after"] = CONNECT_RETRY_AFTER_S
                try:
                    conn.sendall(
                        (json.dumps(payload) + "\n").encode("utf-8")
                    )
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            with self._cond:
                self._next_sid += 1
                session = Session(self, conn, f"s{self._next_sid}")
                self._sessions.append(session)
            metrics.counter("daemon.sessions_opened").inc()
            metrics.register_gauge(
                f"daemon.session.{session.id}.queue_depth",
                session.queue_depth,
            )
            session.start()

    # -- admission (reader threads) --------------------------------------

    def _enqueue(self, session: Session, req: dict) -> None:
        if req.get("op") == "overlay":
            # session-scope the overlay: the daemon stamps ownership
            # (overwriting anything the client claimed) so the store
            # can be cleared when THIS session closes
            req["_owner"] = session.id
        key = (
            supersede_key(req, self.base_dir)
            if supersede_enabled() else None
        )
        rejected = None
        stale: list = []
        with self._cond:
            if key is not None:
                # supersede-in-queue: a newer request for the same
                # buffer makes every queued older sibling stale —
                # remove them BEFORE the admission checks, so an
                # editor typing fast recycles its own queue slots
                # instead of tripping the busy backpressure
                kept = []
                for entry in session.queue:
                    if supersede_key(
                        entry[0], self.base_dir
                    ) == key:
                        stale.append(entry[0])
                    else:
                        kept.append(entry)
                if stale:
                    session.queue[:] = kept
                    self._queued -= len(stale)
                if (
                    key[0] != "overlay"
                    and session.busy
                    and session.current_key == key
                    and session.current_superseded is not None
                ):
                    # the in-flight request is the same buffer's older
                    # vet: wake the dispatcher's sliced join so it
                    # answers `superseded` instead of running stale
                    # work to completion (overlay writes are never
                    # abandoned mid-application)
                    session.current_superseded.set()
            if server.draining():
                rejected = "daemon is draining"
            elif len(session.queue) >= session_queue_depth():
                rejected = (
                    f"session queue full "
                    f"({session_queue_depth()} pending)"
                )
            elif self._queued >= global_queue_depth():
                rejected = (
                    f"admission queue full "
                    f"({global_queue_depth()} pending)"
                )
            else:
                session.queue.append((req, time.monotonic()))
                self._queued += 1
                metrics.counter("daemon.requests").inc()
                self._cond.notify()
        for old_req in stale:
            # a queued-then-superseded request never dispatched: no
            # SLO charge, and its trace shipping bucket (if the traced
            # client pre-created one) is freed — nobody will answer it
            tctx = spans.parse_trace_field(old_req)
            if tctx is not None:
                spans.drain_trace(tctx[0])
            session.reject_superseded(old_req)
        if rejected is not None:
            session.reject_busy(req, rejected)

    def _reader_finished(self, session: Session) -> None:
        with self._cond:
            self._cond.notify_all()
        self._maybe_close(session)

    def _maybe_close(self, session: Session) -> None:
        """Retire a session whose client is done: reader at EOF (or
        dead transport), nothing queued, nothing in flight."""
        with self._cond:
            done = session.read_done and not session.busy and (
                not session.queue or session.dead.is_set()
            )
            if done:
                if session.queue:
                    # a dead client's queued remainder is abandoned
                    metrics.counter("serve.requests_abandoned").inc(
                        len(session.queue)
                    )
                    self._queued -= len(session.queue)
                    session.queue.clear()
                if session in self._sessions:
                    self._sessions.remove(session)
                else:
                    done = False
        if done:
            metrics.unregister_gauge(
                f"daemon.session.{session.id}.queue_depth"
            )
            metrics.counter("daemon.sessions_closed").inc()
            # a disconnected editor's unsaved buffers must not leak
            # into other clients' view of the tree
            pf_overlay.clear_owner(session.id)
            session.close()

    # -- the fair scheduler ----------------------------------------------

    def _next_work(self):
        """Round-robin across sessions with pending work: block until a
        request is dispatchable, return ``(session, req, waited_s)`` —
        or ``None`` when draining (dispatchers retire)."""
        with self._cond:
            while True:
                if server.draining():
                    return None
                n = len(self._sessions)
                for offset in range(n):
                    index = (self._rr + 1 + offset) % n
                    session = self._sessions[index]
                    if session.busy or not session.queue:
                        continue
                    if session.dead.is_set():
                        continue  # _maybe_close will reap it
                    self._rr = index
                    req, waited = session.pop_request()
                    self._queued -= 1
                    session.busy = True
                    return session, req, waited
                # bounded: the drain wake from _on_drain is
                # best-effort (signal-handler context), so the flag is
                # re-checked on a timer as the backstop
                self._cond.wait(0.5)

    def _yield_to_editor(self, session) -> None:
        """Park a batch dispatch while editor-tier work is in flight.
        Bounded (1s total) so a slow interactive request degrades batch
        latency instead of starving it; progress is guaranteed because
        the wait condition is strictly ``_interactive > 0`` and every
        increment is paired with a ``finally`` decrement."""
        deadline = time.monotonic() + 1.0
        waited = False
        with self._cond:
            while (
                self._interactive > 0
                and not self._stop_event.is_set()
                and not session.dead.is_set()
            ):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                waited = True
                self._cond.wait(min(0.05, remaining))
        if waited:
            metrics.counter("editor.boost_delays").inc()

    def _dispatch_loop(self) -> None:
        while True:
            work = self._next_work()
            if work is None:
                return
            session, req, waited = work
            metrics.histogram("daemon.queue_wait.seconds").observe(
                waited
            )
            abandoned = threading.Event()
            session.current_abandoned = abandoned
            if session.dead.is_set():
                abandoned.set()
            # in-flight supersede identity: published under the
            # scheduler lock so the reader thread's admission path can
            # match a newer same-buffer request against it.  Overlay
            # writes are queue-supersede only (never abandoned once
            # they may have started mutating the store)
            key = (
                supersede_key(req, self.base_dir)
                if supersede_enabled() else None
            )
            superseded = None
            if key is not None and key[0] != "overlay":
                superseded = threading.Event()
                with self._cond:
                    session.current_key = key
                    session.current_superseded = superseded
            interactive = False
            if editor_boost_enabled():
                interactive = _interactive_request(req, session)
                if interactive:
                    with self._cond:
                        self._interactive += 1
                else:
                    self._yield_to_editor(session)
            keep_going = True
            try:
                if abandoned.is_set():
                    metrics.counter("serve.requests_abandoned").inc()
                else:
                    reads, writes = _request_roots(req, self.base_dir)
                    token = self._locks.acquire(
                        reads, writes, timeout=lock_timeout(),
                        cancelled=session.dead,
                    )
                    if token is None:
                        # the conflicting holder (a watch over the same
                        # tree, a still-running abandoned writer) did
                        # not clear in time: backpressure, not an
                        # indefinitely parked dispatcher
                        metrics.counter("daemon.lock_timeouts").inc()
                        flight.anomaly("daemon.lock_timeout", {
                            "session": session.id,
                            "op": req.get("op"),
                        })
                        session.reject_busy(
                            req,
                            "a conflicting request holds the target "
                            "tree(s); retry",
                        )
                    else:
                        # released via on_settled — which, for a
                        # deadline-abandoned request, fires only when
                        # the detached handler actually finishes, so a
                        # zombie writer keeps its trees locked and no
                        # sibling can interleave writes with it
                        keep_going = dispatch_request(
                            req, self.base_dir, session.out_lock,
                            session.respond_locked, request_timeout(),
                            abandoned=abandoned,
                            on_settled=(
                                lambda _t=token:
                                self._locks.release(_t)
                            ),
                            superseded=superseded,
                        )
            finally:
                session.current_abandoned = None
                with self._cond:
                    if interactive:
                        self._interactive -= 1
                    session.current_key = None
                    session.current_superseded = None
                    session.busy = False
                    session.requests_total += 1
                    self._cond.notify_all()
            self._maybe_close(session)
            if not keep_going:
                # a client-requested shutdown drains the whole daemon
                # through the one shared drain implementation; this
                # dispatcher runs the teardown itself (stop() skips
                # joining the calling thread) so every session gets its
                # drained-shutdown line even in embedded (start()) mode
                server.request_shutdown()
                self.stop()

    # -- fleet link ------------------------------------------------------

    def _fleet_load(self) -> tuple:
        """(in_flight, queued) — the heartbeat's load snapshot."""
        with self._cond:
            in_flight = sum(1 for s in self._sessions if s.busy)
            return in_flight, self._queued

    def _fleet_link_loop(self) -> None:
        """Register with the coordinator, then heartbeat at a third of
        the lease interval (two beats fit inside one lease, so a single
        dropped packet cannot mark a healthy daemon suspect).  Any
        transport failure tears the link down and re-registers with
        capped deterministic backoff; local serving is unaffected."""
        from ..perf import workers
        from .fleet import lease_seconds

        interval = max(0.05, lease_seconds() / 3.0)
        client = None
        member_id = None
        backoff = 0
        partition_skips = 0
        while not self._stop_event.is_set():
            try:
                if client is None:
                    client = DaemonClient(self.fleet_addr)
                    ack = client.request({
                        "op": "fleet.register",
                        "addr": self.address(),
                        "capacity": daemon_workers(),
                    })
                    if not ack.get("ok"):
                        raise ConnectionError(
                            ack.get("error", "registration refused")
                        )
                    member_id = ack.get("member")
                    # the coordinator's lease is authoritative: a
                    # coordinator started with --lease (or different
                    # env) would otherwise suspect/evict daemons
                    # beating on their own env-derived cadence
                    lease = ack.get("lease_s")
                    if isinstance(lease, (int, float)) and lease > 0:
                        interval = max(0.05, float(lease) / 3.0)
                    metrics.counter("daemon.fleet_registrations").inc()
                    backoff = 0
                if faults.should_fire("fleet.partition", "link"):
                    # deterministic network partition: the next beats
                    # are dropped WITHOUT closing the link (exactly
                    # what a severed network looks like from the
                    # coordinator), so the lease ages through suspect
                    # into eviction; the rejoin then goes through the
                    # stale-lease refusal → re-register path below
                    partition_skips = 7  # 7/3 lease: past the 2-lease evict
                if partition_skips > 0:
                    partition_skips -= 1
                    if self._stop_event.wait(interval):
                        break
                    continue
                in_flight, queued = self._fleet_load()
                ack = client.request({
                    "op": "fleet.heartbeat",
                    "member": member_id,
                    "in_flight": in_flight,
                    "queued": queued,
                    "degraded": bool(
                        workers.pool_state()["degraded"]
                    ),
                    # per-daemon artifact-plane attribution: how much
                    # of this member's work came off the remote tier,
                    # and which per-project namespaces it has served —
                    # the coordinator's locality-placement signal
                    "artifact": metrics.artifact_report(),
                    "namespaces": list(runner.served_scopes()),
                    "remote_active": pf_remote.active(),
                })
                if not ack.get("ok"):
                    raise ConnectionError(
                        ack.get("error", "heartbeat refused")
                    )
            except (OSError, ConnectionError, ValueError):
                if client is not None:
                    client.close()
                client = None
                member_id = None
                backoff = min(backoff + 1, 5)  # capped, deterministic
            if self._stop_event.wait(interval * (1 + backoff)):
                break
        if client is not None:
            client.close()

    # -- maintenance -----------------------------------------------------

    def _maintenance_loop(self) -> None:
        interval = idle_gc_interval()
        while not self._stop_event.wait(interval):
            try:
                pf_cache.get_cache().enforce_budget()
            except Exception:
                pass  # maintenance must never take the daemon down

    # -- stats -----------------------------------------------------------

    def _stats_payload(self) -> dict:
        with self._cond:
            sessions = {s.id: s.state() for s in self._sessions}
            queued = self._queued
        return {
            "listen": self.address(),
            "max_clients": self._max_clients,
            "active_sessions": len(sessions),
            "queued_requests": queued,
            "sessions": {k: sessions[k] for k in sorted(sessions)},
        }

    # -- teardown --------------------------------------------------------

    def stop(self) -> None:
        """Drain and tear down (idempotent): finish in-flight requests,
        answer them, send every session the final drained-shutdown
        line, release globals."""
        with self._stop_lock:
            if self._stopped:
                # a concurrent caller (the CLI's finally racing a
                # shutdown-op dispatcher) must not return before the
                # first stop finished tearing sessions down
                self._stop_done.wait(60.0)
                return
            self._stopped = True
        server.request_shutdown()  # idempotent; runs _on_drain once
        current = threading.current_thread()
        for thread in self._dispatchers:
            if thread is not current:
                # generous: drain promises FINISHING in-flight work,
                # and a cold batch request can legitimately run long
                thread.join(60.0)
        with self._cond:
            sessions = list(self._sessions)
            self._sessions.clear()
            self._queued = 0
        for session in sessions:
            try:
                session.respond(
                    {"ok": True, "op": "shutdown", "drained": True}
                )
            except Exception:
                pass
            metrics.unregister_gauge(
                f"daemon.session.{session.id}.queue_depth"
            )
            pf_overlay.clear_owner(session.id)
            session.close()
        thread = self._accept_thread
        if thread is not None and thread is not current:
            thread.join(5.0)
        thread = self._fleet_thread
        if thread is not None and thread is not current:
            # _on_drain set _stop_event, which breaks the beat wait
            thread.join(5.0)
        if self.spec[0] == "unix":
            try:
                os.unlink(self.spec[1])
            except OSError:
                pass
        server.remove_drain_callback(self._on_drain)
        server.unregister_stats_source("daemon")
        metrics.unregister_gauge("daemon.active_sessions")
        metrics.unregister_gauge("daemon.queued_requests")
        runner.set_project_scoping(False)
        # persist the black box + timeline; the process-global state
        # is released only when no sibling server remains
        server.release_server_telemetry()
        self._stop_done.set()


def serve_daemon(listen: str, clients=None, fleet: str = None) -> int:
    """The ``operator-forge daemon`` entry point: bind, print one
    status line on stderr, serve until SIGTERM/SIGINT (or a client's
    shutdown op), then drain and exit 0.  With ``fleet`` set, the
    daemon registers with (and heartbeats to) that coordinator."""
    import sys

    daemon = ForgeDaemon(listen, clients=clients, fleet=fleet)
    daemon._bind()
    print(
        f"daemon: listening on {daemon.address()} "
        f"(max {daemon._max_clients} clients"
        + (f", fleet {fleet}" if fleet else "")
        + ")",
        file=sys.stderr, flush=True,
    )
    installed = []
    if threading.current_thread() is threading.main_thread():
        import signal

        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                installed.append((
                    signum,
                    signal.signal(signum, server.request_shutdown),
                ))
            except (ValueError, OSError):  # pragma: no cover
                pass
    try:
        daemon.serve_forever()
    except server._DrainSignal:
        pass  # signal broke the blocked accept: drain below
    finally:
        daemon.stop()
        if installed:
            import signal

            for signum, previous in installed:
                try:
                    signal.signal(signum, previous)
                except (ValueError, OSError):  # pragma: no cover
                    pass
    print("daemon: drained, exiting", file=sys.stderr, flush=True)
    return 0


# -- client ----------------------------------------------------------------


#: deterministic backoff step between client reconnect attempts
_CLIENT_BACKOFF_S = 0.05


def client_retries() -> int:
    """Bounded reconnect budget for :class:`DaemonClient`
    (``OPERATOR_FORGE_DAEMON_RETRIES``, default 2): how many extra
    connect (or reconnect-and-resend) attempts a client makes before a
    transport failure surfaces.  The same knob pattern as the remote
    tier's ``OPERATOR_FORGE_REMOTE_RETRIES``."""
    return env_number(
        "OPERATOR_FORGE_DAEMON_RETRIES", 2, cast=int
    )


class DaemonClient:
    """One connection to a running daemon.  Requests go out as JSON
    lines; responses come back one JSON object per line, each echoing
    the request's ``id`` (``busy`` rejections may arrive ahead of an
    earlier queued request's answer — correlate by id when
    pipelining).

    The transport self-heals across a daemon bounce: the initial
    connect retries with bounded deterministic backoff
    (``OPERATOR_FORGE_DAEMON_RETRIES`` × ``0.05s*attempt``), and
    :meth:`request` — on a connect/read failure mid-round-trip —
    reconnects and re-sends within the same budget.  Re-sending is safe
    because every job is idempotent by construction (deterministic ids,
    content-keyed replay): a re-submitted job either replays its
    recorded result or recomputes the identical bytes.  The raw relay
    surface (:meth:`send_line`/:meth:`read_line`) never retries — a
    pass-through (``operator-forge connect``) must see the real stream."""

    def __init__(self, addr: str, timeout=None, retries=None):
        self._addr = addr
        self._timeout = timeout
        self._retries = (
            client_retries() if retries is None else max(0, int(retries))
        )
        self._sock = None
        self._reader = None
        self._connect_with_retry()

    def _connect_once(self) -> None:
        sock = connect_stream(self._addr, timeout=self._timeout)
        self._sock = sock
        self._reader = sock.makefile("r", encoding="utf-8")

    def _connect_with_retry(self) -> None:
        budget = self._retries + 1
        for attempt in range(budget):
            if attempt:
                time.sleep(_CLIENT_BACKOFF_S * attempt)  # deterministic
            try:
                self._connect_once()
                return
            except (OSError, ConnectionError):
                if attempt + 1 >= budget:
                    raise

    def _reconnect(self) -> None:
        self.close()
        self._connect_once()

    def send(self, payload: dict) -> None:
        self._sock.sendall(
            (json.dumps(payload) + "\n").encode("utf-8")
        )

    def read(self):
        """The next response line as a dict, or ``None`` when the
        daemon closed the connection."""
        line = self.read_line()
        if not line:
            return None
        return json.loads(line)

    # raw-line surface for relays (`operator-forge connect`): the
    # protocol is line-oriented, so a pass-through client should not
    # have to re-encode through dicts (or reach into the transport)

    def send_line(self, line: str) -> None:
        """Forward one raw protocol line (newline appended if
        missing)."""
        if not line.endswith("\n"):
            line += "\n"
        self._sock.sendall(line.encode("utf-8"))

    def read_line(self) -> str:
        """The next raw response line (``""`` on EOF)."""
        return self._reader.readline()

    def half_close(self) -> None:
        """Shut down the write side: no more requests will be sent,
        but remaining responses can still be read until the daemon
        closes."""
        import socket as _socket

        try:
            self._sock.shutdown(_socket.SHUT_WR)
        except OSError:
            pass

    #: ops that carry a distributed-trace context when the CLIENT is
    #: tracing — the submissions whose server-side work belongs on the
    #: client's timeline (control ops like ping/heartbeat stay bare)
    _TRACED_OPS = ("job", "batch", "watch", "subscribe")

    def _attach_trace(self, payload: dict) -> None:
        """Stamp an outgoing request with this process's trace context
        (no-op unless tracing is enabled here and the op is traced).
        The trace id derives deterministically from the request's own
        id, so an idempotent re-send rejoins the same trace."""
        if payload.get("op") not in self._TRACED_OPS:
            return
        if "trace" in payload:
            return  # the caller (the fleet coordinator) already did
        ctx = spans.rpc_context(payload.get("id"))
        if ctx is not None:
            payload["trace"] = ctx

    @staticmethod
    def _ingest_trace(response) -> None:
        """Merge a response's shipped span segment into this process's
        ring (the socket-boundary drain-and-merge).  Events this
        process itself produced are skipped: with an in-process server
        (embedded daemon, tests, bench) the ring RETAINS the drained
        segment's copies, and re-ingesting them would duplicate every
        server span in the timeline."""
        if not isinstance(response, dict):
            return
        events = response.pop("trace_events", None)
        if events:
            own = os.getpid()
            spans.ingest_events(
                [e for e in events if e.get("pid") != own]
            )

    def request(self, payload: dict) -> dict:
        """One round trip (non-streaming ops), surviving a daemon
        bounce: a connect/read failure mid-round-trip reconnects with
        bounded deterministic backoff and re-sends (jobs are
        idempotent — see the class docstring), so ``batch --addr``
        outlives a coordinator-initiated daemon restart."""
        self._attach_trace(payload)
        budget = self._retries + 1
        last = None
        for attempt in range(budget):
            if attempt:
                time.sleep(_CLIENT_BACKOFF_S * attempt)  # deterministic
                try:
                    self._reconnect()
                except (OSError, ConnectionError) as exc:
                    last = exc
                    continue
            try:
                self.send(payload)
                response = self.read()
                # correlate by id when the request carries one: an
                # unsolicited line (a drained-shutdown notice buffered
                # before a bounce) must never be mistaken for this
                # request's answer.  Bounded: a flood of unrelated
                # lines is a protocol violation, not a wait-forever
                want = payload.get("id")
                skips = 0
                while (
                    want is not None and response is not None
                    and response.get("id") != want and skips < 64
                ):
                    response = self.read()
                    skips += 1
                if (
                    want is not None and response is not None
                    and response.get("id") != want
                ):
                    # 64 unrelated lines without our answer is a
                    # protocol violation — surface it as a transport
                    # failure (the bounded reconnect gets a clean
                    # buffer) rather than handing the caller some
                    # other request's payload
                    raise ConnectionError(
                        "protocol violation: no response matching "
                        f"id {want!r} within 64 lines"
                    )
            except (OSError, ConnectionError, ValueError) as exc:
                # ValueError covers a line torn mid-JSON by the dying
                # daemon; the re-sent request reads a whole fresh line
                last = exc
                continue
            if response is not None:
                self._ingest_trace(response)
                return response
            last = ConnectionError("daemon closed the connection")
        raise ConnectionError(
            f"daemon at {self._addr}: {last} "
            f"(after {budget} attempt(s))"
        )

    def stream(self, payload: dict):
        """Send a streaming op (watch) and yield every response line
        until the terminal one (``done`` or an error)."""
        self._attach_trace(payload)
        self.send(payload)
        while True:
            response = self.read()
            if response is None:
                return
            self._ingest_trace(response)
            yield response
            if response.get("done") or response.get("ok") is False:
                return

    def close(self) -> None:
        if self._reader is not None:
            try:
                self._reader.close()
            except OSError:
                pass
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
