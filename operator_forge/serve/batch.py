"""The batch orchestrator behind ``operator-forge batch``.

Scheduling: jobs are grouped by read/write conflict over the
directories they touch (nested dirs count as overlapping, so an init
into ``out/`` can never race a vet of ``out/sub``, and a job reading a
tree another job writes always serializes after it; shared read-only
configs do NOT serialize).  Groups preserve manifest order internally
— an ``init -> create-api -> vet -> test`` chain over one project runs
in sequence — and independent groups fan out concurrently through the
``OPERATOR_FORGE_WORKERS=thread|process`` backend
(:mod:`operator_forge.perf.workers`).  Results are always reported in
manifest order with deterministic content, so serial, thread, and
process-pool batches are interchangeable.
"""

from __future__ import annotations

import json
import os
import sys
import time

from ..perf import n_jobs, spans, workers
from .jobs import BatchManifestError, Job, load_manifest  # noqa: F401
from .runner import record_fenceable_roots, run_group


def _overlaps(a: str, b: str) -> bool:
    return a == b or a.startswith(b + os.sep) or b.startswith(a + os.sep)


def _any_overlap(roots_a, roots_b) -> bool:
    return any(_overlaps(a, b) for a in roots_a for b in roots_b)


def plan_groups(jobs) -> list:
    """Partition jobs into ordered execution groups by read/write
    conflict: a job joins (and, bridging, merges) every group whose
    WRITES overlap anything it touches, or whose reads overlap its own
    writes — nested directories count as overlapping.  Jobs that merely
    read a common tree (N projects generated from one config) stay in
    independent groups and fan out; each group's jobs keep manifest
    order."""
    groups: list = []  # each: {"reads": [...], "writes": [...], "jobs": [...]}
    for job in jobs:
        reads, writes = job.reads(), job.writes()
        touches = reads + writes
        matches = [
            g for g in groups
            if _any_overlap(g["writes"], touches)
            or _any_overlap(g["reads"], writes)
        ]
        if not matches:
            groups.append({
                "reads": list(reads), "writes": list(writes),
                "jobs": [job],
            })
            continue
        primary = matches[0]
        for other in matches[1:]:
            primary["jobs"].extend(other["jobs"])
            primary["reads"].extend(other["reads"])
            primary["writes"].extend(other["writes"])
            groups.remove(other)
        primary["jobs"].append(job)
        primary["jobs"].sort(key=lambda j: j.index)
        primary["reads"].extend(
            r for r in reads if r not in primary["reads"]
        )
        primary["writes"].extend(
            w for w in writes if w not in primary["writes"]
        )
    return [g["jobs"] for g in groups]


def _run_group(payload) -> list:
    """Execute one scheduling group; module-level so the process
    backend can pickle it by reference.  ``fresh_roots`` are output
    roots that did not exist when the batch started: a group can be
    re-executed (the workers layer retries tasks whose worker crashed,
    hung past the deadline, or was torn down with a broken pool), and a
    dead attempt may have left a partial tree behind — scaffolding's
    preserve-on-exists semantics must never adopt it, so every
    execution starts those roots from scratch.  Pre-existing roots are
    left alone: regenerating over them is already convergent (partial
    rewrites are re-written on retry because their bytes differ)."""
    import shutil

    group, fresh_roots = payload
    for root in fresh_roots:
        shutil.rmtree(root, ignore_errors=True)
    return run_group(group)


def run_batch(jobs) -> list:
    """Run every job; returns :class:`JobResult` objects in input
    order regardless of how groups were scheduled."""
    groups = plan_groups(jobs)
    payloads = []
    for group in groups:
        fresh_roots = []
        for job in group:
            for root in job.writes():
                if root not in fresh_roots and not os.path.isdir(root):
                    fresh_roots.append(root)
        # created-from-absent roots become eligible for the fleet's
        # fence reset: the fence may only ever delete what some run in
        # this process brought into existence
        record_fenceable_roots(fresh_roots)
        payloads.append((group, fresh_roots))
    with spans.span("serve.batch"):
        per_group = workers.map_ordered(
            _run_group, payloads, site="batch.group"
        )
    by_index = {
        result.index: result
        for results in per_group
        for result in results
    }
    return [by_index[job.index] for job in jobs]


def cmd_batch(manifest_path: str, json_lines: bool = False,
              out=None, addr: str = None) -> int:
    out = out if out is not None else sys.stdout
    try:
        jobs = load_manifest(manifest_path)
    except BatchManifestError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    started = time.perf_counter()
    if addr:
        # run through a resident daemon instead of this process: the
        # manifest is loaded (and its paths resolved) locally, shipped
        # as one batch op, and the daemon's warm caches do the work
        from .daemon import DaemonClient
        from .jobs import specs_key

        try:
            with DaemonClient(addr) as client:
                response = client.request({
                    "op": "batch",
                    "jobs": [job.to_spec() for job in jobs],
                    # the deterministic submission key doubles as the
                    # correlation id AND (under `operator-forge trace`)
                    # the seed the distributed trace id derives from
                    "id": specs_key(jobs),
                })
        except (OSError, ConnectionError) as exc:
            print(f"error: daemon at {addr}: {exc}", file=sys.stderr)
            return 1
        if response.get("ok") is False and "error" in response:
            print(f"error: daemon: {response['error']}",
                  file=sys.stderr)
            return 1
        result_dicts = response.get("results", [])
        backend = f"daemon:{addr}"
    else:
        results = run_batch(jobs)
        result_dicts = [r.to_dict() for r in results]
        backend = workers.backend()
    elapsed = time.perf_counter() - started
    ok = sum(1 for r in result_dicts if r["ok"])
    cached = sum(1 for r in result_dicts if r["cached"])
    failed = len(result_dicts) - ok
    summary = {
        "jobs": len(result_dicts),
        "ok": ok,
        "cached": cached,
        "failed": failed,
        "seconds": round(elapsed, 4),
        "backend": backend,
        "parallelism": n_jobs(),
    }
    if json_lines:
        for result in result_dicts:
            print(json.dumps(result), file=out)
        print(json.dumps({"summary": summary}), file=out)
    else:
        for result in result_dicts:
            status = "ok  " if result["ok"] else "FAIL"
            suffix = " (cached)" if result["cached"] else (
                " ({:.2f}s)".format(result["seconds"])
            )
            print(
                f"{status}  {result['id']}  {result['command']}{suffix}",
                file=out,
            )
            if not result["ok"]:
                for line in result["stderr"].rstrip().splitlines():
                    print(f"      {line}", file=out)
        print(
            f"batch: {summary['jobs']} jobs, {ok} ok, {cached} cached, "
            f"{failed} failed in {elapsed:.2f}s "
            f"(backend={summary['backend']}, "
            f"jobs={summary['parallelism']})",
            file=out,
        )
    return 1 if failed else 0
