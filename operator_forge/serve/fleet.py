"""``operator-forge fleet`` — the fault-tolerant fleet coordinator.

PR 9 gave the fleet a shared artifact tier (the remote cache), PR 10
gave one host a multi-client daemon.  This module is the missing
production piece between them — the Bazel-remote-execution-shaped
scheduler: N daemons register with one coordinator, client jobs route
by project-namespace affinity (warm per-tree caches) with work-stealing
for cold trees, and every health decision is lease-driven so killing
any daemon mid-batch is invisible to clients and provably
byte-identical to a local cache-off recompute.

Architecture:

- **membership by lease** — a daemon started with ``--fleet <addr>``
  opens one registration connection, sends ``fleet.register`` (its own
  listen address + capacity), then heartbeats at a third of
  ``OPERATOR_FORGE_FLEET_LEASE_S``.  Each beat carries the placement
  signal: in-flight count, queued requests, and the PR 7
  ``workers.degraded`` flag.  A lease that ages past one interval marks
  the daemon *suspect* (deprioritized for placement); past two, it is
  *evicted* — as is a daemon whose registration connection drops.  A
  recovered daemon simply re-registers;
- **routing** — a submission's affinity key is the hash of its target
  trees (the same ``serve.job.<hash>`` project namespaces PR 10
  partitions replay records by), so repeat work over one tree lands on
  the daemon whose mem-tier already holds that tree's records.  Cold
  keys (and keys whose preferred daemon is suspect, degraded, or at
  capacity) *work-steal* deterministically, weighing remote-cache
  locality between health and load: a member that has served the
  namespace (heartbeat-reported) ranks first, then — when the
  namespace is known populated in the shared remote tier — any
  remote-active member (it cold-hydrates over the network at the
  remote tier's cold-worker speedup), then everyone else; ties break
  by load then member id.  Submissions whose trees overlap an
  in-flight dispatch are forced onto that dispatch's daemon, where the
  PR 10 path locks serialize them — the fleet-level analogue of the
  daemon's cross-session conflict rule;
- **shared-nothing artifact plane** — daemons share artifacts ONLY
  through the PR 9 remote cache: every crash-retry root reset runs
  behind the daemon-side ``fence`` op (the retry's target clears the
  roots on its own filesystem), and the coordinator's residual local
  sweep is gated by its own created-from-absence containment — on a
  fleet whose daemons live on other hosts (or in private roots
  simulating them) that sweep is structurally empty, so the
  coordinator never touches a daemon's disk;
- **elasticity** — with ``OPERATOR_FORGE_FLEET_MAX`` set (or the CLI's
  ``--min``/``--max``), the monitor loop doubles as an autoscaler:
  queue depth per healthy member and the PR 15 per-tenant SLO signal
  (p99 over ``OPERATOR_FORGE_FLEET_SCALE_P99_S``, or deadline-miss
  growth) spawn daemon subprocesses — each with a PRIVATE
  ``OPERATOR_FORGE_CACHE_DIR``, so a cold spawn hydrates from the
  shared remote tier, never a sibling's disk — and a fleet that sits
  fully idle for ``OPERATOR_FORGE_FLEET_IDLE_S`` retires one
  coordinator-spawned daemon per window (evict-then-drain: in-flight
  work is answered first).  Scale events ride the same heartbeat/
  suspect/evict machinery as crash churn, so byte-identity holds
  across them by construction;
- **re-dispatch** — submissions are idempotent: deterministic job ids
  (PR 3's manifest model, :func:`~operator_forge.serve.jobs.specs_key`)
  over content-keyed replay mean re-running a submission reproduces its
  bytes.  So when a daemon dies mid-run (connection severed, read
  deadline tripped), the coordinator resets any output root that did
  not exist at admission (the PR 7 crash-retry rule: scaffolding's
  preserve-on-exists semantics must never adopt a dead attempt's
  partial tree) and re-dispatches to a healthy daemon, with bounded
  deterministic retry/backoff (``OPERATOR_FORGE_FLEET_RETRIES`` ×
  0.05s·attempt).  The reset is *fenced* by a liveness probe: a member
  that still answers a fresh ping after its dispatch failed (a severed
  connection, not a dead host) may harbor a zombie writer, so its
  retry pins the same daemon behind a ``fence`` op — the fence
  write-locks the submission's trees (queueing behind the zombie's
  path locks) and resets the fresh roots server-side once they are
  quiet; only a probe-dead member's retry resets locally and
  re-routes.  A submission that exhausts the budget is
  *quarantined*: executed once in-process by the coordinator itself
  (mirroring the workers layer's poison-task quarantine-to-thread), so
  a job that kills every daemon it touches still completes without
  ricocheting through the fleet forever.  A daemon's ``busy`` answer
  is backpressure, not failure: retried within the same budget, then
  propagated to the client;
- **chaos sites** — ``fleet.daemon_crash@dispatch`` (the dispatch
  connection severed after the job is sent), ``fleet.heartbeat_lost@
  lease`` (a received beat dropped without refreshing the lease),
  ``fleet.dispatch_hang@route`` (the dispatch sleeps past the
  ``OPERATOR_FORGE_FLEET_DISPATCH_S`` deadline), ``fleet.partition@
  link`` (daemon-side: beats stop without the connection closing —
  suspect, evict, stale-lease refusal, re-register), and
  ``fleet.steal_kill@steal`` (a STOLEN dispatch's connection severed
  after the send, mid-hydration) extend
  :mod:`operator_forge.perf.faults`; every one is recoverable, so
  chaos runs — including SIGKILL of a real daemon subprocess mid-batch
  — must stay byte-identical to a cache-off serial recompute (bench
  ``fleet`` section + the commit-check live-fleet step);
- **drain** — SIGTERM/SIGINT (or a client's ``shutdown`` op) ride the
  one shared :func:`~operator_forge.serve.server.request_shutdown`
  machinery: the listener closes, in-flight dispatches finish and are
  answered, *queued* clients are answered ``busy`` with a
  ``retry_after`` hint (never silently dropped), every registered
  daemon is sent a ``shutdown`` op and drains, every session gets the
  final drained-shutdown line, and the coordinator exits 0.

Observability: the coordinator registers a ``fleet`` stats source
(per-daemon lease age, in-flight, degrade flag, dispatch/eviction/
re-dispatch counters, stable key order) surfaced by the serve ``stats``
op, ``operator-forge stats``, and ``operator-forge fleet-status``.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time

from ..perf import env_number, faults, flight, metrics, spans
from ..perf.netaddr import bind_listener, bound_address, parse_listen
from . import server
from .batch import _overlaps, run_batch
from .daemon import DaemonClient
from .jobs import (
    BatchManifestError,
    jobs_from_specs,
    specs_from_request,
    specs_key,
)
from .runner import (
    _scope_label,
    is_fenceable_root,
    record_fenceable_roots,
    run_job,
)
from .server import dispatch_request
from .session import CONNECT_RETRY_AFTER_S, Session

DEFAULT_LEASE_S = 5.0
DEFAULT_RETRIES = 2
DEFAULT_MAX_CLIENTS = 128
DEFAULT_GLOBAL_QUEUE = 256
#: deterministic backoff step between re-dispatch attempts (seconds)
_BACKOFF_S = 0.05
#: per-daemon artifact-plane attribution carried by heartbeats, in the
#: stable key order ``fleet-status --json`` surfaces them
_ARTIFACT_KEYS = (
    "hydrated", "remote_corrupt", "remote_hits", "remote_misses",
    "remote_puts",
)


def lease_seconds() -> float:
    """The heartbeat lease interval (``OPERATOR_FORGE_FLEET_LEASE_S``,
    default 5s): a daemon whose lease ages past one interval is
    suspect, past two is evicted.  Daemons beat at a third of it, so a
    single dropped beat can never mark a healthy daemon suspect."""
    return env_number(
        "OPERATOR_FORGE_FLEET_LEASE_S", DEFAULT_LEASE_S, minimum=0.2
    )


def fleet_retries() -> int:
    """Bounded re-dispatch budget per submission
    (``OPERATOR_FORGE_FLEET_RETRIES``, default 2): how many times a
    failed dispatch moves to another daemon before the submission is
    quarantined to in-process execution."""
    return env_number(
        "OPERATOR_FORGE_FLEET_RETRIES", DEFAULT_RETRIES, cast=int
    )


def dispatch_timeout() -> float:
    """Read deadline per dispatch round trip
    (``OPERATOR_FORGE_FLEET_DISPATCH_S``; 0 or unset disables).  Off by
    default: a dead daemon is detected by its connection dropping, and
    a legitimate cold batch can run long — enable it to also catch
    *hung* daemons (the ``fleet.dispatch_hang`` path)."""
    return env_number("OPERATOR_FORGE_FLEET_DISPATCH_S", 0.0)


def fleet_workers() -> int:
    """Coordinator dispatcher-thread count
    (``OPERATOR_FORGE_FLEET_WORKERS``; default 8).  Dispatchers mostly
    wait on daemon round trips, so the default is wider than the
    daemon's CPU-bound dispatcher pool."""
    return env_number(
        "OPERATOR_FORGE_FLEET_WORKERS", 8, cast=int, minimum=1
    )


def max_clients() -> int:
    """Concurrent-connection ceiling (``OPERATOR_FORGE_FLEET_CLIENTS``,
    default 128; daemon registration connections count too)."""
    return env_number(
        "OPERATOR_FORGE_FLEET_CLIENTS", DEFAULT_MAX_CLIENTS,
        cast=int, minimum=1,
    )


def global_queue_depth() -> int:
    """Coordinator-wide admission bound (``OPERATOR_FORGE_FLEET_QUEUE``,
    default 256)."""
    return env_number(
        "OPERATOR_FORGE_FLEET_QUEUE", DEFAULT_GLOBAL_QUEUE,
        cast=int, minimum=1,
    )


def session_queue_depth() -> int:
    # the per-session bound is a transport property, not a fleet one:
    # share the daemon's knob
    from .daemon import session_queue_depth as daemon_depth

    return daemon_depth()


def _hang_seconds() -> float:
    """How long an injected ``fleet.dispatch_hang`` sleeps — the same
    ``OPERATOR_FORGE_FAULT_HANG_S`` knob the workers layer uses."""
    return env_number("OPERATOR_FORGE_FAULT_HANG_S", 30.0)


# -- elasticity knobs ------------------------------------------------------


def fleet_min() -> int:
    """Autoscaler pool floor (``OPERATOR_FORGE_FLEET_MIN``, default 0).
    The coordinator keeps at least this many daemons registered,
    spawning its own when short."""
    return env_number("OPERATOR_FORGE_FLEET_MIN", 0, cast=int, minimum=0)


def fleet_max() -> int:
    """Autoscaler pool ceiling (``OPERATOR_FORGE_FLEET_MAX``, default 0
    = the autoscaler is OFF and the fleet keeps its PR 14 fixed-size
    behavior)."""
    return env_number("OPERATOR_FORGE_FLEET_MAX", 0, cast=int, minimum=0)


def scale_queue_threshold() -> float:
    """Queue pressure that triggers scale-up: queued submissions per
    healthy member (``OPERATOR_FORGE_FLEET_SCALE_QUEUE``, default 2)."""
    return env_number(
        "OPERATOR_FORGE_FLEET_SCALE_QUEUE", 2.0, minimum=0.1
    )


def scale_p99_threshold() -> float:
    """SLO pressure that triggers scale-up: any tenant's p99 above this
    many seconds (``OPERATOR_FORGE_FLEET_SCALE_P99_S``; 0 or unset
    disables the latency leg — deadline-miss growth still counts)."""
    return env_number("OPERATOR_FORGE_FLEET_SCALE_P99_S", 0.0)


def scale_idle_seconds() -> float:
    """How long the fleet must sit fully idle (nothing queued, nothing
    in flight anywhere) before ONE coordinator-spawned daemon is
    retired (``OPERATOR_FORGE_FLEET_IDLE_S``, default 10)."""
    return env_number(
        "OPERATOR_FORGE_FLEET_IDLE_S", 10.0, minimum=0.5
    )


class _Member:
    """One registered daemon: its lease, load, and dispatch state."""

    __slots__ = (
        "id", "addr", "capacity", "session", "registered_at",
        "last_beat", "suspect", "degraded", "queued",
        "reported_in_flight", "in_flight", "dispatched",
        "active_roots", "namespaces", "artifact", "remote_active",
    )

    def __init__(self, member_id: str, addr: str, capacity: int,
                 session):
        self.id = member_id
        self.addr = addr
        self.capacity = max(1, capacity)
        self.session = session
        now = time.monotonic()
        self.registered_at = now
        self.last_beat = now
        self.suspect = False
        self.degraded = False
        self.queued = 0
        self.reported_in_flight = 0
        self.in_flight = 0       # coordinator-side dispatch count
        self.dispatched = 0      # lifetime submissions routed here
        self.active_roots = []   # [(reads, writes)] per live dispatch
        self.namespaces = set()  # scope labels this daemon has served
        self.artifact = {}       # heartbeat artifact-plane attribution
        self.remote_active = False  # daemon has a remote cache wired


def _conflicts(reads, writes, held_reads, held_writes) -> bool:
    """The batch scheduler's conflict rule over two root sets: my
    writes against everything held, my reads against held writes."""
    for w in writes:
        for other in held_reads + held_writes:
            if _overlaps(w, other):
                return True
    for r in reads:
        for other in held_writes:
            if _overlaps(r, other):
                return True
    return False


class FleetCoordinator:
    """The coordinator: listener + sessions + health-driven scheduler."""

    def __init__(self, listen: str, lease: float = None, clients=None,
                 elastic: dict = None):
        self.spec = parse_listen(listen)
        self._lease = lease
        self._max_clients = clients if clients else max_clients()
        self.base_dir = os.getcwd()
        self._listener = None
        self._accept_thread = None
        self._dispatchers: list = []
        self._monitor = None
        self._stop_event = threading.Event()
        self._cond = threading.Condition()
        self._sessions: list = []
        self._queued = 0        # pending client requests, under _cond
        self._rr = 0            # round-robin cursor, under _cond
        self._next_sid = 0
        self._member_seq = 0
        self._members: dict = {}   # member id -> _Member
        self._affinity: dict = {}  # namespace label -> member id
        #: (reads, writes) of quarantined submissions running
        #: IN-PROCESS right now — consulted by _route's overlap check,
        #: or a daemon could be handed a tree the coordinator itself
        #: is still writing
        self._local_roots: list = []
        #: scope labels known populated in the shared remote tier
        #: (heartbeats + successful dispatches to remote-active
        #: members) — the locality half of placement
        self._populated: set = set()
        #: the autoscaler's pool: listen addr -> subprocess.Popen of
        #: coordinator-spawned daemons.  ``elastic`` overrides the
        #: OPERATOR_FORGE_FLEET_MIN/MAX env knobs ({"min", "max",
        #: "env"}); None falls through to the environment
        self._elastic = dict(elastic) if elastic else None
        self._spawned: dict = {}
        self._spawn_dir = None
        self._spawn_seq = 0
        self._last_spawn = 0.0
        self._idle_since = None
        self._slo_misses_seen = 0
        self._stop_lock = threading.Lock()
        self._stopped = False
        self._stop_done = threading.Event()

    def lease_s(self) -> float:
        return self._lease if self._lease else lease_seconds()

    # -- lifecycle -------------------------------------------------------

    def address(self) -> str:
        return bound_address(self.spec, self._listener)

    def _bind(self) -> None:
        # the accept loop wakes on its own to observe the drain flag
        # (close/shutdown do not reliably break a blocked AF_UNIX
        # accept — the daemon's listener carries the same note)
        self._listener = bind_listener(
            self.spec, backlog=min(128, self._max_clients * 2),
            accept_timeout=0.5,
        )

    def _boot(self) -> None:
        # spans + the always-on event ring (the flight recorder's
        # black box, and where daemon-shipped segments land before the
        # client drains them), refcounted with any embedded daemon
        server.retain_server_telemetry()
        server._drain.clear()
        self._stop_event.clear()
        server.on_drain(self._on_drain)
        server.register_stats_source("fleet", self._stats_payload)
        metrics.register_gauge(
            "fleet.members", lambda: len(self._members)
        )
        metrics.register_gauge(
            "fleet.queued_requests", lambda: self._queued
        )
        for i in range(fleet_workers()):
            thread = threading.Thread(
                target=self._dispatch_loop, daemon=True,
                name=f"fleet-dispatch-{i}",
            )
            thread.start()
            self._dispatchers.append(thread)
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="fleet-monitor",
        )
        self._monitor.start()

    def start(self) -> None:
        """Bind and accept on a background thread (tests, bench); the
        CLI uses :meth:`serve_forever`."""
        if self._listener is None:
            self._bind()
        self._boot()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="fleet-accept",
        )
        self._accept_thread.start()

    def serve_forever(self) -> None:
        if self._listener is None:
            self._bind()
        self._boot()
        self._accept_loop()

    def _on_drain(self) -> None:
        # may run in signal-handler context: tiny and non-blocking
        try:
            self._listener.close()
        except (OSError, AttributeError):
            pass
        self._stop_event.set()
        if self._cond.acquire(blocking=False):
            try:
                self._cond.notify_all()
            finally:
                self._cond.release()

    def _accept_loop(self) -> None:
        while not server.draining():
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed: draining
            conn.settimeout(None)
            if server.draining():
                try:
                    conn.close()
                except OSError:
                    pass
                return
            with self._cond:
                active = len(self._sessions)
            if active >= self._max_clients:
                metrics.counter("fleet.busy_rejections").inc()
                payload = server._error(
                    f"fleet coordinator at its {self._max_clients}-"
                    "connection capacity", kind="busy",
                )
                payload["retry_after"] = CONNECT_RETRY_AFTER_S
                try:
                    conn.sendall(
                        (json.dumps(payload) + "\n").encode("utf-8")
                    )
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            with self._cond:
                self._next_sid += 1
                session = Session(self, conn, f"f{self._next_sid}")
                session.member_id = None  # set by fleet.register
                self._sessions.append(session)
            session.start()

    # -- membership (reader threads) -------------------------------------

    def _register_member(self, session: Session, req: dict) -> None:
        addr = str(req.get("addr") or "").strip()
        req_id = req.get("id")
        if not addr:
            self._answer(session, server._error(
                "fleet.register: addr is required", req_id))
            return
        try:
            capacity = int(req.get("capacity") or 1)
        except (TypeError, ValueError):
            capacity = 1
        with self._cond:
            # a daemon bounce re-registers on the same address: the
            # stale entry is replaced (its affinities clear with it)
            for stale in [
                m for m in self._members.values() if m.addr == addr
            ]:
                self._evict_locked(stale, counted=False)
            self._member_seq += 1
            member = _Member(
                f"d{self._member_seq}", addr, capacity, session
            )
            self._members[member.id] = member
            session.member_id = member.id
        metrics.counter("fleet.registrations").inc()
        self._answer(session, {
            "ok": True, "op": "fleet.register", "member": member.id,
            "lease_s": self.lease_s(),
            **({"id": req_id} if req_id is not None else {}),
        })

    def _heartbeat(self, session: Session, req: dict) -> None:
        req_id = req.get("id")
        with self._cond:
            member = self._members.get(session.member_id or "")
        if member is None:
            # evicted (or never registered): tell the daemon so its
            # link re-registers instead of beating into the void
            self._answer(session, server._error(
                "fleet.heartbeat: not a registered member "
                "(re-register)", req_id))
            return
        metrics.counter("fleet.heartbeats").inc()
        if faults.fire("lease", "fleet.heartbeat_lost"):
            # the beat is "lost on the wire": acknowledged but the
            # lease is NOT refreshed, so it ages toward suspect; the
            # next (un-dropped) beat recovers it
            self._answer(session, {
                "ok": True, "op": "fleet.heartbeat",
                **({"id": req_id} if req_id is not None else {}),
            })
            return
        with self._cond:
            member.last_beat = time.monotonic()
            if member.suspect:
                member.suspect = False
                metrics.counter("fleet.recoveries").inc()
            member.queued = int(req.get("queued") or 0)
            member.reported_in_flight = int(req.get("in_flight") or 0)
            member.degraded = bool(req.get("degraded"))
            member.remote_active = bool(req.get("remote_active"))
            artifact = req.get("artifact")
            if isinstance(artifact, dict):
                member.artifact = {
                    key: int(artifact.get(key) or 0)
                    for key in _ARTIFACT_KEYS
                }
            namespaces = req.get("namespaces")
            if isinstance(namespaces, list):
                labels = {str(n) for n in namespaces[:256]}
                member.namespaces |= labels
                if member.remote_active:
                    # write-behind means every namespace a
                    # remote-active daemon has served is (or is about
                    # to be) populated in the shared tier — the signal
                    # cold-route placement weighs
                    self._populated |= labels
        self._answer(session, {
            "ok": True, "op": "fleet.heartbeat",
            **({"id": req_id} if req_id is not None else {}),
        })

    def _evict_locked(self, member: _Member, counted=True) -> None:
        """Remove a member (caller holds ``_cond``): its affinities
        clear so future routing re-decides, and any in-flight dispatch
        to it will fail on its own connection and re-dispatch."""
        self._members.pop(member.id, None)
        for key in [
            k for k, v in self._affinity.items() if v == member.id
        ]:
            del self._affinity[key]
        if member.session is not None:
            member.session.member_id = None
        if counted:
            metrics.counter("fleet.evictions").inc()
            # a lost daemon is exactly the moment a post-mortem wants
            # the ring for (anomaly() never blocks: _cond is held here)
            flight.anomaly("fleet.evict", {
                "member": member.id, "addr": member.addr,
            })

    def _monitor_loop(self) -> None:
        while True:
            lease = self.lease_s()
            if self._stop_event.wait(max(0.05, lease / 4.0)):
                return
            now = time.monotonic()
            with self._cond:
                for member in list(self._members.values()):
                    age = now - member.last_beat
                    if age > 2 * lease:
                        # second missed lease: evicted.  In-flight
                        # dispatches to it fail over on their own
                        self._evict_locked(member)
                    elif age > lease and not member.suspect:
                        member.suspect = True
                        metrics.counter("fleet.suspects").inc()
                        flight.anomaly("fleet.suspect", {
                            "member": member.id, "addr": member.addr,
                            "lease_age_s": round(age, 3),
                        })
                self._cond.notify_all()
            try:
                self._autoscale()
            except Exception:
                # the autoscaler must never take the health monitor
                # down with it — a failed spawn just retries next tick
                pass

    # -- elasticity (monitor thread) -------------------------------------

    def _scale_bounds(self) -> tuple:
        """``(min, max)`` daemon-pool bounds; ``(0, 0)`` means the
        autoscaler is off (the PR 14 fixed-fleet behavior)."""
        if self._elastic is not None:
            lo = int(self._elastic.get("min") or 0)
            hi = int(self._elastic.get("max") or 0)
        else:
            lo, hi = fleet_min(), fleet_max()
        if hi <= 0:
            return (0, 0)
        return (max(0, lo), max(hi, lo))

    def _reap_spawned(self) -> None:
        for addr, proc in list(self._spawned.items()):
            if proc.poll() is not None:
                self._spawned.pop(addr, None)

    def _spawn_member(self) -> None:
        """Spawn one daemon subprocess.  Shared-nothing by
        construction: each spawn gets a PRIVATE
        ``OPERATOR_FORGE_CACHE_DIR``, so the only artifact state it
        shares with the rest of the fleet is the remote cache it
        inherits through the environment — a cold spawn hydrates its
        trees from the shared tier, never from a sibling's disk."""
        if self._spawn_dir is None:
            self._spawn_dir = tempfile.mkdtemp(prefix="forge-fleet-")
        self._spawn_seq += 1
        tag = f"a{self._spawn_seq}"
        listen = os.path.join(self._spawn_dir, f"{tag}.sock")
        env = dict(os.environ)
        env.update((self._elastic or {}).get("env") or {})
        env["OPERATOR_FORGE_CACHE_DIR"] = os.path.join(
            self._spawn_dir, f"{tag}-cache"
        )
        try:
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "operator_forge.cli.main",
                    "daemon", "--listen", listen,
                    "--fleet", self.address(),
                ],
                cwd=self.base_dir, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
        except OSError:
            metrics.counter("fleet.spawn_failures").inc()
            return
        self._spawned[listen] = proc
        self._last_spawn = time.monotonic()
        metrics.counter("fleet.scale_ups").inc()
        flight.anomaly("fleet.scale_up", {"listen": listen})

    def _retire_member(self, member: _Member) -> None:
        """Retire one coordinator-spawned daemon: evicted first (no
        new dispatches route there), then drained on a background
        thread — its in-flight work is answered before it exits."""
        with self._cond:
            live = self._members.get(member.id)
            if live is not None:
                self._evict_locked(live, counted=False)
        metrics.counter("fleet.scale_downs").inc()
        flight.anomaly("fleet.scale_down", {
            "member": member.id, "addr": member.addr,
        })
        threading.Thread(
            target=self._drain_member, args=(member,), daemon=True,
            name=f"fleet-retire-{member.id}",
        ).start()

    def _autoscale(self) -> None:
        """One autoscaler tick (rides the monitor loop's lease/4
        cadence): spawn on queue or SLO pressure, retire on sustained
        idleness, always within ``[min, max]``."""
        self._reap_spawned()
        lo, hi = self._scale_bounds()
        if hi <= 0:
            return
        now = time.monotonic()
        with self._cond:
            members = list(self._members.values())
            queued = self._queued + sum(m.queued for m in members)
            busy = self._queued > 0 or any(
                m.in_flight or m.queued or m.reported_in_flight
                for m in members
            )
            member_addrs = {m.addr for m in members}
        healthy = [m for m in members if not m.suspect]
        # a spawn that has not registered yet still counts, or every
        # tick until its first heartbeat would spawn another
        pending = sum(
            1 for addr, proc in self._spawned.items()
            if addr not in member_addrs and proc.poll() is None
        )
        count = len(members) + pending
        # scale-up pressure: queue depth per healthy member, any
        # tenant's p99 over the knob, or deadline-miss growth.  The
        # SLO legs only count while work is in the system: percentiles
        # are cumulative, and a sticky over-bar p99 on an idle fleet
        # would flap spawn/retire forever
        pressure = count < lo
        if not pressure and count < hi and (busy or queued > 0):
            depth = queued / max(1, len(healthy))
            pressure = queued > 0 and (
                not healthy or depth >= scale_queue_threshold()
            )
            if not pressure:
                p99_bar = scale_p99_threshold()
                slo = metrics.slo_report()
                misses = sum(
                    row.get("deadline_misses", 0)
                    for row in slo.values()
                )
                if misses > self._slo_misses_seen:
                    self._slo_misses_seen = misses
                    pressure = True
                elif p99_bar > 0 and any(
                    row.get("p99", 0.0) > p99_bar
                    for row in slo.values()
                ):
                    pressure = True
        if pressure and count < hi:
            # one spawn per tick, rate-limited so a crash-looping
            # daemon binary cannot fork-bomb the host
            if now - self._last_spawn >= 1.0:
                self._spawn_member()
            return
        # scale-down: the fleet must sit FULLY idle for the idle
        # window, and only coordinator-spawned members retire
        if busy:
            self._idle_since = None
            return
        if self._idle_since is None:
            self._idle_since = now
            return
        if now - self._idle_since < scale_idle_seconds():
            return
        if len(members) <= lo:
            return
        victims = [m for m in members if m.addr in self._spawned]
        if not victims:
            return
        # newest spawn retires first (LIFO): the longest-lived daemons
        # hold the warmest mem-tiers
        victim = max(victims, key=lambda m: m.registered_at)
        self._idle_since = now  # one retirement per idle window
        self._retire_member(victim)

    # -- admission (reader threads) --------------------------------------

    def _enqueue(self, session: Session, req: dict) -> None:
        op = req.get("op")
        if op == "fleet.register":
            self._register_member(session, req)
            return
        if op == "fleet.heartbeat":
            self._heartbeat(session, req)
            return
        rejected = None
        with self._cond:
            if server.draining():
                rejected = "fleet coordinator is draining"
            elif len(session.queue) >= session_queue_depth():
                rejected = (
                    f"session queue full "
                    f"({session_queue_depth()} pending)"
                )
            elif self._queued >= global_queue_depth():
                rejected = (
                    f"admission queue full "
                    f"({global_queue_depth()} pending)"
                )
            else:
                session.queue.append((req, time.monotonic()))
                self._queued += 1
                metrics.counter("fleet.requests").inc()
                self._cond.notify()
        if rejected is not None:
            session.reject_busy(req, rejected)

    def _reader_finished(self, session: Session) -> None:
        if session.member_id is not None:
            # the registration connection dropped: the daemon process
            # is gone (or cut off) — evict now rather than waiting two
            # lease intervals for the lease to age out
            with self._cond:
                member = self._members.get(session.member_id)
                if member is not None:
                    self._evict_locked(member)
        with self._cond:
            self._cond.notify_all()
        self._maybe_close(session)

    def _maybe_close(self, session: Session) -> None:
        with self._cond:
            done = session.read_done and not session.busy and (
                not session.queue or session.dead.is_set()
            )
            if done:
                if session.queue:
                    metrics.counter("serve.requests_abandoned").inc(
                        len(session.queue)
                    )
                    self._queued -= len(session.queue)
                    session.queue.clear()
                if session in self._sessions:
                    self._sessions.remove(session)
                else:
                    done = False
        if done:
            session.close()

    # -- the scheduler ---------------------------------------------------

    def _next_work(self):
        with self._cond:
            while True:
                if server.draining():
                    return None
                n = len(self._sessions)
                for offset in range(n):
                    index = (self._rr + 1 + offset) % n
                    session = self._sessions[index]
                    if session.busy or not session.queue:
                        continue
                    if session.dead.is_set():
                        continue
                    self._rr = index
                    req, waited = session.pop_request()
                    self._queued -= 1
                    session.busy = True
                    return session, req, waited
                self._cond.wait(0.5)

    def _answer(self, session: Session, payload: dict) -> None:
        try:
            session.respond(payload)
        except server._AbandonedRequest:
            metrics.counter("serve.requests_abandoned").inc()

    def _dispatch_loop(self) -> None:
        while True:
            work = self._next_work()
            if work is None:
                return
            session, req, waited = work
            metrics.histogram("fleet.queue_wait.seconds").observe(
                waited
            )
            keep_going = True
            try:
                op = req.get("op") or (
                    "job" if "command" in req else None
                )
                if session.dead.is_set():
                    metrics.counter("serve.requests_abandoned").inc()
                elif op in ("job", "batch"):
                    # a traced submission adopts its context for the
                    # whole routing lifetime: the coordinator's own
                    # spans, the daemon's shipped segment, and any
                    # quarantined local run all land in one trace.
                    # The answer is written AFTER the routing spans
                    # close — the segment drain must include the
                    # `fleet:{op}` span itself (it is the parent the
                    # daemon's shipped segment hangs from; shipping
                    # from inside it would orphan the daemon's spans
                    # in the merged timeline)
                    tctx = spans.parse_trace_field(req)
                    if tctx is not None and spans.trace_enabled():
                        with spans.remote_segment(
                            tctx[0], tctx[1], "fleet"
                        ):
                            with spans.span(f"fleet:{op}"):
                                response = self._forward(
                                    req, op
                                )
                        if response is not None:
                            response["trace_events"] = (
                                spans.drain_trace(tctx[0])
                            )
                    else:
                        with spans.span(f"fleet:{op}"):
                            response = self._forward(req, op)
                    if response is not None:
                        self._answer(session, response)
                elif op in ("watch", "explain"):
                    self._answer(session, server._error(
                        f"op {op!r} is not routed by the fleet "
                        "coordinator; connect to a daemon directly",
                        req.get("id"),
                    ))
                else:
                    keep_going = dispatch_request(
                        req, self.base_dir, session.out_lock,
                        session.respond_locked, 0.0,
                    )
            finally:
                with self._cond:
                    session.busy = False
                    session.requests_total += 1
                    self._cond.notify_all()
            self._maybe_close(session)
            if not keep_going:
                # a client's shutdown op drains the WHOLE fleet
                server.request_shutdown()
                self.stop()

    # -- routing ---------------------------------------------------------

    def _route(self, affinity_key: str, reads, writes, excluded):
        """Pick (and charge) a member for one dispatch attempt;
        returns ``(member, stolen)`` — ``(None, False)`` when no
        member is routable, ``stolen`` True when the work-steal branch
        chose (a steal or cold route).  Caller releases via
        :meth:`_release`.  Deterministic: overlap-forced first (trees
        already in flight stay on their daemon, whose path locks
        serialize them), then healthy affinity, then the best-ranked
        healthy candidate (work-stealing) — rank weighs remote-cache
        locality between load classes: a member that has served the
        namespace holds it warm; when the namespace is known-populated
        in the shared remote tier, any remote-active member hydrates
        at the remote tier's cold-worker speedup; anything else
        recomputes cold — ties broken by load then member id."""
        with self._cond:
            # a quarantined submission running in-process holds its
            # trees too: overlapping work must wait, not route
            for held_reads, held_writes in self._local_roots:
                if _conflicts(reads, writes, held_reads, held_writes):
                    return None, False
            # a submission overlapping an in-flight dispatch MUST land
            # on that dispatch's member — two daemons writing one tree
            # would bypass every path lock in the system
            for member in sorted(
                self._members.values(), key=lambda m: m.id
            ):
                for held_reads, held_writes in member.active_roots:
                    if _conflicts(reads, writes,
                                  held_reads, held_writes):
                        if member.id in excluded:
                            # its attempt failed: let the re-dispatch
                            # loop back off and re-route
                            return None, False
                        return self._charge_locked(
                            member, affinity_key, reads, writes
                        ), False
            candidates = [
                m for m in self._members.values()
                if m.id not in excluded
            ]
            if not candidates:
                return None, False
            preferred = self._members.get(
                self._affinity.get(affinity_key, "")
            )
            if (
                preferred is not None
                and preferred.id not in excluded
                and not preferred.suspect
                and not preferred.degraded
                and preferred.in_flight < preferred.capacity
            ):
                chosen = preferred
                stolen = False
            else:
                # work-stealing: a degraded daemon sheds load before
                # it fails, a suspect one is routed only as last
                # resort, a member at capacity (the saturated affinity
                # owner is still a candidate) yields to any member
                # with a free slot — that IS the steal — and among
                # members with headroom artifact locality outranks raw
                # load: hydrating from the shared remote tier beats a
                # cold recompute on an idler member
                populated = affinity_key in self._populated

                def _rank(m):
                    if affinity_key in m.namespaces:
                        locality = 0
                    elif populated and m.remote_active:
                        locality = 1
                    else:
                        locality = 2
                    return (m.suspect, m.degraded,
                            m.in_flight >= m.capacity, locality,
                            m.in_flight + m.queued, m.id)

                chosen = min(candidates, key=_rank)
                stolen = True
                if preferred is not None and chosen is not preferred:
                    metrics.counter("fleet.steals").inc()
                if (
                    affinity_key in chosen.namespaces
                    or (populated and chosen.remote_active)
                ):
                    metrics.counter("fleet.locality_routes").inc()
            return self._charge_locked(
                chosen, affinity_key, reads, writes
            ), stolen

    def _charge_locked(self, member: _Member, affinity_key: str,
                       reads, writes) -> _Member:
        self._affinity[affinity_key] = member.id
        member.in_flight += 1
        member.dispatched += 1
        member.active_roots.append((reads, writes))
        return member

    def _release(self, member: _Member, reads, writes) -> None:
        with self._cond:
            member.in_flight = max(0, member.in_flight - 1)
            try:
                member.active_roots.remove((reads, writes))
            except ValueError:
                pass
            self._cond.notify_all()

    # -- dispatch --------------------------------------------------------

    def _forward(self, req: dict, op: str):
        """Route one submission; returns the FINAL response dict (the
        dispatch loop answers it after the routing spans close, so a
        traced submission's drained segment includes the ``fleet:op``
        span the daemon segments hang from), or ``None`` when nothing
        should be sent."""
        req_id = req.get("id")
        specs = specs_from_request(req)
        try:
            jobs = jobs_from_specs(specs, self.base_dir)
        except BatchManifestError as exc:
            return server._error(str(exc), req_id)
        key = specs_key(jobs)
        affinity_key = _scope_label(
            tuple(sorted({job.target() for job in jobs}))
        )
        reads = tuple(sorted({
            root for job in jobs for root in job.reads()
        }))
        writes = tuple(sorted({
            root for job in jobs for root in job.writes()
        }))
        # the crash-retry rule (PR 7): output roots absent at admission
        # are reset before any RE-dispatch, so a dead daemon's partial
        # tree is never adopted by preserve-on-exists scaffolding
        fresh_roots = [
            root for root in writes if not os.path.isdir(root)
        ]
        # the coordinator's own created-from-absence observation: any
        # local fallback sweep of these roots (quarantine, dead-member
        # retry) runs under the same fenceable-root containment the
        # daemon-side fence op enforces
        record_fenceable_roots(fresh_roots)
        if op == "job":
            forward_req = {"op": "job", "job": jobs[0].to_spec()}
        else:
            forward_req = {
                "op": "batch",
                "jobs": [job.to_spec() for job in jobs],
            }
        forward_req["id"] = key  # the idempotency key travels with it
        if spans.current_context() is not None:
            # a traced submission (the dispatch loop adopted its
            # segment): the child context makes the daemon's segment
            # parent onto the coordinator's current routing span, so
            # the merged timeline reads client -> coordinator -> daemon
            forward_req["trace"] = spans.rpc_context(key)

        budget = fleet_retries()
        excluded: set = set()
        attempt = 0
        pinned = None       # re-dispatch target forced by fencing
        need_fence = False  # the pinned member must fence first
        reset_next = True   # whether the next retry resets fresh roots
        dispatch_failed = False  # a dispatch died with work possibly
        #                          half-run (vs pure busy backpressure)
        busy_response = None     # the last busy answer, for honest
        #                          propagation when nothing ever failed
        started = time.perf_counter()
        while True:
            if attempt:
                time.sleep(_BACKOFF_S * attempt)  # deterministic
            # the crash-retry reset is DEFERRED until the retry's
            # target is routed: the target daemon fence-resets the
            # roots on ITS filesystem (shared-nothing: the coordinator
            # never reaches into a daemon's disk), then the local
            # containment-gated sweep covers the shared-fs topology
            reset_pending = reset_next and attempt > 0
            reset_next = True
            member = None
            stolen = False
            if pinned is not None:
                stale = pinned
                pinned = None
                with self._cond:
                    live = self._members.get(stale.id)
                    if live is not None:
                        self._charge_locked(
                            live, affinity_key, reads, writes
                        )
                        member = live
                if member is None:
                    # the pinned daemon was evicted between the probe
                    # and this retry — the zombie question is still
                    # open, so the fence runs against its last known
                    # address anyway: success means the roots were
                    # reset behind its path locks and ANY daemon may
                    # take the retry; a dead daemon fails both fence
                    # and probe, restoring the safe local-reset path;
                    # an alive-but-unfenceable one burns a bounded
                    # attempt
                    if self._fence_member(stale, reads, writes,
                                          fresh_roots):
                        need_fence = False
                    elif self._probe_member(stale):
                        if attempt >= budget:
                            return self._quarantine(
                                req_id, op, jobs, fresh_roots,
                                reads=reads, writes=writes,
                                last_member=stale,
                            )
                        attempt += 1
                        reset_next = False
                        pinned = stale
                        need_fence = True
                        continue
                    else:
                        self._reset_roots(fresh_roots)
                        need_fence = False
            if member is None:
                need_fence = False
                member, stolen = self._route(
                    affinity_key, reads, writes, excluded
                )
            if member is None:
                if not self._members:
                    if dispatch_failed:
                        # a dispatch already died (and may have
                        # half-run): the client's tree state is OURS
                        # to finish — quarantine, never bounce the
                        # mess back as busy
                        return self._quarantine(
                            req_id, op, jobs, fresh_roots,
                            reads=reads, writes=writes,
                        )
                    payload = server._error(
                        "no daemons registered with the fleet; retry",
                        req_id, kind="busy",
                    )
                    payload["retry_after"] = CONNECT_RETRY_AFTER_S
                    return payload
                if attempt >= budget:
                    if not dispatch_failed and busy_response is not None:
                        # only backpressure happened: nothing half-ran,
                        # so the honest answer is busy, not a local run
                        # that bypasses the fleet's admission control
                        busy_response["id"] = req_id
                        if req_id is None:
                            busy_response.pop("id", None)
                        return busy_response
                    return self._quarantine(
                        req_id, op, jobs, fresh_roots,
                        reads=reads, writes=writes,
                    )
                # members exist but every one is excluded (a lone
                # daemon whose dispatch failed, possibly transiently):
                # clear the exclusions so the next bounded attempt may
                # retry it rather than quarantining early
                excluded.clear()
                attempt += 1
                continue
            if reset_pending and not need_fence:
                # the deferred crash-retry reset: fence the retry's
                # target only when a dispatch actually died mid-run
                # (pure busy backpressure never created the roots)
                self._reset_roots(
                    fresh_roots,
                    member=member if dispatch_failed else None,
                    reads=reads, writes=writes,
                )
            if need_fence:
                # the previous attempt may still be running on this
                # member as a zombie: the fence queues behind its path
                # locks and resets the fresh roots server-side, so the
                # retry below starts from the same tree state a first
                # dispatch would have
                need_fence = False
                if not self._fence_member(member, reads, writes,
                                          fresh_roots):
                    self._release(member, reads, writes)
                    if attempt >= budget:
                        return self._quarantine(
                            req_id, op, jobs, fresh_roots,
                            reads=reads, writes=writes,
                            last_member=member,
                        )
                    if self._probe_member(member):
                        pinned = member
                        need_fence = True
                        reset_next = False  # the zombie may still live
                    else:
                        with self._cond:
                            live = self._members.get(member.id)
                            if live is not None:
                                self._evict_locked(live)
                        excluded.add(member.id)
                    attempt += 1
                    continue
            hung = faults.fire("route", "fleet.dispatch_hang")
            try:
                if hung:
                    # a hung daemon: the dispatch sleeps past the
                    # configured deadline, then the deadline verdict
                    # drives the normal re-dispatch path
                    deadline = dispatch_timeout() or _hang_seconds()
                    time.sleep(min(deadline, _hang_seconds()))
                    raise socket.timeout(
                        "injected fault: fleet.dispatch_hang@route"
                    )
                response = self._dispatch_once(
                    member, forward_req, stolen=stolen
                )
            except (OSError, ConnectionError, ValueError):
                # the dispatch failed with the submission possibly
                # mid-run.  The fencing decision is a fresh liveness
                # probe of the member:
                #
                # - DEAD (connect refused): the host is gone — no
                #   writer can still touch the output trees, so the
                #   retry resets the fresh roots and re-routes to a
                #   healthy daemon (the SIGKILL recovery path);
                # - ALIVE (a severed connection or a tripped dispatch
                #   deadline, not a dead host): the submission may
                #   STILL BE RUNNING there as a zombie writer, so
                #   resetting roots here would race it.  The retry
                #   pins the SAME daemon behind a fence op: the fence
                #   write-locks the submission's trees (queueing
                #   behind the zombie's path locks) and performs the
                #   fresh-root reset server-side once they are quiet —
                #   then the re-dispatch starts from first-attempt
                #   tree state, race-free.
                self._release(member, reads, writes)
                dispatch_failed = True
                if attempt >= budget:
                    return self._quarantine(
                        req_id, op, jobs, fresh_roots,
                        reads=reads, writes=writes,
                        last_member=member,
                    )
                if self._probe_member(member):
                    pinned = member
                    need_fence = True
                    reset_next = False  # the fence resets, serialized
                    with self._cond:
                        live = self._members.get(member.id)
                        if live is not None and not live.suspect:
                            live.suspect = True
                            metrics.counter("fleet.suspects").inc()
                else:
                    with self._cond:
                        live = self._members.get(member.id)
                        if live is not None:
                            self._evict_locked(live)
                    excluded.add(member.id)
                attempt += 1
                metrics.counter("fleet.redispatches").inc()
                flight.anomaly("fleet.redispatch", {
                    "member": member.id, "op": op,
                    "submission": key, "attempt": attempt,
                })
                continue
            self._release(member, reads, writes)
            if (
                response.get("ok") is False
                and response.get("error_kind") == "busy"
            ):
                # backpressure, not failure: the daemon is alive but
                # full — retry within the budget, then propagate the
                # busy answer honestly.  The busy member is EXCLUDED
                # for the remaining attempts (not evicted): the failed
                # attempt's _charge_locked just rewrote the affinity
                # entry to point at it, and its heartbeat-reported
                # queue depth refreshes far slower than the retry
                # backoff, so without the exclusion every retry would
                # re-route straight back to the one full daemon while
                # idle siblings sit unused (with a single member, the
                # all-excluded branch above clears the set and retries
                # it anyway, still bounded)
                if attempt >= budget:
                    response["id"] = req_id
                    if req_id is None:
                        response.pop("id", None)
                    return response
                busy_response = response
                excluded.add(member.id)
                attempt += 1
                metrics.counter("fleet.busy_retries").inc()
                continue
            break
        with self._cond:
            live = self._members.get(member.id)
            if live is not None:
                # the dispatch landed: the member now holds this
                # namespace warm, and — write-behind — a remote-active
                # member has populated it in the shared tier, which is
                # what lets a future cold route (or a daemon that never
                # saw this tree) hydrate over the network
                live.namespaces.add(affinity_key)
                if live.remote_active:
                    self._populated.add(affinity_key)
        elapsed = time.perf_counter() - started
        metrics.histogram("fleet.dispatch.seconds").observe(elapsed)
        metrics.counter("fleet.dispatches").inc()
        # per-tenant SLO at the fleet edge: the affinity key IS the
        # project-namespace label, so coordinator latency and daemon
        # cache attribution key on the same tenants
        metrics.observe_slo(affinity_key, elapsed)
        if req_id is not None:
            response["id"] = req_id
        else:
            response.pop("id", None)
        return response

    def _reset_roots(self, fresh_roots, member: _Member = None,
                     reads=(), writes=()) -> None:
        """The shared-nothing crash-retry reset.  Output roots absent
        at admission are cleared before a re-dispatch — WITHOUT the
        coordinator reaching into any daemon's filesystem: when the
        retry's target is known, its ``fence`` op resets the roots on
        the daemon's own disk (a no-op for roots it never observed
        created-from-absence); the local sweep then covers the
        shared-filesystem topology, gated by the coordinator's own
        fenceable-root containment — on a true shared-nothing fleet
        the roots never existed on this host and the sweep is
        structurally empty."""
        if not fresh_roots:
            return
        if member is not None:
            self._fence_member(member, reads, writes, fresh_roots)
        for root in fresh_roots:
            if os.path.isdir(root) and is_fenceable_root(root):
                shutil.rmtree(root, ignore_errors=True)

    def _probe_member(self, member: _Member) -> bool:
        """The fencing probe: is the daemon at ``member.addr`` alive
        right now?  A fresh connect + ping with a short deadline — the
        answer decides whether a failed dispatch's retry may reset
        output roots and re-route (dead: nothing can still be writing)
        or must pin the same daemon without a reset (alive: a zombie
        writer may still hold the trees, and only that daemon's path
        locks can serialize the retry behind it)."""
        try:
            client = DaemonClient(
                member.addr, timeout=min(2.0, self.lease_s()),
                retries=0,
            )
        except (OSError, ConnectionError):
            return False
        try:
            return bool(client.request({"op": "ping"}).get("ok"))
        except (OSError, ConnectionError, ValueError):
            return False
        finally:
            client.close()

    def _fence_member(self, member: _Member, reads, writes,
                      fresh_roots) -> bool:
        """Run the zombie fence on ``member``: a ``fence`` op whose
        roots cover the submission's trees.  The daemon write-locks
        them (waiting out any zombie writer) and resets the fresh
        roots under the lock.  ``False`` when the fence could not run
        (transport gone, or the zombie outlived the daemon's bounded
        lock wait and the fence answered busy) — the caller decides
        between another bounded attempt and quarantine."""
        try:
            client = DaemonClient(member.addr, timeout=90.0, retries=0)
        except (OSError, ConnectionError):
            return False
        try:
            response = client.request({
                "op": "fence",
                "roots": list(reads) + list(writes),
                "reset": list(fresh_roots),
                "id": "fence",
            })
            return response.get("ok") is True
        except (OSError, ConnectionError, ValueError):
            return False
        finally:
            client.close()

    def _dispatch_once(self, member: _Member, forward_req: dict,
                       stolen: bool = False):
        """One dispatch round trip to a member daemon.  Raises on any
        transport failure (the caller's re-dispatch loop owns
        recovery); a fresh connection per dispatch keeps failure
        semantics crisp — a dead daemon is an immediate connect or
        read error, never a stale pooled socket."""
        timeout = dispatch_timeout() or None
        client = DaemonClient(member.addr, timeout=timeout, retries=0)
        try:
            client.send(forward_req)
            if faults.fire("dispatch", "fleet.daemon_crash"):
                # the daemon "dies" after the job was sent but before
                # its response is read — the exact mid-run crash shape
                # SIGKILL produces; the submission's idempotency is
                # what makes the re-dispatch safe
                raise ConnectionError(
                    "injected fault: fleet.daemon_crash@dispatch"
                )
            if stolen and faults.fire("steal", "fleet.steal_kill"):
                # kill-during-steal: the steal/cold-route target dies
                # AFTER the stolen submission was sent, its tree still
                # hydrating from the remote tier — the fence +
                # re-dispatch path must leave no half-hydrated root
                # behind.  The site only counts stolen dispatches, so
                # nth-hit selection is deterministic over steals
                raise ConnectionError(
                    "injected fault: fleet.steal_kill@steal"
                )
            response = client.read()
            if response is None:
                raise ConnectionError("daemon closed mid-dispatch")
            if isinstance(response, dict):
                # the daemon's shipped span segment lands in OUR ring
                # (tagged with the submission's trace), to be drained
                # into the client's response by the caller.  Own-pid
                # events are skipped: an in-process daemon's segment
                # copies are already retained in this ring
                events = response.pop("trace_events", None)
                if events:
                    own = os.getpid()
                    spans.ingest_events(
                        [e for e in events if e.get("pid") != own]
                    )
            return response
        finally:
            client.close()

    def _quarantine(self, req_id, op: str, jobs,
                    fresh_roots, reads=(), writes=(),
                    last_member=None) -> dict:
        """The poison-submission backstop, mirroring the workers
        layer's quarantine-to-thread: a submission that exhausted its
        re-dispatch budget runs ONCE in-process, so it completes (or
        fails on its own merits) without taking more daemons with it.

        Before the local run, the zombie question is settled one last
        time: if the final failed dispatch's daemon may still be alive
        (``last_member``), the fence runs against it — success means
        the trees are quiet and the roots already reset server-side; a
        failed fence against a still-alive daemon gets one lease-long
        grace period (a genuinely wedged writer is the one residual
        race a coordinator without kill authority cannot close, so it
        is bounded and documented rather than ignored)."""
        metrics.counter("fleet.jobs_quarantined").inc(len(jobs))
        flight.anomaly("fleet.quarantine", {
            "op": op, "jobs": len(jobs),
            "last_member": getattr(last_member, "id", None),
        })
        fenced = False
        if last_member is not None:
            fenced = self._fence_member(
                last_member, reads, writes, fresh_roots
            )
            if not fenced and self._probe_member(last_member):
                time.sleep(self.lease_s())
        # bounded wait for overlapping in-flight dispatches (and
        # sibling quarantines) to clear, then HOLD the trees in
        # _local_roots so _route refuses to hand them to a daemon
        # while the local run writes them
        hold = (reads, writes)
        deadline = time.monotonic() + self.lease_s()
        with self._cond:
            while time.monotonic() < deadline:
                if not any(
                    _conflicts(reads, writes, held_r, held_w)
                    for held_r, held_w in [
                        roots
                        for m in self._members.values()
                        for roots in m.active_roots
                    ] + self._local_roots
                ):
                    break
                self._cond.wait(0.1)
            self._local_roots.append(hold)
        try:
            if not fenced:
                # the coordinator IS the executor here, so the local
                # reset is legitimate — and containment-gated like
                # every other sweep
                self._reset_roots(fresh_roots)
            started = time.perf_counter()
            if op == "job":
                response = run_job(jobs[0]).to_dict()
                response["op"] = "job"
            else:
                results = run_batch(jobs)
                response = {
                    "ok": all(r.ok for r in results),
                    "op": "batch",
                    "results": [r.to_dict() for r in results],
                    "cached": sum(1 for r in results if r.cached),
                    "seconds": round(
                        time.perf_counter() - started, 4
                    ),
                }
        finally:
            with self._cond:
                try:
                    self._local_roots.remove(hold)
                except ValueError:
                    pass
                self._cond.notify_all()
        if req_id is not None:
            response["id"] = req_id
        return response

    # -- stats -----------------------------------------------------------

    def _stats_payload(self) -> dict:
        now = time.monotonic()
        lo, hi = self._scale_bounds()
        with self._cond:
            members = {
                m.id: {
                    "addr": m.addr,
                    "artifact": {
                        key: m.artifact.get(key, 0)
                        for key in _ARTIFACT_KEYS
                    },
                    "capacity": m.capacity,
                    "degraded": bool(m.degraded),
                    "dispatched": m.dispatched,
                    "in_flight": m.in_flight,
                    "lease_age_s": round(
                        max(0.0, now - m.last_beat), 3
                    ),
                    "namespaces": len(m.namespaces),
                    "queued": m.queued,
                    "spawned": m.addr in self._spawned,
                    "state": "suspect" if m.suspect else "healthy",
                }
                for m in self._members.values()
            }
            queued = self._queued
            affinities = len(self._affinity)
            populated = len(self._populated)
            spawned_live = sum(
                1 for proc in self._spawned.values()
                if proc.poll() is None
            )
        return {
            "affinities": affinities,
            "counters": {
                name: metrics.counter(name).value()
                for name in (
                    "fleet.busy_retries", "fleet.dispatches",
                    "fleet.evictions", "fleet.heartbeats",
                    "fleet.jobs_quarantined", "fleet.locality_routes",
                    "fleet.recoveries", "fleet.redispatches",
                    "fleet.registrations", "fleet.scale_downs",
                    "fleet.scale_ups", "fleet.steals",
                    "fleet.suspects",
                )
            },
            "editor": metrics.editor_report(),
            "lease_s": self.lease_s(),
            "listen": self.address(),
            "members": {k: members[k] for k in sorted(members)},
            "populated_namespaces": populated,
            "queued_requests": queued,
            "scale": {
                "max": hi,
                "min": lo,
                "spawned_live": spawned_live,
            },
            "slo": metrics.slo_report(),
        }

    # -- teardown --------------------------------------------------------

    def _drain_member(self, member: _Member) -> None:
        """Ask one daemon to drain (the coordinator-initiated bounce):
        its shutdown op finishes in-flight work, answers every session,
        and exits 0 — the daemon-side machinery PR 10 shipped."""
        try:
            client = DaemonClient(member.addr, timeout=60.0, retries=0)
        except (OSError, ConnectionError):
            return  # already gone
        try:
            client.send({"op": "shutdown"})
            # the ack, then the drained line; either may be cut short
            # if the daemon wins the race to close
            client.read()
            client.read()
        except (OSError, ConnectionError, ValueError):
            pass
        finally:
            client.close()

    def stop(self) -> None:
        """Drain and tear down (idempotent): in-flight dispatches
        finish and are answered, queued clients are answered ``busy``
        with retry_after, every registered daemon is drained, every
        session gets the final drained-shutdown line, exit 0."""
        with self._stop_lock:
            if self._stopped:
                self._stop_done.wait(120.0)
                return
            self._stopped = True
        server.request_shutdown()  # idempotent; runs _on_drain once
        current = threading.current_thread()
        for thread in self._dispatchers:
            if thread is not current:
                thread.join(120.0)
        with self._cond:
            sessions = list(self._sessions)
            self._sessions.clear()
            queued = [
                (session, req)
                for session in sessions
                for (req, _t) in session.queue
            ]
            for session in sessions:
                session.queue.clear()
            self._queued = 0
            members = list(self._members.values())
            self._members.clear()
            self._affinity.clear()
        # the drain promise: a queued client is ANSWERED, never
        # silently dropped — busy + retry_after, the same shape
        # admission control uses
        for session, req in queued:
            session.reject_busy(req, "fleet coordinator is draining")
        drainers = [
            threading.Thread(
                target=self._drain_member, args=(member,), daemon=True,
            )
            for member in members
        ]
        for thread in drainers:
            thread.start()
        for thread in drainers:
            thread.join(90.0)
        # coordinator-spawned daemons were drained above (they were
        # registered members); anything still running gets an
        # escalating terminate/kill so the fleet never leaks processes
        for addr, proc in list(self._spawned.items()):
            try:
                proc.wait(10.0)
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(5.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    try:
                        proc.wait(5.0)
                    except subprocess.TimeoutExpired:  # pragma: no cover
                        pass
        self._spawned.clear()
        if self._spawn_dir is not None:
            shutil.rmtree(self._spawn_dir, ignore_errors=True)
            self._spawn_dir = None
        for session in sessions:
            try:
                session.respond(
                    {"ok": True, "op": "shutdown", "drained": True}
                )
            except Exception:
                pass
            session.close()
        thread = self._accept_thread
        if thread is not None and thread is not current:
            thread.join(5.0)
        thread = self._monitor
        if thread is not None and thread is not current:
            thread.join(5.0)
        if self.spec[0] == "unix":
            try:
                os.unlink(self.spec[1])
            except OSError:
                pass
        server.remove_drain_callback(self._on_drain)
        server.unregister_stats_source("fleet")
        metrics.unregister_gauge("fleet.members")
        metrics.unregister_gauge("fleet.queued_requests")
        # persist the black box + timeline; global state released only
        # when no sibling server remains (see server.py)
        server.release_server_telemetry()
        self._stop_done.set()


def serve_fleet(listen: str, lease: float = None, clients=None,
                elastic: dict = None) -> int:
    """The ``operator-forge fleet`` entry point: bind, print one status
    line on stderr, coordinate until SIGTERM/SIGINT (or a client's
    shutdown op), then drain the whole fleet and exit 0."""
    coordinator = FleetCoordinator(
        listen, lease=lease, clients=clients, elastic=elastic
    )
    coordinator._bind()
    lo, hi = coordinator._scale_bounds()
    scale_note = f", autoscale {lo}..{hi}" if hi else ""
    print(
        f"fleet: coordinating on {coordinator.address()} "
        f"(lease {coordinator.lease_s():g}s{scale_note})",
        file=sys.stderr, flush=True,
    )
    installed = []
    if threading.current_thread() is threading.main_thread():
        import signal

        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                installed.append((
                    signum,
                    signal.signal(signum, server.request_shutdown),
                ))
            except (ValueError, OSError):  # pragma: no cover
                pass
    try:
        coordinator.serve_forever()
    except server._DrainSignal:
        pass  # signal broke the blocked accept: drain below
    finally:
        coordinator.stop()
        if installed:
            import signal

            for signum, previous in installed:
                try:
                    signal.signal(signum, previous)
                except (ValueError, OSError):  # pragma: no cover
                    pass
    print("fleet: drained, exiting", file=sys.stderr, flush=True)
    return 0


def fleet_status(addr: str):
    """One ``stats`` round trip to a coordinator (or daemon), returning
    the full stats payload — the ``fleet-status`` CLI's data source."""
    with DaemonClient(addr) as client:
        return client.request({"op": "stats"})
