"""``operator-forge watch`` — the edit loop, served.

Polls the directories a job set reads (mtime+size first, content hash
only for files that moved), feeds each delta into the dependency graph
(:data:`operator_forge.perf.depgraph.GRAPH` — reverse-dependency
invalidation), and re-runs the job set; the incremental layers
underneath (index delta, per-file analysis replay, per-package suite
replay, per-job/group replay) recompute only what the edit reached.
Each cycle emits one JSON-serializable payload::

    {"op": "watch", "cycle": N, "changed": [...], "removed": [...],
     "results": [<job result>...], "ok": true,
     "graph": {"dirty": d, "reused": r, "recomputed": c},
     "provenance": [{"file": rel, "event": "changed",
                     "chain": [...]}, ...],
     "seconds": s}

``graph`` counts are per-cycle deltas of the shared graph counters
(also surfaced cumulatively by the serve ``stats`` op).
``provenance`` is the per-cycle invalidation story — for every changed
or removed file, the deterministic chain of artifacts it dirtied,
derived structurally by :mod:`operator_forge.gocheck.explain` (so it
is identical whatever cache mode or worker backend ran the cycle).
Each cycle's wall time also lands in the ``watch.cycle.seconds``
metrics histogram (p50/p99 via serve ``stats``).  Jobs run in-process
(groups in manifest order through the shared runner) so every cycle
reuses the resident caches — the point of watching.

The loop is deliberately injectable for tests and the serve op:
``cycles`` bounds how many job runs happen (the first cycle always
runs, unconditionally — it primes the graph), ``poll`` overrides the
sleep between polls (tests mutate the tree there), and ``emit``
receives each payload as it completes.
"""

from __future__ import annotations

import os
import time

from ..perf import faults, metrics
from ..perf import overlay as pf_overlay
from ..perf.depgraph import GRAPH
from .batch import plan_groups
from .runner import run_group

#: the most recent cycle's change set — ``(root, rel)`` pairs — kept so
#: a later serve ``explain`` op (no explicit ``changed`` list) can
#: answer "why did the last cycle recompute?"
LAST_CHANGED: list = []
LAST_REMOVED: list = []


def watch_roots(jobs) -> list:
    """The directories whose bytes can invalidate any of *jobs* —
    every job's read set plus its output tree (a generated dir is the
    next job's input, and external edits to it must trigger too)."""
    roots: list = []
    for job in jobs:
        for root in job.reads() + job.writes():
            if root not in roots:
                roots.append(root)
    return roots


def snapshot(roots) -> dict:
    """``{root: {relpath: (mtime_ns, size)}}`` for every regular file
    under each root, with the tree-state pruning rules (dot-dirs and
    dot-files skipped).  Stat-only: content hashes happen lazily in
    the layers below, through their stat-validated memo.

    A file that vanishes between listing and stat (an editor's
    atomic-rename replace, a build step's temp file) is simply skipped
    — it reads as removed this poll and reappears on the next, which
    the invalidation layer already handles; the ``watch.vanish`` chaos
    fault exercises exactly this race."""
    # one enabled() probe per poll, not one per scanned file: with no
    # fault spec active the stat-only hot loop must stay stat-only
    # (10k files × 2 Hz would otherwise pay 20k registry probes/s)
    chaos = faults.enabled()
    if chaos and faults.should_fire("watch.scan_error", "scan.walk"):
        raise OSError("injected fault: watch.scan_error@scan.walk")
    out: dict = {}
    for root in roots:
        files: dict = {}
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames if not d.startswith(".")
            )
            for name in sorted(filenames):
                if name.startswith("."):
                    continue
                path = os.path.join(dirpath, name)
                if chaos and faults.should_fire("watch.vanish", "scan"):
                    continue  # chaos: lost the stat race on this file
                try:
                    st = os.stat(path)
                except OSError:
                    continue  # vanished mid-scan: the real race
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                files[rel] = (st.st_mtime_ns, st.st_size)
        if pf_overlay.count():
            # an overlaid file's signature is its overlay version, not
            # its disk stat: setting, editing, or clearing an overlay
            # reads as a tree change and triggers the minimal re-run
            files.update(pf_overlay.signatures_under(root))
        out[root] = files
    return out


def _snapshot_with_retry(roots, retries: int = 3, backoff: float = 0.05):
    """:func:`snapshot` with bounded deterministic backoff: a transient
    ``OSError`` from the walk (a directory swapped out mid-scan, an
    NFS hiccup, the injected ``watch.scan_error``) must degrade to a
    skipped poll, never kill a long-lived watch loop.  Returns ``None``
    when the tree stayed unreadable — the caller keeps its previous
    state and polls again."""
    for attempt in range(retries + 1):
        try:
            return snapshot(roots)
        except OSError:
            if attempt < retries:
                # counted only when a retry actually follows; the final
                # failed attempt is the poll's one scan_failures, not
                # a phantom extra retry
                metrics.counter("watch.scan_retries").inc()
                time.sleep(backoff * (attempt + 1))
    metrics.counter("watch.scan_failures").inc()
    return None


def diff_snapshots(prev: dict, cur: dict) -> tuple:
    """(changed, removed) path lists — each entry ``(root, rel)`` —
    between two :func:`snapshot` results."""
    changed: list = []
    removed: list = []
    for root, files in cur.items():
        before = prev.get(root, {})
        for rel, sig in files.items():
            if before.get(rel) != sig:
                changed.append((root, rel))
        for rel in before:
            if rel not in files:
                removed.append((root, rel))
    return changed, removed


def _invalidate(changed, removed) -> int:
    """Feed a delta into the dependency graph: every touched file's
    source node is invalidated, sweeping its transitive dependents."""
    keys = []
    for root, rel in list(changed) + list(removed):
        path = os.path.join(root, rel)
        keys.append(("src", rel))
        keys.append(("src", path))
    return GRAPH.invalidate(keys) if keys else 0


def run_jobs(jobs) -> list:
    """One in-process pass over the job set: groups planned by
    read/write conflict, run in manifest order, results in input
    order (the watch loop's unit of work)."""
    groups = plan_groups(jobs)
    by_index: dict = {}
    for group in groups:
        for result in run_group(group):
            by_index[result.index] = result
    return [by_index[job.index] for job in jobs]


def _group_by_root(changed, removed) -> dict:
    """``{root: ([changed rels], [removed rels])}`` from the watch
    loop's ``(root, rel)`` pairs — rels stay relative to the watch
    root they were recorded under."""
    by_root: dict = {}
    for idx, pairs in enumerate((changed, removed)):
        for root, rel in pairs:
            by_root.setdefault(root, ([], []))[idx].append(rel)
    return by_root


def _provenance_summary(changed, removed) -> list:
    """Per-cycle invalidation story: for every touched file, the
    deterministic structural chain from the edit to the artifacts it
    dirtied (grouped per watch root, roots in sorted order)."""
    from ..gocheck.explain import explain_summary

    by_root = _group_by_root(changed, removed)
    out: list = []
    for root in sorted(by_root):
        rels_changed, rels_removed = by_root[root]
        out.extend(explain_summary(root, rels_changed, rels_removed))
    return out


def last_cycle_explain() -> tuple:
    """``(sorted roots, structured changes, joined text report)`` for
    the most recent cycle's recorded change set — the serve ``explain``
    op's no-change-set answer.  Empty roots means nothing recorded."""
    from ..gocheck.explain import (
        explain_report,
        explain_summary,
        package_imports,
    )

    by_root = _group_by_root(LAST_CHANGED, LAST_REMOVED)
    changes: list = []
    reports: list = []
    for root in sorted(by_root):
        rels_changed, rels_removed = by_root[root]
        # one shared walk per root for both renderings
        imports = package_imports(root) if os.path.isdir(root) else {}
        changes.extend(explain_summary(
            root, rels_changed, rels_removed, imports=imports))
        reports.append(explain_report(
            root, rels_changed, rels_removed, imports=imports))
    return sorted(by_root), changes, "".join(reports)


def watch_cycle(jobs, cycle: int, changed=(), removed=(),
                dirtied: int = 0) -> dict:
    """Run the job set once and package the per-cycle payload.
    ``dirtied`` is the node count the pre-cycle invalidation swept."""
    counters_before = GRAPH.counters()
    started = time.perf_counter()
    results = run_jobs(jobs)
    seconds = time.perf_counter() - started
    metrics.histogram("watch.cycle.seconds").observe(seconds)
    counters_after = GRAPH.counters()
    graph = {
        key: counters_after[key] - counters_before[key]
        for key in ("dirty", "reused", "recomputed")
    }
    graph["dirty"] += dirtied
    return {
        "op": "watch",
        "cycle": cycle,
        "changed": sorted(rel for _root, rel in changed),
        "removed": sorted(rel for _root, rel in removed),
        "ok": all(r.ok for r in results),
        "results": [r.to_dict() for r in results],
        "graph": graph,
        "provenance": _provenance_summary(changed, removed),
        "seconds": round(seconds, 4),
    }


def watch_loop(jobs, emit, cycles=None, interval: float = 0.5,
               poll=None) -> int:
    """Poll-and-rerun until ``cycles`` job runs have happened (forever
    when ``None``).  The first cycle runs unconditionally; afterwards a
    cycle fires only when the snapshot actually changed.  ``poll()``
    replaces the between-poll sleep (tests edit the tree there; a
    ``False`` return stops the loop).  Returns the number of cycles
    run."""
    roots = watch_roots(jobs)
    write_roots = []
    for job in jobs:
        for root in job.writes():
            if root not in write_roots:
                write_roots.append(root)

    def absorb_own_writes(state: dict) -> None:
        # a cycle's own output (init/create regenerating its tree) must
        # not read as an external edit on the next poll — a watch whose
        # manifest writes would otherwise hot-loop on itself.  Only the
        # write roots re-snapshot; an external edit to a READ root that
        # raced the cycle still diffs against the pre-cycle baseline.
        if not write_roots:
            return
        cur = _snapshot_with_retry(write_roots)
        if cur is not None:
            state.update(cur)

    ran = 0
    # baseline BEFORE the first cycle: an edit landing while cycle 0
    # runs (an overlay op racing the subscribe prime, say) diffs
    # against the pre-cycle state and fires one redundant (but
    # correct) cycle instead of being silently absorbed into the
    # baseline and lost.  An unreadable first snapshot primes empty:
    # the next successful poll then reads every file as changed —
    # same redundant-cycle recovery, never a dead loop.
    state = _snapshot_with_retry(roots) or {}
    emit(watch_cycle(jobs, ran))
    ran += 1
    absorb_own_writes(state)
    while cycles is None or ran < cycles:
        if poll is not None:
            if poll() is False:
                break
        else:  # pragma: no cover - timing loop
            time.sleep(interval)
        cur = _snapshot_with_retry(roots)
        if cur is None:
            continue  # tree unreadable this poll: keep state, retry
        changed, removed = diff_snapshots(state, cur)
        if not changed and not removed:
            continue
        state = cur
        LAST_CHANGED[:] = sorted(changed)
        LAST_REMOVED[:] = sorted(removed)
        dirtied = _invalidate(changed, removed)
        emit(watch_cycle(jobs, ran, changed, removed, dirtied))
        ran += 1
        absorb_own_writes(state)
    return ran


def cmd_watch(manifest_path: str, cycles=None, interval: float = 0.5,
              json_lines: bool = False, out=None) -> int:
    """The ``operator-forge watch`` CLI: watch a batch manifest's jobs
    and re-run them on every tree change, streaming one JSON line (or
    a human summary) per cycle."""
    import json as _json
    import sys

    from .jobs import BatchManifestError, load_manifest

    out = out if out is not None else sys.stdout
    try:
        jobs = load_manifest(manifest_path)
    except BatchManifestError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    failures = []

    def emit(payload: dict) -> None:
        if not payload["ok"]:
            failures.append(payload["cycle"])
        if json_lines:
            print(_json.dumps(payload), file=out, flush=True)
            return
        graph = payload["graph"]
        edits = ""
        if payload["changed"] or payload["removed"]:
            edits = " (%s)" % ", ".join(
                payload["changed"] + [f"-{r}" for r in payload["removed"]]
            )
        print(
            "cycle %d: %s %d jobs in %.2fs — graph dirty=%d reused=%d "
            "recomputed=%d%s"
            % (
                payload["cycle"],
                "ok" if payload["ok"] else "FAIL",
                len(payload["results"]),
                payload["seconds"],
                graph["dirty"], graph["reused"], graph["recomputed"],
                edits,
            ),
            file=out, flush=True,
        )
        for entry in payload.get("provenance", ()):
            print(f"  why: {entry['file']} {entry['event']}", file=out)
            for line in entry["chain"]:
                print(f"  {line}", file=out)
        for result in payload["results"]:
            if not result["ok"]:
                print(f"  FAIL {result['id']} ({result['command']})",
                      file=out)
                for line in result["stderr"].rstrip().splitlines():
                    print(f"      {line}", file=out)

    try:
        watch_loop(jobs, emit, cycles=cycles, interval=interval)
    except KeyboardInterrupt:  # pragma: no cover - interactive exit
        pass
    return 1 if failures else 0
