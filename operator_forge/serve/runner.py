"""Execute one batch/serve job in-process, with dirty-tracked replay.

A job's observable effect — its output tree mutation plus its
stdout/stderr/exit code — is a deterministic function of (a) the input
tree it reads (the workload-config directory for generation commands,
the project tree for checking commands) and (b) the output tree it
writes into.  Both are snapshotted as file-hash sets through the shared
:class:`~operator_forge.perf.cache.ContentCache` and folded into the
job's content key, so an unchanged re-submission replays the recorded
result without executing anything; any drifted byte produces a
different key and falls back to a live run.

Replay is only ever recorded for *fixed-point* executions — runs that
left the output tree exactly as they found it (checking commands
trivially; generation commands once the project has converged, which
takes the usual two generations while the scaffold picks up its own
boilerplate).  Skipping a fixed-point job is indistinguishable from
re-running it, so cached and live batches stay byte-identical — the
property tests/test_serve_batch.py and bench.py's batch identity guard
enforce.

Two granularities, because an ``init`` re-run over a *finished* project
is deliberately not idempotent (it restores init's minimal ``main.go``,
which the following ``create api`` overwrites with the full one):

- :func:`run_job` — per-job replay, engages for vet/test always and for
  generation jobs whose project has converged under that command alone;
- :func:`run_group` — whole-chain replay for a scheduling group: the
  ``init -> create api -> vet -> test`` cycle maps a steady tree onto
  itself even though its members individually do not, so an unchanged
  group replays as a unit and a dirty-tracked re-batch recomputes only
  the touched group.

Modes follow ``OPERATOR_FORGE_CACHE``: ``off`` always executes, ``mem``
replays within one process (the serve loop's warm path), ``disk``
replays across processes through the HMAC-signed store (how persistent
process-pool workers share a primed batch).
"""

from __future__ import annotations

import contextlib
import io
import os
import sys
import threading
import time

from .. import __version__
from ..perf import cache as pf_cache
from ..perf import env_number, faults, metrics, spans
from .jobs import Job, JobResult

_STAGE = "serve.job"
_SCHEMA = 1

# -- per-project cache namespaces (daemon) -------------------------------
#
# The daemon serves many clients on many trees through ONE ContentCache.
# Content keys already make cross-tree collisions impossible (every key
# folds in the tree-state hashes), but one flat namespace means one
# project's churn competes with every other's in the mem/disk LRU and
# the per-namespace stats lump all clients together.  With scoping
# enabled (the daemon turns it on), each job's replay records land in a
# per-project namespace — `serve.job.<12-hex of its target dir>` —
# layered on the shared store: eviction pressure and hit/miss
# attribution partition per tree, and the bytes recorded are identical
# (replay == re-run still holds, namespaces only partition the store).

_project_scoped = [False]


def set_project_scoping(enabled: bool) -> None:
    """Enable per-project cache namespaces (the daemon's setting; the
    stdio serve loop and one-shot batch keep the flat namespace)."""
    _project_scoped[0] = bool(enabled)


def project_scoping() -> bool:
    return _project_scoped[0]


def _scope_label(roots) -> str:
    return pf_cache.hash_parts(tuple(sorted(roots)))[:12]


def _job_stage(job: Job) -> str:
    if not _project_scoped[0]:
        return _STAGE
    return f"{_STAGE}.{_scope_label((job.target(),))}"


# -- served scopes (fleet locality signal) --------------------------------
#
# Which per-project scope labels this process has served, most recent
# last, FIFO-bounded like the fenceable-root registry.  The daemon
# ships the list in its fleet heartbeats: a member that has served a
# tree before holds its replay records warm (mem/disk tiers), and —
# with the remote tier active — has populated the shared cache-server
# namespace for it, so the coordinator's steal/cold-route placement
# can weigh cache locality alongside load.

_SCOPES_MAX = 256

_scopes_lock = threading.Lock()
_scopes: dict = {}  # label -> True, insertion-ordered


def _record_scope(label: str) -> None:
    if not _project_scoped[0]:
        return
    with _scopes_lock:
        _scopes.pop(label, None)
        _scopes[label] = True
        while len(_scopes) > _SCOPES_MAX:
            del _scopes[next(iter(_scopes))]


def served_scopes() -> tuple:
    """Scope labels (per-project namespace hashes) this process has
    served, most recent last, bounded at 256."""
    with _scopes_lock:
        return tuple(_scopes)

# -- fenceable roots (the fleet's zombie fence) ---------------------------
#
# The ``fence`` op resets output roots so a re-dispatched submission
# starts from first-attempt tree state — but an op that rmtrees
# caller-supplied paths must be CONTAINED: before this registry, any
# connected client could delete any directory the daemon user can
# remove, when no other serve op can delete anything (scaffolding is
# preserve-on-exists).  The fence may only reset a root this process
# itself observed being created from absence — exactly the set the
# fleet's crash-retry rule resets (roots absent at admission) — so a
# pre-existing tree can never become deletable through the protocol.

_FENCEABLE_MAX = 4096  # FIFO-bounded; far above any live fleet's churn

_fenceable_lock = threading.Lock()
_fenceable: dict = {}  # abspath -> True, insertion-ordered


def record_fenceable_roots(roots) -> None:
    """Record output roots that were ABSENT when their job/batch
    started executing here (called by the batch scheduler and the
    serve job path before any write lands)."""
    with _fenceable_lock:
        for root in roots:
            path = os.path.abspath(root)
            _fenceable.pop(path, None)
            _fenceable[path] = True
        while len(_fenceable) > _FENCEABLE_MAX:
            del _fenceable[next(iter(_fenceable))]


def is_fenceable_root(path: str) -> bool:
    """Whether the fence op may reset ``path`` (see above)."""
    with _fenceable_lock:
        return os.path.abspath(path) in _fenceable


#: bounded deterministic retry for exceptions that escape a job's own
#: error handling (``OPERATOR_FORGE_JOB_RETRIES``) — a job that *fails*
#: (nonzero rc) is a result and is never retried; a job that *raises*
#: is plausibly transient (injected faults, I/O hiccups) and gets
#: re-run on fresh capture buffers before being reported as rc 1
DEFAULT_JOB_RETRIES = 2


def job_retries() -> int:
    return env_number(
        "OPERATOR_FORGE_JOB_RETRIES", DEFAULT_JOB_RETRIES, cast=int
    )


class _ThreadRouter(io.TextIOBase):
    """A stdout/stderr stand-in that routes writes to the calling
    thread's capture buffer, falling back to the real stream.
    ``contextlib.redirect_stdout`` swaps the *process-wide*
    ``sys.stdout``, so concurrent group threads would capture each
    other's output; this keeps captures per-thread."""

    def __init__(self, fallback):
        self.fallback = fallback
        self.local = threading.local()

    def _target(self):
        return getattr(self.local, "target", None) or self.fallback

    def write(self, s) -> int:
        return self._target().write(s)

    def flush(self) -> None:
        self._target().flush()

    def writable(self) -> bool:
        return True


_capture_lock = threading.Lock()
_capture_depth = 0
_router_out = None
_router_err = None


def _new_capture_lock_after_fork() -> None:
    # fork (the perf.workers process pool) can land while a parent
    # thread holds the capture lock; the child would inherit it locked
    # and deadlock installing its own capture
    global _capture_lock
    _capture_lock = threading.Lock()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_new_capture_lock_after_fork)


@contextlib.contextmanager
def _captured():
    """Capture this thread's stdout/stderr into fresh buffers.  The
    first active capture installs the routers; the last restores the
    original streams, so the process looks untouched outside a batch."""
    global _capture_depth, _router_out, _router_err
    with _capture_lock:
        if _capture_depth == 0:
            _router_out = _ThreadRouter(sys.stdout)
            _router_err = _ThreadRouter(sys.stderr)
            sys.stdout, sys.stderr = _router_out, _router_err
        _capture_depth += 1
        router_out, router_err = _router_out, _router_err
    out_buf, err_buf = io.StringIO(), io.StringIO()
    router_out.local.target = out_buf
    router_err.local.target = err_buf
    try:
        yield out_buf, err_buf
    finally:
        router_out.local.target = None
        router_err.local.target = None
        with _capture_lock:
            _capture_depth -= 1
            if _capture_depth == 0:
                sys.stdout = router_out.fallback
                sys.stderr = router_err.fallback


def _dep_roots(job: Job) -> tuple:
    return job.reads()


def _out_root(job: Job):
    writes = job.writes()
    return writes[0] if writes else None


def _tree_state(root: str) -> tuple:
    from ..gocheck.cache import tree_state

    if not os.path.isdir(root):
        return ("<missing>",)
    return tree_state(root)


def _job_key(job: Job, pre_deps: tuple, pre_out: tuple) -> str:
    from ..gocheck import compiler

    return pf_cache.hash_parts(
        _SCHEMA, __version__, _STAGE, tuple(job.argv()),
        compiler.mode() if job.command == "test" else "",
        pre_deps, pre_out,
    )


def run_job(job: Job) -> JobResult:
    """Run (or replay) one job; never raises — failures come back as a
    nonzero-rc :class:`~operator_forge.serve.jobs.JobResult`."""
    from ..cli.main import main as cli_main

    cache = pf_cache.get_cache()
    stage = _job_stage(job)
    _record_scope(_scope_label((job.target(),)))
    key = None
    pre_out: tuple = ()
    if cache.mode() != "off":
        with spans.span("serve.state"):
            pre_deps = tuple(
                (root, _tree_state(root)) for root in _dep_roots(job)
            )
            out_root = _out_root(job)
            pre_out = _tree_state(out_root) if out_root else ()
            key = _job_key(job, pre_deps, pre_out)
        hit = cache.get(stage, key)
        if hit is not pf_cache.MISS:
            rc, stdout, stderr = hit
            metrics.counter("serve.jobs_replayed").inc()
            metrics.histogram("serve.job.seconds").observe(0.0)
            # a replayed request still served a tenant: SLO latency is
            # what the client experienced, cache hit or not
            metrics.observe_slo(_scope_label((job.target(),)), 0.0)
            return JobResult(
                id=job.id, command=job.command, rc=rc, stdout=stdout,
                stderr=stderr, seconds=0.0, cached=True, index=job.index,
            )

    started = time.perf_counter()
    retries = job_retries()
    attempt = 0
    while True:
        # fresh capture buffers per attempt: a retried job's output
        # must be byte-identical to a first-try success, with no
        # residue from the failed attempt
        with spans.span(
            f"serve.job:{job.command}", args={"job": job.id}
        ), _captured() as (
            out_buf, err_buf
        ):
            try:
                if faults.should_fire("job.fail", "serve.job"):
                    raise RuntimeError(
                        "injected fault: job.fail@serve.job"
                    )
                rc = cli_main(job.argv())
                break
            except SystemExit as exc:  # argparse rejection of a bad spec
                code = exc.code
                rc = code if isinstance(code, int) else (
                    0 if code is None else 1
                )
                break
            except Exception as exc:
                # one job must never take down a batch — and an escaped
                # exception (unlike a nonzero rc) is plausibly
                # transient, so it earns a bounded deterministic retry.
                # TimeoutError is the exception to that: it is the
                # workers layer's verdict that a task hangs on every
                # attempt (its own retry/respawn/quarantine budget is
                # already spent proving it), so re-running the whole
                # job would multiply the full deadline wait and leak
                # more abandoned daemon threads for the same outcome
                if attempt < retries and not isinstance(
                    exc, TimeoutError
                ):
                    attempt += 1
                    metrics.counter("serve.job.retries").inc()
                    time.sleep(0.01 * attempt)  # deterministic backoff
                    continue
                if isinstance(exc, TimeoutError):
                    # the workers layer's verdict: this job blew its
                    # task deadline on every attempt — an SLO deadline
                    # miss charged to the tenant it was serving
                    metrics.count_deadline_miss(
                        _scope_label((job.target(),))
                    )
                err_buf.write(f"internal error: {exc}\n")
                rc = 1
                break
    result = JobResult(
        id=job.id, command=job.command, rc=rc,
        stdout=out_buf.getvalue(), stderr=err_buf.getvalue(),
        seconds=time.perf_counter() - started, index=job.index,
    )
    metrics.counter("serve.jobs_executed").inc()
    metrics.histogram("serve.job.seconds").observe(result.seconds)
    metrics.observe_slo(_scope_label((job.target(),)), result.seconds)
    if key is not None and rc == 0:
        out_root = _out_root(job)
        post_out = _tree_state(out_root) if out_root else ()
        if post_out == pre_out:
            # fixed point: replaying (skipping) this job later is
            # indistinguishable from re-running it on the same bytes
            cache.put(stage, key, (rc, result.stdout, result.stderr))
    return result


_GROUP_STAGE = "serve.group"


def _group_roots(group) -> tuple:
    """(input roots, written roots) of a whole group; a vet/test path
    lands among the inputs, a generated dir among the outputs (both,
    when a chain vets its own output — the duplicate snapshot is
    harmless)."""
    dep_roots: list = []
    out_roots: list = []
    for job in group:
        for root in _dep_roots(job):
            if root not in dep_roots:
                dep_roots.append(root)
        out_root = _out_root(job)
        if out_root is not None and out_root not in out_roots:
            out_roots.append(out_root)
    return tuple(dep_roots), tuple(out_roots)


def run_group(group) -> list:
    """Run one scheduling group (jobs over one directory, in manifest
    order), replaying the whole chain when nothing it reads or writes
    has changed since a recorded fixed-point run."""
    cache = pf_cache.get_cache()
    group_stage = _GROUP_STAGE
    if _project_scoped[0]:
        group_stage = (
            f"{_GROUP_STAGE}."
            f"{_scope_label({job.target() for job in group})}"
        )
    key = None
    pre_out: tuple = ()
    if len(group) > 1 and cache.mode() != "off":
        from ..gocheck import compiler

        dep_roots, out_roots = _group_roots(group)
        with spans.span("serve.state"):
            pre_deps = tuple(
                (root, _tree_state(root)) for root in dep_roots
            )
            pre_out = tuple(
                (root, _tree_state(root)) for root in out_roots
            )
            key = pf_cache.hash_parts(
                _SCHEMA, __version__, _GROUP_STAGE,
                tuple(tuple(job.argv()) for job in group),
                compiler.mode()
                if any(job.command == "test" for job in group) else "",
                pre_deps, pre_out,
            )
        hit = cache.get(group_stage, key)
        if hit is not pf_cache.MISS:
            metrics.counter("serve.jobs_replayed").inc(len(group))
            for job in group:
                metrics.histogram("serve.job.seconds").observe(0.0)
                metrics.observe_slo(_scope_label((job.target(),)), 0.0)
            return [
                JobResult(
                    id=job.id, command=job.command, rc=rc,
                    stdout=stdout, stderr=stderr, seconds=0.0,
                    cached=True, index=job.index,
                )
                for job, (rc, stdout, stderr) in zip(group, hit)
            ]

    results = [run_job(job) for job in group]

    if key is not None and all(result.rc == 0 for result in results):
        _, out_roots = _group_roots(group)
        post_out = tuple(
            (root, _tree_state(root)) for root in out_roots
        )
        if post_out == pre_out:
            # the chain is at its collective fixed point (e.g. init
            # restored the minimal main.go and create-api re-completed
            # it): skipping the whole group later reproduces this state
            cache.put(
                group_stage, key,
                [(r.rc, r.stdout, r.stderr) for r in results],
            )
    return results
