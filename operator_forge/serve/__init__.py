"""Batch orchestration and persistent serving (PR 3 tentpole).

operator-forge was strictly one-shot: every ``init`` / ``create api`` /
``vet`` / ``test`` invocation paid interpreter startup, re-primed the
content-addressed caches from zero (or disk), and ran one project at a
time on a GIL-bound thread pool.  This package amortizes the warmth PR 1
(generation cache) and PR 2 (gocheck fast path) built across *many*
requests and *many* cores:

- :mod:`operator_forge.serve.jobs` — the job model: a manifest of N
  init/create-api/vet/lint/test requests over distinct output
  directories,
  normalized to CLI argv vectors with deterministic ids;
- :mod:`operator_forge.serve.runner` — executes one job in-process with
  file-hash dirty-tracking through the shared
  :class:`~operator_forge.perf.cache.ContentCache`: a job whose input
  tree and output tree are unchanged replays its recorded result
  without recomputing;
- :mod:`operator_forge.serve.batch` — the orchestrator: groups jobs by
  the directory they touch (chains like init → create-api → vet → test
  over one project stay ordered), fans groups out through the
  ``OPERATOR_FORGE_WORKERS=thread|process`` backend
  (:mod:`operator_forge.perf.workers`), and reports results in
  deterministic input order;
- :mod:`operator_forge.serve.server` — ``operator-forge serve``: a
  resident process reading JSON-lines requests from stdin, answering
  one JSON line per request, with per-request spans feeding the
  profiler and bench.py's ``batch`` section;
- :mod:`operator_forge.serve.daemon` /
  :mod:`operator_forge.serve.session` — ``operator-forge daemon``
  (PR 10): the same protocol served to N concurrent socket clients
  through a round-robin fair scheduler with bounded admission queues,
  cross-session path locks, per-project cache namespaces, and the one
  shared SIGTERM drain; ``connect`` and ``batch --addr`` are the
  client side.

Serial, thread-parallel, process-pool, and multi-client daemon
execution produce byte-identical output trees in every cache mode
(tests/test_serve_batch.py, tests/test_daemon.py; bench.py's
``batch.identity_by_cache_mode`` + ``daemon`` guards, enforced by
scripts/commit-check.sh).
"""
