"""The `create api` scaffolder: APIs, controllers, resources, hooks,
config, samples, and main.go wiring for every workload in a config tree.

Reference: internal/plugins/workload/v1/scaffolds/api.go:64-282
(scaffoldWorkload recursing over collection components).
"""

from __future__ import annotations

import os

from ..perf import spans
from ..workload.config import Processor
from .context import ProjectConfig, WorkloadView, views_for
from .machinery import FileSpec, Fragment, Scaffold
from .templates import admission as admission_tpl
from .templates import api as api_tpl
from .templates import companion_cli as cli_tpl
from .templates import controller as controller_tpl
from .templates import e2e as e2e_tpl
from .templates import kustomize as kustomize_tpl
from .templates import resources as resources_tpl
from .templates import webhook as webhook_tpl


def api_files(
    views: list[WorkloadView],
    output_dir: str = "",
    with_resources: bool = True,
    with_controllers: bool = True,
    enable_conversion: bool = False,
) -> list[FileSpec]:
    """Build the create-api file set.  ``with_resources`` /
    ``with_controllers`` mirror the reference's ``--resource`` /
    ``--controller`` kubebuilder flags (docs/api-updates-upgrades.md:19-29:
    API-only regeneration uses ``--controller=false --resource``)."""
    specs: list[FileSpec] = []
    groups_done: set[str] = set()
    group_versions_done: set[tuple[str, str]] = set()

    for view in views:
        if with_resources:
            if (view.group, view.version) not in group_versions_done:
                group_versions_done.add((view.group, view.version))
                specs.append(api_tpl.group_version_info(view))

            specs.append(api_tpl.types_file(view))
            specs.append(api_tpl.deepcopy_file(view))
            specs.extend(api_tpl.kind_registry_files(view))

            specs.append(resources_tpl.resources_file(view))
            specs.extend(resources_tpl.definition_files(view))
            specs.append(resources_tpl.mutate_hook(view))
            specs.append(resources_tpl.dependencies_hook(view))

            specs.append(
                api_tpl.crd_yaml(view, output_dir, conversion=enable_conversion)
            )
            specs.append(api_tpl.sample_file(view))
            if enable_conversion:
                specs.extend(webhook_tpl.conversion_files(view, output_dir))

        if with_controllers:
            specs.append(controller_tpl.controller_file(view))
            specs.append(controller_tpl.reconcile_test_file(view))
            if view.group not in groups_done:
                groups_done.add(view.group)
                specs.append(
                    controller_tpl.suite_test_file(
                        view, [v.kind for v in views if v.group == view.group]
                    )
                )

    if with_resources:
        specs.append(kustomize_tpl.crd_kustomization(views))
        specs.append(kustomize_tpl.samples_kustomization(views))
        specs.append(kustomize_tpl.manager_cluster_role(views))
        if views:
            specs.extend(cli_tpl.cli_files(views, views[0].config))
            specs.extend(e2e_tpl.e2e_files(views, views[0].config))
    return specs


def main_go_fragments(
    views: list[WorkloadView],
    with_resources: bool = True,
    with_controllers: bool = True,
) -> list[Fragment]:
    """Wire each workload's scheme and reconciler into main.go
    (reference MainUpdater, scaffolds/api.go:149-156)."""
    fragments: list[Fragment] = []
    seen_apis: set[str] = set()
    seen_controllers: set[str] = set()

    for view in views:
        api_alias = view.api_import_alias
        if with_resources and api_alias not in seen_apis:
            seen_apis.add(api_alias)
            fragments.append(
                Fragment(
                    path="main.go",
                    marker="imports",
                    code=f'{api_alias} "{view.api_types_import}"',
                )
            )
            fragments.append(
                Fragment(
                    path="main.go",
                    marker="scheme",
                    code=f"utilruntime.Must({api_alias}.AddToScheme(scheme))",
                )
            )

        if not with_controllers:
            continue

        controllers_alias = f"{view.group}controllers"
        if controllers_alias not in seen_controllers:
            seen_controllers.add(controllers_alias)
            fragments.append(
                Fragment(
                    path="main.go",
                    marker="imports",
                    code=(
                        f'{controllers_alias} '
                        f'"{view.config.repo}/controllers/{view.group}"'
                    ),
                )
            )

        fragments.append(
            Fragment(
                path="main.go",
                marker="reconcilers",
                code=(
                    f"if err := {controllers_alias}.New{view.kind}Reconciler"
                    f"(mgr).SetupWithManager(mgr); err != nil {{\n"
                    f'\tsetupLog.Error(err, "unable to create controller", '
                    f'"controller", "{view.kind}")\n'
                    f"\tos.Exit(1)\n"
                    f"}}\n"
                ),
            )
        )
    return fragments


def api_plan(
    views: list[WorkloadView],
    output_dir: str = "",
    with_resources: bool = True,
    with_controllers: bool = True,
    enable_conversion: bool = False,
) -> tuple[list[FileSpec], list[Fragment]]:
    """Render the create-api file plan (specs + main.go/kind-registry
    fragments).  For the plain path — no conversion, no admission — this
    is the complete effect of ``create api`` and therefore the unit the
    content-addressed pipeline cache persists and replays."""
    fragments = main_go_fragments(views, with_resources, with_controllers)
    if with_resources:
        for view in views:
            fragments.extend(api_tpl.kind_registry_fragments(view))
    with spans.span("render"):
        specs = api_files(
            views, output_dir, with_resources, with_controllers,
            enable_conversion,
        )
    return specs, fragments


def scaffold_api(
    output_dir: str,
    processor: Processor,
    config: ProjectConfig,
    boilerplate_text: str = "",
    with_resources: bool = True,
    with_controllers: bool = True,
    enable_conversion: bool = False,
    dry_run: bool = False,
) -> Scaffold:
    views = views_for(processor.get_workloads(), config)
    scaffold = Scaffold(
        output_dir=output_dir, boilerplate=boilerplate_text, dry_run=dry_run
    )
    specs, fragments = api_plan(
        views, output_dir, with_resources, with_controllers, enable_conversion
    )

    # admission webhooks recorded in PROJECT: keep their manifests and
    # wiring in sync on every re-scaffold
    admission = (
        config.webhook_defaulting or config.webhook_validation
    ) and with_resources

    multi_version = []
    if enable_conversion and with_resources:
        # infra is only scaffolded once a kind actually has 2+ versions
        multi_version = [
            v for v in views if webhook_tpl.other_versions(v, output_dir)
        ]
        if multi_version:
            specs.extend(
                spec for spec in webhook_tpl.webhook_config_tree(config)
                # with admission on, _admission_specs supplies the
                # webhook kustomization (manifests + service)
                if not admission
                or spec.path != "config/webhook/kustomization.yaml"
            )
            for view in multi_version:
                hub = webhook_tpl.hub_version(view, output_dir)
                # when admission webhooks are on, SetupWebhookWithManager
                # already routes the CURRENT version's type through
                # NewWebhookManagedBy (serving /convert too); registering
                # the same type again would panic the webhook server on
                # a duplicate path at manager startup — and a conversion
                # fragment left behind by an earlier non-admission
                # scaffold is equally stale, so strip it
                if admission and hub == view.version:
                    if _strip_conversion_registration(
                        output_dir, view, hub, dry_run=dry_run
                    ):
                        scaffold.changes.append(("fragment", "main.go"))
                    continue
                fragments.append(
                    webhook_tpl.main_go_webhook_fragment(view, hub)
                )
    if admission:
        specs.extend(
            _admission_specs(views, config, include_tree=not multi_version)
        )
        for view in views:
            fragments.extend(
                admission_tpl.main_go_admission_fragments(view)
            )

    scaffold.execute(specs, fragments)
    if multi_version or admission:
        changed = webhook_tpl.update_default_kustomization(
            output_dir, dry_run=dry_run
        )
        if dry_run and changed:
            scaffold.changes.append(
                ("fragment", "config/default/kustomization.yaml")
            )
    return scaffold


def _strip_conversion_registration(
    output_dir: str,
    view: WorkloadView,
    hub: str,
    dry_run: bool = False,
) -> bool:
    """Remove the stale ``NewWebhookManagedBy(...).For(&hub.Kind{})``
    block from main.go (emitted by the conversion path before admission
    webhooks existed for the kind).  Returns True when a block was
    removed (or would be, under dry_run)."""
    main_path = os.path.join(output_dir, "main.go")
    if not os.path.isfile(main_path):
        return False
    with open(main_path, encoding="utf-8") as fh:
        lines = fh.readlines()
    anchor = (
        f"ctrl.NewWebhookManagedBy(mgr).For(&{view.group}{hub}"
        f".{view.kind}{{}}).Complete()"
    )
    start = next(
        (i for i, line in enumerate(lines) if anchor in line), None
    )
    if start is None:
        return False
    # the fragment is a brace-balanced if-block: drop through its close
    depth = 0
    end = start
    for i in range(start, len(lines)):
        depth += lines[i].count("{") - lines[i].count("}")
        if depth <= 0:
            end = i
            break
    if not dry_run:
        del lines[start:end + 1]
        with open(main_path, "w", encoding="utf-8") as fh:
            fh.writelines(lines)
    return True


def _admission_specs(
    views: list[WorkloadView],
    config: ProjectConfig,
    include_tree: bool = True,
    force: bool = False,
) -> list[FileSpec]:
    # the shared tree, minus its conversion-only webhook kustomization —
    # the admission variant below replaces it, and emitting both would
    # double-write the file and contradict the dry-run report.  The
    # caller passes include_tree=False when the conversion path already
    # emitted the tree this run.
    specs: list[FileSpec] = []
    if include_tree:
        specs.extend(
            spec for spec in webhook_tpl.webhook_config_tree(config)
            if spec.path != "config/webhook/kustomization.yaml"
        )
    for view in views:
        specs.append(
            admission_tpl.webhook_stub_file(
                view, config.webhook_defaulting,
                config.webhook_validation, force=force,
            )
        )
    specs.append(
        admission_tpl.webhook_manifests_file(
            config, views, config.webhook_defaulting,
            config.webhook_validation,
        )
    )
    specs.append(admission_tpl.webhook_kustomization_file())
    return specs


def scaffold_webhook(
    output_dir: str,
    processor: Processor,
    config: ProjectConfig,
    boilerplate_text: str = "",
    dry_run: bool = False,
    force: bool = False,
) -> Scaffold:
    """The `create webhook` scaffolder: admission stubs, registration
    objects, cert-manager wiring, and main.go registration for every
    workload kind.  ``config.webhook_defaulting`` / ``webhook_validation``
    select the interfaces scaffolded; ``force`` regenerates user-owned
    stubs instead of preserving them (kubebuilder --force)."""
    views = views_for(processor.get_workloads(), config)
    scaffold = Scaffold(
        output_dir=output_dir, boilerplate=boilerplate_text, dry_run=dry_run
    )
    specs = _admission_specs(views, config, force=force)
    fragments: list[Fragment] = []
    for view in views:
        fragments.extend(admission_tpl.main_go_admission_fragments(view))
        # a project previously scaffolded with --enable-conversion
        # registered the hub type through NewWebhookManagedBy; the
        # SetupWebhookWithManager registration added here serves
        # /convert for that type too, so the old fragment is stale —
        # strip it rather than rely on the builder's path dedup
        hub = webhook_tpl.hub_version(view, output_dir)
        if hub == view.version and _strip_conversion_registration(
            output_dir, view, hub, dry_run=dry_run
        ):
            scaffold.changes.append(("fragment", "main.go"))
    scaffold.execute(specs, fragments)
    changed = webhook_tpl.update_default_kustomization(
        output_dir, dry_run=dry_run
    )
    if dry_run and changed:
        scaffold.changes.append(
            ("fragment", "config/default/kustomization.yaml")
        )
    return scaffold
