"""Scaffolding engine and generated-project templates.

Reference: internal/plugins/workload/v1/scaffolds (+ kubebuilder's
``machinery`` package which the reference builds on).  This package provides:

- :mod:`machinery`: file specs, if-exists policies (overwrite / skip /
  error), marker-based fragment insertion for idempotent re-scaffolding;
- :mod:`context`: the scaffold-time view of a workload (naming, paths,
  GVK, imports);
- :mod:`project`: the ``init`` scaffolder (project skeleton);
- :mod:`api`: the ``create api`` scaffolder (APIs, controllers, resources,
  companion CLI, samples, tests);
- :mod:`templates/`: the generated-code bodies.
"""

from .machinery import (  # noqa: F401
    FileSpec,
    Fragment,
    IfExists,
    Scaffold,
    ScaffoldError,
)
