"""The `init` scaffolder: project skeleton.

Reference: internal/plugins/workload/v1/scaffolds/init.go:33-90 (plus the
kubebuilder golang/kustomize plugin output the reference's plugin bundle
produces before it runs).
"""

from __future__ import annotations

from ..perf import spans
from .context import ProjectConfig
from .machinery import FileSpec, Scaffold
from .templates import kustomize, orchestrate, project


def init_files(
    config: ProjectConfig, workload_names: list[str]
) -> list[FileSpec]:
    specs = [
        project.project_file(config),
        project.boilerplate(),
        project.gitignore(),
        project.dockerignore(),
        project.go_mod(config),
        project.main_go(config),
        project.dockerfile(),
        project.makefile(config),
        project.readme(config, workload_names),
    ]
    specs.extend(orchestrate.orchestrate_files(config.repo))
    specs.extend(kustomize.default_tree(config))
    specs.extend(kustomize.prometheus_tree())
    return specs


def scaffold_init(
    output_dir: str,
    config: ProjectConfig,
    workload_names: list[str],
    boilerplate_text: str = "",
) -> Scaffold:
    scaffold = Scaffold(output_dir=output_dir, boilerplate=boilerplate_text)
    with spans.span("render"):
        specs = init_files(config, workload_names)
    scaffold.execute(specs)
    return scaffold
