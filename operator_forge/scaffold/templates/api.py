"""API templates: group/version info, kind types, kind registry files,
deepcopy implementations, CRD YAML, and CR samples.

Reference: internal/plugins/workload/v1/scaffolds/templates/api/{types,group,
kind}.go and config/samples/crd_sample.go.  Two deliberate additions over the
reference: deepcopy code and CRD YAML are generated directly (the reference
defers both to controller-gen via ``make manifests``/``make generate``), so a
generated project is complete before any tooling runs.
"""

from __future__ import annotations

from ...utils import to_file_name
from ...workload.api_fields import APIFields
from ...workload.fieldmarkers import FieldType
from ..context import WorkloadView
from ..machinery import FileSpec, Fragment, IfExists
from ..render import compiled_render


@compiled_render("api.group_version_info")
def group_version_info(view: WorkloadView) -> FileSpec:
    content = f'''// Package {view.version} contains API Schema definitions for the {view.group}
// {view.version} API group.
// +kubebuilder:object:generate=true
// +groupName={view.full_group}
package {view.version}

import (
\t"k8s.io/apimachinery/pkg/runtime/schema"
\t"sigs.k8s.io/controller-runtime/pkg/scheme"
)

var (
\t// GroupVersion is group version used to register these objects.
\tGroupVersion = schema.GroupVersion{{Group: "{view.full_group}", Version: "{view.version}"}}

\t// SchemeBuilder is used to add go types to the GroupVersionKind scheme.
\tSchemeBuilder = &scheme.Builder{{GroupVersion: GroupVersion}}

\t// AddToScheme adds the types in this group-version to the given scheme.
\tAddToScheme = SchemeBuilder.AddToScheme
)
'''
    return FileSpec(
        path=f"{view.api_types_dir}/groupversion_info.go", content=content
    )


def _dependency_imports(view: WorkloadView) -> list[str]:
    imports = []
    seen = set()
    for dep in view.workload.get_dependencies():
        if dep.api_group == view.group:
            continue
        alias = f"{dep.api_group}{dep.api_version}"
        if alias in seen:
            continue
        seen.add(alias)
        imports.append(
            f'\t{alias} "{view.config.repo}/apis/{dep.api_group}/{dep.api_version}"'
        )
    return imports


def _dependency_entries(view: WorkloadView) -> list[str]:
    entries = []
    for dep in view.workload.get_dependencies():
        if dep.api_group == view.group:
            entries.append(f"\t\t&{dep.api_kind}{{}},")
        else:
            entries.append(
                f"\t\t&{dep.api_group}{dep.api_version}.{dep.api_kind}{{}},"
            )
    return entries


@compiled_render("api.types_file")
def types_file(view: WorkloadView) -> FileSpec:
    """The <kind>_types.go file (reference templates/api/types.go:50-196)."""
    kind = view.kind
    spec_fields = view.workload.get_api_spec_fields() or APIFields.new_spec_root()
    spec_code = spec_fields.generate_api_spec(kind)

    dep_imports = "\n".join(_dependency_imports(view))
    if dep_imports:
        dep_imports = "\n" + dep_imports
    dep_entries = "\n".join(_dependency_entries(view))
    if dep_entries:
        dep_entries = "\n" + dep_entries + "\n\t"

    cluster_scope_marker = (
        "\n// +kubebuilder:resource:scope=Cluster"
        if view.workload.is_cluster_scoped()
        else ""
    )

    content = f'''package {view.version}

import (
\t"errors"

\tmetav1 "k8s.io/apimachinery/pkg/apis/meta/v1"
\t"k8s.io/apimachinery/pkg/runtime/schema"

\t"{view.config.repo}/pkg/orchestrate"{dep_imports}
)

// ErrUnableToConvert{kind} is returned when an object cannot be converted
// to a *{kind}.
var ErrUnableToConvert{kind} = errors.New("unable to convert to {kind}")

// EDIT THIS FILE!  THIS IS SCAFFOLDING FOR YOU TO OWN!
// NOTE: json tags are required.  Any new fields you add must have json tags
// for the fields to be serialized.
{spec_code}
// {kind}Status defines the observed state of {kind}.
type {kind}Status struct {{
\t// INSERT ADDITIONAL STATUS FIELD - define observed state of cluster
\t// Important: Run "make" to regenerate code after modifying this file

\tCreated               bool                                   `json:"created,omitempty"`
\tDependenciesSatisfied bool                                   `json:"dependenciesSatisfied,omitempty"`
\tConditions            []*orchestrate.PhaseCondition          `json:"conditions,omitempty"`
\tResources             []*orchestrate.ChildResourceCondition  `json:"resources,omitempty"`
}}

// +kubebuilder:object:root=true
// +kubebuilder:subresource:status{cluster_scope_marker}

// {kind} is the Schema for the {view.plural} API.
type {kind} struct {{
\tmetav1.TypeMeta   `json:",inline"`
\tmetav1.ObjectMeta `json:"metadata,omitempty"`
\tSpec   {kind}Spec   `json:"spec,omitempty"`
\tStatus {kind}Status `json:"status,omitempty"`
}}

// +kubebuilder:object:root=true

// {kind}List contains a list of {kind}.
type {kind}List struct {{
\tmetav1.TypeMeta `json:",inline"`
\tmetav1.ListMeta `json:"metadata,omitempty"`
\tItems           []{kind} `json:"items"`
}}

//
// orchestrate.Workload interface methods
//

// GetCreatedStatus returns whether the workload has been reconciled.
func (workload *{kind}) GetCreatedStatus() bool {{
\treturn workload.Status.Created
}}

// SetCreatedStatus records whether the workload has been reconciled.
func (workload *{kind}) SetCreatedStatus(created bool) {{
\tworkload.Status.Created = created
}}

// GetDependencyStatus returns the dependency satisfaction status.
func (workload *{kind}) GetDependencyStatus() bool {{
\treturn workload.Status.DependenciesSatisfied
}}

// SetDependencyStatus records the dependency satisfaction status.
func (workload *{kind}) SetDependencyStatus(satisfied bool) {{
\tworkload.Status.DependenciesSatisfied = satisfied
}}

// GetPhaseConditions returns the phase conditions of the workload.
func (workload *{kind}) GetPhaseConditions() []*orchestrate.PhaseCondition {{
\treturn workload.Status.Conditions
}}

// SetPhaseCondition records a phase condition, replacing any prior condition
// for the same phase.
func (workload *{kind}) SetPhaseCondition(condition *orchestrate.PhaseCondition) {{
\tfor i, current := range workload.Status.Conditions {{
\t\tif current.Phase == condition.Phase {{
\t\t\tworkload.Status.Conditions[i] = condition

\t\t\treturn
\t\t}}
\t}}

\tworkload.Status.Conditions = append(workload.Status.Conditions, condition)
}}

// GetChildResourceConditions returns the child resource conditions.
func (workload *{kind}) GetChildResourceConditions() []*orchestrate.ChildResourceCondition {{
\treturn workload.Status.Resources
}}

// SetChildResourceCondition records a child resource condition, replacing any
// prior condition for the same resource.
func (workload *{kind}) SetChildResourceCondition(resource *orchestrate.ChildResourceCondition) {{
\tfor i, current := range workload.Status.Resources {{
\t\tif current.Group == resource.Group && current.Version == resource.Version &&
\t\t\tcurrent.Kind == resource.Kind &&
\t\t\tcurrent.Name == resource.Name && current.Namespace == resource.Namespace {{
\t\t\tworkload.Status.Resources[i] = resource

\t\t\treturn
\t\t}}
\t}}

\tworkload.Status.Resources = append(workload.Status.Resources, resource)
}}

// GetDependencyWorkloads returns the workloads this workload depends upon.
func (*{kind}) GetDependencyWorkloads() []orchestrate.Workload {{
\treturn []orchestrate.Workload{{{dep_entries}}}
}}

// GetWorkloadGVK returns the GVK for this workload type.
func (*{kind}) GetWorkloadGVK() schema.GroupVersionKind {{
\treturn GroupVersion.WithKind("{kind}")
}}

func init() {{
\tSchemeBuilder.Register(&{kind}{{}}, &{kind}List{{}})
}}
'''
    return FileSpec(path=view.types_file, content=content)


def _struct_names(kind: str, fields: APIFields) -> list[str]:
    """Collect the nested struct type names of a spec tree."""
    names = []

    def walk(node: APIFields):
        for child in node.children:
            if child.type == FieldType.STRUCT:
                names.append(kind + child.struct_name)
                walk(child)

    walk(fields)
    return names


@compiled_render("api.deepcopy_file")
def deepcopy_file(view: WorkloadView) -> FileSpec:
    """Generated deepcopy implementations for the kind and its nested spec
    structs (the reference defers this to controller-gen)."""
    kind = view.kind
    spec_fields = view.workload.get_api_spec_fields() or APIFields.new_spec_root()
    structs = _struct_names(kind, spec_fields)

    parts = [
        f'''//go:build !ignore_autogenerated

// Code generated by operator-forge. DO NOT EDIT.

package {view.version}

import (
\truntime "k8s.io/apimachinery/pkg/runtime"

\t"{view.config.repo}/pkg/orchestrate"
)
'''
    ]

    # nested spec structs hold only value types, so a shallow copy is a deep
    # copy
    for struct in [f"{kind}Spec"] + structs:
        parts.append(f'''
// DeepCopyInto copies the receiver into out.
func (in *{struct}) DeepCopyInto(out *{struct}) {{
\t*out = *in
}}

// DeepCopy returns a deep copy of the {struct}.
func (in *{struct}) DeepCopy() *{struct} {{
\tif in == nil {{
\t\treturn nil
\t}}

\tout := new({struct})
\tin.DeepCopyInto(out)

\treturn out
}}
''')

    parts.append(f'''
// DeepCopyInto copies the receiver into out.
func (in *{kind}Status) DeepCopyInto(out *{kind}Status) {{
\t*out = *in

\tif in.Conditions != nil {{
\t\tout.Conditions = make([]*orchestrate.PhaseCondition, len(in.Conditions))
\t\tfor i := range in.Conditions {{
\t\t\tout.Conditions[i] = in.Conditions[i].DeepCopy()
\t\t}}
\t}}

\tif in.Resources != nil {{
\t\tout.Resources = make([]*orchestrate.ChildResourceCondition, len(in.Resources))
\t\tfor i := range in.Resources {{
\t\t\tout.Resources[i] = in.Resources[i].DeepCopy()
\t\t}}
\t}}
}}

// DeepCopy returns a deep copy of the {kind}Status.
func (in *{kind}Status) DeepCopy() *{kind}Status {{
\tif in == nil {{
\t\treturn nil
\t}}

\tout := new({kind}Status)
\tin.DeepCopyInto(out)

\treturn out
}}

// DeepCopyInto copies the receiver into out.
func (in *{kind}) DeepCopyInto(out *{kind}) {{
\t*out = *in
\tout.TypeMeta = in.TypeMeta
\tin.ObjectMeta.DeepCopyInto(&out.ObjectMeta)
\tout.Spec = in.Spec
\tin.Status.DeepCopyInto(&out.Status)
}}

// DeepCopy returns a deep copy of the {kind}.
func (in *{kind}) DeepCopy() *{kind} {{
\tif in == nil {{
\t\treturn nil
\t}}

\tout := new({kind})
\tin.DeepCopyInto(out)

\treturn out
}}

// DeepCopyObject returns a deep copy as a runtime.Object.
func (in *{kind}) DeepCopyObject() runtime.Object {{
\treturn in.DeepCopy()
}}

// DeepCopyInto copies the receiver into out.
func (in *{kind}List) DeepCopyInto(out *{kind}List) {{
\t*out = *in
\tout.TypeMeta = in.TypeMeta
\tin.ListMeta.DeepCopyInto(&out.ListMeta)

\tif in.Items != nil {{
\t\tout.Items = make([]{kind}, len(in.Items))
\t\tfor i := range in.Items {{
\t\t\tin.Items[i].DeepCopyInto(&out.Items[i])
\t\t}}
\t}}
}}

// DeepCopy returns a deep copy of the {kind}List.
func (in *{kind}List) DeepCopy() *{kind}List {{
\tif in == nil {{
\t\treturn nil
\t}}

\tout := new({kind}List)
\tin.DeepCopyInto(out)

\treturn out
}}

// DeepCopyObject returns a deep copy as a runtime.Object.
func (in *{kind}List) DeepCopyObject() runtime.Object {{
\treturn in.DeepCopy()
}}
''')
    content = "".join(parts)
    return FileSpec(
        path=f"{view.api_types_dir}/zz_generated_deepcopy_"
        f"{to_file_name(view.kind_lower)}.go",
        content=content,
    )


@compiled_render("api.kind_registry_files")
def kind_registry_files(view: WorkloadView) -> list[FileSpec]:
    """apis/<group>/<kind>.go (+ _latest.go): version registry for a kind
    (reference templates/api/kind.go:34-188)."""
    kind = view.kind
    alias = view.api_import_alias
    kind_file = to_file_name(view.kind_lower)
    registry = f'''package {view.group}

import (
\t"sigs.k8s.io/controller-runtime/pkg/client"

\t{alias} "{view.api_types_import}"
\t// +operator-builder:scaffold:{view.kind_lower}:imports
)

// {kind}Objects returns one empty object for every known API version of
// {kind}, newest first.  New versions of this kind are registered here as
// they are scaffolded.
func {kind}Objects() []client.Object {{
\treturn []client.Object{{
\t\t&{alias}.{kind}{{}},
\t\t// +operator-builder:scaffold:{view.kind_lower}:versions
\t}}
}}
'''
    latest = f'''package {view.group}

import (
\t{alias} "{view.api_types_import}"
)

// {kind}Latest aliases the newest API version of {kind}.
type {kind}Latest = {alias}.{kind}

// {kind}LatestVersion is the newest API version of {kind}.
const {kind}LatestVersion = "{view.version}"
'''
    return [
        # the registry is created once, then grown through its scaffold
        # markers as new API versions are added (see kind_registry_fragments)
        FileSpec(
            path=f"apis/{view.group}/{kind_file}.go",
            content=registry,
            if_exists=IfExists.SKIP,
        ),
        FileSpec(
            path=f"apis/{view.group}/{kind_file}_latest.go", content=latest
        ),
    ]


@compiled_render("api.kind_registry_fragments")
def kind_registry_fragments(view: WorkloadView) -> list[Fragment]:
    """Insert the current API version into an existing kind registry
    (reference templates/api/kind.go's Inserter markers
    ``operator-builder:imports`` / ``operator-builder:groupversions``)."""
    kind_file = to_file_name(view.kind_lower)
    path = f"apis/{view.group}/{kind_file}.go"
    alias = view.api_import_alias
    return [
        Fragment(
            path=path,
            marker=f"{view.kind_lower}:imports",
            code=f'{alias} "{view.api_types_import}"',
        ),
        Fragment(
            path=path,
            marker=f"{view.kind_lower}:versions",
            code=f"&{alias}.{view.kind}{{}},",
        ),
    ]


# -- CRD + sample YAML ----------------------------------------------------


def _schema_for(field: APIFields) -> dict:
    if field.type == FieldType.STRUCT:
        props = {
            child.manifest_name: _schema_for(child) for child in field.children
        }
        schema: dict = {"type": "object", "properties": props}
        # controller-gen semantics on the generated types: every field
        # carries `omitempty` (reference api.go:294) so nothing is
        # required unless explicitly marked +kubebuilder:validation:Required
        # (only the injected collection-ref name is, workload.go:150-212)
        required = [
            child.manifest_name
            for child in field.children
            if any("validation:Required" in m for m in child.markers)
        ]
        if required:
            schema["required"] = required
        return schema
    type_map = {
        FieldType.STRING: "string",
        FieldType.INT: "integer",
        FieldType.BOOL: "boolean",
    }
    schema: dict = {"type": type_map.get(field.type, "string")}
    if field.default_value is not None:
        schema["default"] = field.default_value
    if field.comments:
        schema["description"] = " ".join(field.comments)
    return schema


def _condition_schema() -> dict:
    return {
        "type": "array",
        "items": {
            "type": "object",
            "properties": {
                "phase": {"type": "string"},
                "state": {"type": "string"},
                "message": {"type": "string"},
            },
            "required": ["phase", "state"],
        },
    }


def _resource_condition_schema() -> dict:
    return {
        "type": "array",
        "items": {
            "type": "object",
            "properties": {
                "group": {"type": "string"},
                "version": {"type": "string"},
                "kind": {"type": "string"},
                "name": {"type": "string"},
                "namespace": {"type": "string"},
                "created": {"type": "boolean"},
                "message": {"type": "string"},
            },
            "required": ["group", "version", "kind", "name", "created"],
        },
    }


def _yaml_dump(data, indent: int = 0) -> str:
    """Small deterministic YAML renderer for CRD documents.  A pure
    function of the document dict, so the dump lowers once per content
    hash into the ``render.lower`` blob store (the YAML representer
    walk is one of the costliest pieces of a cold ``create api``)."""
    from operator_forge.utils import yamlcompat as pyyaml

    from ..render import lowered_blob

    return lowered_blob(
        "api.crd_yaml_dump",
        (data,),
        lambda: pyyaml.safe_dump(
            data, sort_keys=False, default_flow_style=False
        ),
    )


def _merge_crd_versions(view: WorkloadView, crd: dict, output_dir: str) -> dict:
    """Merge previously scaffolded API versions into a regenerated CRD.

    A multi-version kind must present every version in one CRD document.
    The current scaffold pass only knows the current config's version, so
    prior versions are carried over from the existing CRD file on disk with
    ``storage: false`` (the newest scaffolded version becomes the storage
    version).  The reference reaches the same end state via controller-gen
    reading all Go type versions."""
    import os
    import sys

    from operator_forge.utils import yamlcompat as pyyaml

    if not output_dir:
        return crd
    existing_path = os.path.join(
        output_dir, "config", "crd", "bases", view.crd_file_name
    )
    if not os.path.exists(existing_path):
        return crd
    def warn(reason: str) -> None:
        # never silently drop previously scaffolded versions: overwriting
        # with a single-version CRD would break clusters storing objects at
        # an older version; keep the unreadable file as a .bak so the
        # recovery instruction is actionable
        backup_note = ""
        try:
            import shutil

            shutil.copyfile(existing_path, existing_path + ".bak")
            backup_note = f"; original preserved at {existing_path}.bak"
        except OSError:
            pass
        print(
            f"warning: unable to read existing CRD {existing_path} "
            f"({reason}); keeping only the current API version "
            f"{view.version} — restore older versions manually if "
            f"needed{backup_note}",
            file=sys.stderr,
        )

    try:
        with open(existing_path, "r", encoding="utf-8") as handle:
            existing = pyyaml.safe_load(handle.read())
    except Exception as exc:
        warn(str(exc))
        return crd

    spec = existing.get("spec") if isinstance(existing, dict) else None
    old_versions = spec.get("versions") if isinstance(spec, dict) else None
    if not isinstance(old_versions, list):
        # valid YAML but not a CRD document (hand edit, conflict markers
        # that still parse as a scalar, ...)
        warn("file does not contain a CRD with spec.versions")
        return crd

    new_names = {v["name"] for v in crd["spec"]["versions"]}
    carried = []
    for version in old_versions:
        if not isinstance(version, dict) or version.get("name") in new_names:
            continue
        version = dict(version)
        version["storage"] = False
        carried.append(version)
    crd["spec"]["versions"] = carried + crd["spec"]["versions"]
    return crd


@compiled_render("api.crd_yaml", subset=False)
def crd_yaml(
    view: WorkloadView, output_dir: str = "", conversion: bool = False
) -> FileSpec:
    """config/crd/bases/<group>_<plural>.yaml rendered directly from the
    APIFields tree (the reference requires controller-gen for this).
    ``output_dir`` lets the renderer merge API versions already scaffolded
    on disk.  With ``conversion`` enabled, multi-version CRDs get a
    webhook conversion strategy + cert-manager CA injection (see
    templates/webhook.py)."""
    spec_fields = view.workload.get_api_spec_fields() or APIFields.new_spec_root()
    scope = "Cluster" if view.workload.is_cluster_scoped() else "Namespaced"
    crd = {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {
            "annotations": {
                "controller-gen.kubebuilder.io/version": "(operator-forge)"
            },
            "name": f"{view.plural}.{view.full_group}",
        },
        "spec": {
            "group": view.full_group,
            "names": {
                "kind": view.kind,
                "listKind": f"{view.kind}List",
                "plural": view.plural,
                "singular": view.kind_lower,
            },
            "scope": scope,
            "versions": [
                {
                    "name": view.version,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "description": f"{view.kind} is the Schema for the "
                            f"{view.plural} API.",
                            "properties": {
                                "apiVersion": {"type": "string"},
                                "kind": {"type": "string"},
                                "metadata": {"type": "object"},
                                "spec": _schema_for(spec_fields),
                                "status": {
                                    "type": "object",
                                    "properties": {
                                        "created": {"type": "boolean"},
                                        "dependenciesSatisfied": {
                                            "type": "boolean"
                                        },
                                        "conditions": _condition_schema(),
                                        "resources": (
                                            _resource_condition_schema()
                                        ),
                                    },
                                },
                            },
                        }
                    },
                }
            ],
        },
    }
    crd = _merge_crd_versions(view, crd, output_dir)
    if conversion and len(crd["spec"]["versions"]) > 1:
        from . import webhook as webhook_tpl

        crd["spec"]["conversion"] = webhook_tpl.crd_conversion_stanza(
            view.config
        )
        key, value = webhook_tpl.crd_ca_injection_annotation(view.config)
        crd["metadata"].setdefault("annotations", {})[key] = value
    return FileSpec(
        path=f"config/crd/bases/{view.crd_file_name}",
        content=_yaml_dump(crd),
        add_boilerplate=False,
    )


def sample_yaml(view: WorkloadView, required_only: bool = False) -> str:
    """A sample custom resource manifest
    (reference templates/config/samples/crd_sample.go:28-64)."""
    spec_fields = view.workload.get_api_spec_fields() or APIFields.new_spec_root()
    spec = spec_fields.generate_sample_spec(required_only)
    return (
        f"apiVersion: {view.full_group}/{view.version}\n"
        f"kind: {view.kind}\n"
        "metadata:\n"
        f"  name: {view.kind_lower}-sample\n"
        f"{spec}"
    )


@compiled_render("api.sample_file")
def sample_file(view: WorkloadView) -> FileSpec:
    return FileSpec(
        path=f"config/samples/{view.sample_file_name}",
        content=sample_yaml(view, required_only=False),
        add_boilerplate=False,
    )
