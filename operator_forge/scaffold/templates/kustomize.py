"""Kustomize config-tree templates.

Reference: the kustomize tree the reference inherits from kubebuilder's
kustomize plugin, plus its own CRD kustomization
(templates/config/crd/kustomization.go:25-116).
"""

from __future__ import annotations

from ..context import ProjectConfig, WorkloadView
from ..machinery import FileSpec, IfExists
from .project import leader_election_id
from ..render import compiled_render, lowered_blob


def _controller_manager_config(config: ProjectConfig) -> FileSpec:
    """The ControllerManagerConfig file mounted into --component-config
    deployments; must set the same probe/metrics addresses the flag-driven
    variant defaults to, since the Deployment probes target them."""
    return FileSpec(
        path="config/manager/controller_manager_config.yaml",
        content=f"""apiVersion: controller-runtime.sigs.k8s.io/v1alpha1
kind: ControllerManagerConfig
health:
  healthProbeBindAddress: :8081
metrics:
  bindAddress: :8080
webhook:
  port: 9443
leaderElection:
  leaderElect: true
  resourceName: {leader_election_id(config)}
""",
        add_boilerplate=False,
    )


@compiled_render("kustomize.crd_kustomization")
def crd_kustomization(views: list[WorkloadView]) -> FileSpec:
    resources = "\n".join(
        f"- bases/{view.crd_file_name}" for view in views
    )
    content = (
        "# This kustomization.yaml is not intended to be run by itself,\n"
        "# since it depends on service name and namespace that are out of\n"
        "# this kustomize package. It should be run by config/default.\n"
        f"resources:\n{resources}\n"
    )
    return FileSpec(
        path="config/crd/kustomization.yaml",
        content=content,
        add_boilerplate=False,
    )


@compiled_render("kustomize.samples_kustomization")
def samples_kustomization(views: list[WorkloadView]) -> FileSpec:
    resources = "\n".join(f"- {view.sample_file_name}" for view in views)
    content = f"## Sample custom resources\nresources:\n{resources}\n"
    return FileSpec(
        path="config/samples/kustomization.yaml",
        content=content,
        add_boilerplate=False,
    )


@compiled_render("kustomize.default_tree")
def default_tree(config: ProjectConfig) -> list[FileSpec]:
    project = config.project_name
    namespace = f"{project}-system"

    # --component-config projects read manager options from a mounted
    # ControllerManagerConfig file instead of flags (reference
    # templates/main.go:236-257); the deployment must agree with main.go on
    # which of the two is in use or the manager exits on an unknown flag
    if config.component_config:
        manager_args = "- --config=/controller_manager_config.yaml"
        manager_mounts = """
        volumeMounts:
        - name: manager-config
          mountPath: /controller_manager_config.yaml
          subPath: controller_manager_config.yaml"""
        manager_volumes = """
      volumes:
      - name: manager-config
        configMap:
          name: manager-config"""
        manager_kustomization_extra = """
generatorOptions:
  disableNameSuffixHash: true

configMapGenerator:
- name: manager-config
  files:
  - controller_manager_config.yaml
"""
        component_config_files = [_controller_manager_config(config)]
    else:
        manager_args = "- --leader-elect"
        manager_mounts = ""
        manager_volumes = ""
        manager_kustomization_extra = ""
        component_config_files = []

    return component_config_files + [
        FileSpec(
            path="config/default/kustomization.yaml",
            content=f"""# Adds namespace to all resources.
namespace: {namespace}

# Value of this field is prepended to the names of all resources.
namePrefix: {project}-

resources:
- ../crd
- ../rbac
- ../manager
# Uncomment to scrape controller metrics with the Prometheus operator:
#- ../prometheus
""",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/manager/kustomization.yaml",
            content=f"""resources:
- manager.yaml
- metrics_service.yaml
{manager_kustomization_extra}
images:
- name: controller
  newName: controller
  newTag: latest
""",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/manager/metrics_service.yaml",
            content="""apiVersion: v1
kind: Service
metadata:
  labels:
    control-plane: controller-manager
  name: controller-manager-metrics-service
  namespace: system
spec:
  ports:
  - name: http
    port: 8080
    protocol: TCP
    targetPort: 8080
  selector:
    control-plane: controller-manager
""",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/manager/manager.yaml",
            content=f"""apiVersion: v1
kind: Namespace
metadata:
  labels:
    control-plane: controller-manager
  name: system
---
apiVersion: apps/v1
kind: Deployment
metadata:
  name: controller-manager
  namespace: system
  labels:
    control-plane: controller-manager
spec:
  selector:
    matchLabels:
      control-plane: controller-manager
  replicas: 1
  template:
    metadata:
      labels:
        control-plane: controller-manager
    spec:
      securityContext:
        runAsNonRoot: true
      containers:
      - command:
        - /manager
        args:
        {manager_args}
        image: controller:latest
        name: manager
        securityContext:
          allowPrivilegeEscalation: false
          capabilities:
            drop:
            - "ALL"
        livenessProbe:
          httpGet:
            path: /healthz
            port: 8081
          initialDelaySeconds: 15
          periodSeconds: 20
        readinessProbe:
          httpGet:
            path: /readyz
            port: 8081
          initialDelaySeconds: 5
          periodSeconds: 10
        resources:
          limits:
            cpu: 500m
            memory: 256Mi
          requests:
            cpu: 10m
            memory: 64Mi{manager_mounts}
      serviceAccountName: controller-manager
      terminationGracePeriodSeconds: 10{manager_volumes}
""",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/rbac/kustomization.yaml",
            content="""resources:
- service_account.yaml
- role.yaml
- role_binding.yaml
- leader_election_role.yaml
- leader_election_role_binding.yaml
""",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/rbac/service_account.yaml",
            content="""apiVersion: v1
kind: ServiceAccount
metadata:
  name: controller-manager
  namespace: system
""",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/rbac/role_binding.yaml",
            content="""apiVersion: rbac.authorization.k8s.io/v1
kind: ClusterRoleBinding
metadata:
  name: manager-rolebinding
roleRef:
  apiGroup: rbac.authorization.k8s.io
  kind: ClusterRole
  name: manager-role
subjects:
- kind: ServiceAccount
  name: controller-manager
  namespace: system
""",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/rbac/leader_election_role.yaml",
            content="""apiVersion: rbac.authorization.k8s.io/v1
kind: Role
metadata:
  name: leader-election-role
rules:
- apiGroups:
  - ""
  resources:
  - configmaps
  verbs:
  - get
  - list
  - watch
  - create
  - update
  - patch
  - delete
- apiGroups:
  - coordination.k8s.io
  resources:
  - leases
  verbs:
  - get
  - list
  - watch
  - create
  - update
  - patch
  - delete
- apiGroups:
  - ""
  resources:
  - events
  verbs:
  - create
  - patch
""",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/rbac/leader_election_role_binding.yaml",
            content="""apiVersion: rbac.authorization.k8s.io/v1
kind: RoleBinding
metadata:
  name: leader-election-rolebinding
roleRef:
  apiGroup: rbac.authorization.k8s.io
  kind: Role
  name: leader-election-role
subjects:
- kind: ServiceAccount
  name: controller-manager
  namespace: system
""",
            add_boilerplate=False,
        ),
    ]


@compiled_render("kustomize.prometheus_tree")
def prometheus_tree() -> list[FileSpec]:
    """config/prometheus: an optional ServiceMonitor for the controller's
    metrics endpoint (the kubebuilder kustomize plugin ships the same tree;
    enable by uncommenting ``../prometheus`` in config/default)."""
    return [
        FileSpec(
            path="config/prometheus/kustomization.yaml",
            content="resources:\n- monitor.yaml\n",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/prometheus/monitor.yaml",
            content="""# Prometheus Monitor Service (Metrics)
apiVersion: monitoring.coreos.com/v1
kind: ServiceMonitor
metadata:
  labels:
    control-plane: controller-manager
  name: controller-manager-metrics-monitor
  namespace: system
spec:
  endpoints:
  - path: /metrics
    port: http
  selector:
    matchLabels:
      control-plane: controller-manager
""",
            add_boilerplate=False,
        ),
    ]


@compiled_render("kustomize.manager_cluster_role")
def manager_cluster_role(views: list[WorkloadView]) -> FileSpec:
    """config/rbac/role.yaml aggregated from every workload's inferred rules
    (the reference defers this to controller-gen reading the
    ``+kubebuilder:rbac`` markers; operator-forge emits it directly)."""
    from operator_forge.utils import yamlcompat as pyyaml

    rule_map: dict = {}
    order: list = []

    def add(group: str, resource: str, verbs: list[str]):
        key = (group, resource)
        if key not in rule_map:
            rule_map[key] = []
            order.append(key)
        for verb in verbs:
            if verb not in rule_map[key]:
                rule_map[key].append(verb)

    add("", "namespaces", ["list", "watch"])
    add("", "events", ["create", "patch"])
    for view in views:
        for rule in view.workload.get_rbac_rules():
            if not rule.is_resource_rule():
                continue
            group = "" if rule.group == "core" else rule.group
            add(group, rule.resource, rule.verbs)
        for child in view.workload.get_manifests().all_child_resources():
            for rule in child.rbac or []:
                if not rule.is_resource_rule():
                    continue
                group = "" if rule.group == "core" else rule.group
                add(group, rule.resource, rule.verbs)

    rules = [
        {
            "apiGroups": [group],
            "resources": [resource],
            "verbs": rule_map[(group, resource)],
        }
        for (group, resource) in order
    ]
    doc = {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": {"name": "manager-role"},
        "rules": rules,
    }
    return FileSpec(
        path="config/rbac/role.yaml",
        # the rules document is pure data: lower the representer walk
        # once per content hash alongside the render programs
        content=lowered_blob(
            "kustomize.cluster_role_yaml",
            (doc,),
            lambda: pyyaml.safe_dump(doc, sort_keys=False),
        ),
        add_boilerplate=False,
    )
