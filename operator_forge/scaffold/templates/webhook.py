"""Conversion-webhook scaffolding for multi-version kinds.

The reference scaffolds multiple API versions of a kind but punts version
conversion entirely to the user (docs/api-updates-upgrades.md describes
re-running ``create api`` with a new version; kubebuilder's ``create
webhook`` is never wrapped).  This module goes beyond the reference
(documented deviation, PARITY.md): with ``create api --enable-conversion``
a multi-version kind gets the full controller-runtime conversion-webhook
wiring:

- a Hub marker on the newest (storage) version,
- ConvertTo/ConvertFrom spoke stubs on every older version (user-owned,
  SKIP on re-scaffold, defaulting to a JSON round-trip which is correct
  for compatible schemas),
- the webhook Service / cert-manager Issuer+Certificate kustomize trees,
- a manager Deployment patch mounting the serving certificate,
- a ``spec.conversion`` webhook stanza + cert-manager CA-injection
  annotation on the generated CRD (kubebuilder reaches the same end state
  via kustomize patches; we generate CRDs directly).
"""

from __future__ import annotations

import os
import re

from ..context import ProjectConfig, WorkloadView
from ..machinery import FileSpec, Fragment, IfExists
from ...utils.names import to_file_name
from ..render import compiled_render


def other_versions(view: WorkloadView, output_dir: str) -> list[str]:
    """Previously scaffolded API versions of this kind (on disk), oldest
    first, excluding the current one."""
    if not output_dir:
        return []
    group_dir = os.path.join(output_dir, "apis", view.group)
    if not os.path.isdir(group_dir):
        return []
    types_name = f"{to_file_name(view.kind_lower)}_types.go"
    found = []
    for entry in sorted(os.listdir(group_dir)):
        if entry == view.version:
            continue
        if re.fullmatch(r"v\d+[a-z0-9]*", entry) and os.path.exists(
            os.path.join(group_dir, entry, types_name)
        ):
            found.append(entry)
    return found


_HUB_COMMENT = "Hub marks this version as the conversion hub"

_VERSION_RE = re.compile(r"v(\d+)(?:(alpha|beta)(\d+)?)?$")
_STAGE_RANK = {"alpha": 0, "beta": 1, None: 2}


def _version_key(version: str) -> tuple:
    """Kubernetes API version ordering: v1alpha1 < v1alpha2 < v1beta1 <
    v1 < v2alpha1 < v2.  Unparseable versions sort first."""
    match = _VERSION_RE.fullmatch(version)
    if not match:
        return (-1, 0, 0)
    major, stage, stage_num = match.groups()
    return (int(major), _STAGE_RANK[stage], int(stage_num or 0))


def hub_version(view: WorkloadView, output_dir: str) -> str:
    """The conversion hub is the newest version of the kind across the
    current config AND everything already scaffolded on disk — re-running
    `create api` for an older version (the documented partial-re-scaffold
    flow) must not demote the real hub."""
    return max(
        [view.version] + other_versions(view, output_dir), key=_version_key
    )


@compiled_render("webhook.conversion_files", subset=False)
def conversion_files(view: WorkloadView, output_dir: str) -> list[FileSpec]:
    """Hub + spoke conversion files for a multi-version kind; empty when the
    kind has a single scaffolded version.

    Spoke stubs are user-owned (SKIP on re-scaffold) — with one exception:
    when the hub moves to a newer version, the previous hub's generated
    ``Hub()`` file must become a spoke, so a file still containing the
    generated hub marker is overwritten (two hubs would not compile)."""
    all_versions = sorted(
        {view.version, *other_versions(view, output_dir)}, key=_version_key
    )
    if len(all_versions) < 2:
        return []
    hub = all_versions[-1]
    specs = [_hub_file(view, hub)]
    for spoke_version in all_versions[:-1]:
        spec = _spoke_file(view, spoke_version, hub)
        existing = os.path.join(output_dir, spec.path)
        if os.path.exists(existing):
            try:
                with open(existing, "r", encoding="utf-8") as handle:
                    content = handle.read()
                if _HUB_COMMENT in content:
                    spec.if_exists = IfExists.OVERWRITE
                elif f"/apis/{view.group}/{hub}\"" not in content:
                    # user-owned spoke still converting to an older hub:
                    # it will not compile against the migrated hub type
                    import sys

                    print(
                        f"warning: {spec.path} converts to a version other "
                        f"than the current hub {hub}; update its "
                        f"ConvertTo/ConvertFrom target (file is user-owned "
                        f"and was left unchanged)",
                        file=sys.stderr,
                    )
            except OSError:
                pass
        specs.append(spec)
    return specs


def _conversion_file_path(view: WorkloadView, version: str) -> str:
    return os.path.join(
        "apis", view.group, version,
        f"{to_file_name(view.kind_lower)}_conversion.go",
    )


def _hub_file(view: WorkloadView, hub: str) -> FileSpec:
    content = f'''package {hub}

// Hub marks this version as the conversion hub: every other served
// version of {view.kind} converts to and from this one
// (sigs.k8s.io/controller-runtime/pkg/conversion).
func (*{view.kind}) Hub() {{}}
'''
    return FileSpec(path=_conversion_file_path(view, hub), content=content)


def _spoke_file(view: WorkloadView, old_version: str, hub: str) -> FileSpec:
    hub_alias = f"{view.group}{hub}"
    kind = view.kind
    content = f'''package {old_version}

import (
\t"encoding/json"
\t"fmt"

\t"sigs.k8s.io/controller-runtime/pkg/conversion"

\t{hub_alias} "{view.config.repo}/apis/{view.group}/{hub}"
)

// ConvertTo converts this {kind} ({old_version}) to the Hub version
// ({hub}).  The default implementation is a JSON round-trip,
// which is correct while the schemas are structurally compatible; adjust
// the field mappings below when they diverge.  This file is user-owned:
// re-running `create api` never overwrites it.
func (src *{kind}) ConvertTo(dstRaw conversion.Hub) error {{
\tdst, ok := dstRaw.(*{hub_alias}.{kind})
\tif !ok {{
\t\treturn fmt.Errorf("unexpected conversion hub type for {kind}: %T", dstRaw)
\t}}

\tdata, err := json.Marshal(src)
\tif err != nil {{
\t\treturn err
\t}}

\tif err := json.Unmarshal(data, dst); err != nil {{
\t\treturn err
\t}}

\tdst.TypeMeta.APIVersion = {hub_alias}.GroupVersion.String()
\tdst.TypeMeta.Kind = "{kind}"

\treturn nil
}}

// ConvertFrom converts the Hub version ({hub}) to this
// {kind} ({old_version}).
func (dst *{kind}) ConvertFrom(srcRaw conversion.Hub) error {{
\tsrc, ok := srcRaw.(*{hub_alias}.{kind})
\tif !ok {{
\t\treturn fmt.Errorf("unexpected conversion hub type for {kind}: %T", srcRaw)
\t}}

\tdata, err := json.Marshal(src)
\tif err != nil {{
\t\treturn err
\t}}

\tif err := json.Unmarshal(data, dst); err != nil {{
\t\treturn err
\t}}

\tdst.TypeMeta.APIVersion = GroupVersion.String()
\tdst.TypeMeta.Kind = "{kind}"

\treturn nil
}}
'''
    return FileSpec(
        path=_conversion_file_path(view, old_version),
        content=content,
        if_exists=IfExists.SKIP,
    )


# -- kustomize config trees ----------------------------------------------


@compiled_render("webhook.webhook_config_tree")
def webhook_config_tree(config: ProjectConfig) -> list[FileSpec]:
    """config/webhook + config/certmanager + the manager webhook patch."""
    project = config.project_name
    namespace = f"{project}-system"
    service = f"{project}-webhook-service"
    return [
        FileSpec(
            path="config/webhook/kustomization.yaml",
            content="""resources:
- service.yaml
""",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/webhook/service.yaml",
            content="""apiVersion: v1
kind: Service
metadata:
  name: webhook-service
  namespace: system
spec:
  ports:
  - port: 443
    protocol: TCP
    targetPort: 9443
  selector:
    control-plane: controller-manager
""",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/certmanager/kustomization.yaml",
            content="""resources:
- certificate.yaml

configurations:
- kustomizeconfig.yaml
""",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/certmanager/kustomizeconfig.yaml",
            content="""# Teach kustomize that Certificate.spec.issuerRef.name refers to the
# Issuer resource, so the namePrefix applied to the Issuer is also
# applied to the reference (without this the prefixed Issuer is never
# found and the serving certificate is never issued).
nameReference:
- kind: Issuer
  group: cert-manager.io
  fieldSpecs:
  - kind: Certificate
    group: cert-manager.io
    path: spec/issuerRef/name
""",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/certmanager/certificate.yaml",
            content=f"""# Self-signed issuer + serving certificate for the conversion webhook.
# Requires cert-manager to be installed in the cluster.
apiVersion: cert-manager.io/v1
kind: Issuer
metadata:
  name: selfsigned-issuer
  namespace: system
spec:
  selfSigned: {{}}
---
apiVersion: cert-manager.io/v1
kind: Certificate
metadata:
  name: serving-cert
  namespace: system
spec:
  dnsNames:
  - {service}.{namespace}.svc
  - {service}.{namespace}.svc.cluster.local
  issuerRef:
    kind: Issuer
    name: selfsigned-issuer
  secretName: webhook-server-cert
""",
            add_boilerplate=False,
        ),
        FileSpec(
            path="config/default/manager_webhook_patch.yaml",
            content="""apiVersion: apps/v1
kind: Deployment
metadata:
  name: controller-manager
  namespace: system
spec:
  template:
    spec:
      containers:
      - name: manager
        ports:
        - containerPort: 9443
          name: webhook-server
          protocol: TCP
        volumeMounts:
        - mountPath: /tmp/k8s-webhook-server/serving-certs
          name: cert
          readOnly: true
      volumes:
      - name: cert
        secret:
          defaultMode: 420
          secretName: webhook-server-cert
""",
            add_boilerplate=False,
        ),
    ]


def update_default_kustomization(output_dir: str, dry_run: bool = False) -> bool:
    """Wire the webhook + certmanager trees and the manager patch into
    config/default/kustomization.yaml.

    Works on any project layout — including projects initialized before the
    scaffold markers existed and files the user has edited — by editing the
    YAML lines directly and idempotently: resource entries are inserted
    into the existing ``resources:`` list, and the patch entry is added to
    an existing ``patches:`` section rather than duplicating the key.

    Returns True when the file changed (or would change, with *dry_run*).
    """
    path = os.path.join(output_dir, "config", "default", "kustomization.yaml")
    if not os.path.exists(path):
        return False
    with open(path, "r", encoding="utf-8") as handle:
        original = handle.read()
    lines = original.split("\n")

    def has_entry(entry: str) -> bool:
        return any(line.strip() == entry for line in lines)

    def list_insert_at(key: str) -> int | None:
        """Index just after the last entry of a top-level ``key:`` list.
        List items may span multiple lines (e.g. a patch's ``target:``
        block): indented continuation lines belong to the current item and
        must not be split from it."""
        start = None
        for i, line in enumerate(lines):
            if line.strip() == f"{key}:" and not line.startswith((" ", "\t")):
                start = i
                break
        if start is None:
            return None
        end = start + 1
        for i in range(start + 1, len(lines)):
            stripped = lines[i].strip()
            if not stripped or stripped.startswith("#"):
                continue
            if stripped.startswith("- ") or lines[i][0] in (" ", "\t"):
                end = i + 1
            else:
                break
        return end

    for entry in ["- ../certmanager", "- ../webhook"]:
        if not has_entry(entry):
            at = list_insert_at("resources")
            if at is None:
                lines += ["resources:", entry]
            else:
                lines.insert(at, entry)

    patch_entry = "- path: manager_webhook_patch.yaml"
    if not has_entry(patch_entry):
        at = list_insert_at("patches")
        if at is None:
            if lines and lines[-1] == "":
                lines = lines[:-1]
            lines += ["", "patches:", patch_entry, ""]
        else:
            lines.insert(at, patch_entry)

    updated = "\n".join(lines)
    if updated == original:
        return False
    if not dry_run:
        with open(path, "w", encoding="utf-8") as handle:
            handle.write(updated)
    return True


@compiled_render("webhook.main_go_webhook_fragment")
def main_go_webhook_fragment(view: WorkloadView, hub: str) -> Fragment:
    """Register the hub type with the webhook builder so controller-runtime
    serves /convert for the kind."""
    alias = f"{view.group}{hub}"
    return Fragment(
        path="main.go",
        marker="reconcilers",
        code=(
            f"if err := ctrl.NewWebhookManagedBy(mgr)."
            f"For(&{alias}.{view.kind}{{}}).Complete(); err != nil {{\n"
            f'\tsetupLog.Error(err, "unable to create conversion webhook", '
            f'"webhook", "{view.kind}")\n'
            f"\tos.Exit(1)\n"
            f"}}\n"
        ),
    )


def crd_conversion_stanza(config: ProjectConfig) -> dict:
    """The spec.conversion block pointing at the (name-prefixed) webhook
    service; kustomize namePrefix does not rewrite these embedded values,
    so the final names are computed here."""
    project = config.project_name
    return {
        "strategy": "Webhook",
        "webhook": {
            "clientConfig": {
                "service": {
                    "name": f"{project}-webhook-service",
                    "namespace": f"{project}-system",
                    "path": "/convert",
                },
            },
            "conversionReviewVersions": ["v1"],
        },
    }


def crd_ca_injection_annotation(config: ProjectConfig) -> tuple[str, str]:
    project = config.project_name
    return (
        "cert-manager.io/inject-ca-from",
        f"{project}-system/{project}-serving-cert",
    )
