"""Project-skeleton templates: main.go, go.mod, Dockerfile, Makefile,
README, PROJECT, .gitignore, boilerplate.

Reference: internal/plugins/workload/v1/scaffolds/templates/{main,gomod,
dockerfile,makefile,readme}.go plus the kubebuilder golang/kustomize plugin
output the reference inherits.
"""

from __future__ import annotations

from ..context import ProjectConfig
from ..machinery import FileSpec, IfExists
from ..render import compiled_render

CONTROLLER_RUNTIME_VERSION = "v0.14.6"
K8S_VERSION = "v0.26.3"
GO_VERSION = "1.19"


def _fnv1a(data: str) -> int:
    h = 0xCBF29CE484222325
    for byte in data.encode("utf-8"):
        h ^= byte
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def leader_election_id(config: ProjectConfig) -> str:
    """Stable leader-election ID (the reference hashes with FNV in the
    generated main.go, templates/main.go:~250)."""
    digest = _fnv1a(config.repo) & 0xFFFFFFFF
    domain = config.domain or "operator-forge.io"
    return f"{digest:08x}.{domain}"


@compiled_render("project.project_file")
def project_file(config: ProjectConfig) -> FileSpec:
    return FileSpec(
        path="PROJECT", content=config.to_yaml(), add_boilerplate=False
    )


@compiled_render("project.boilerplate")
def boilerplate(license_header: str = "") -> FileSpec:
    content = license_header or (
        "/*\nCopyright 2026.\n\nLicensed under the Apache License, Version"
        ' 2.0 (the "License");\nyou may not use this file except in'
        " compliance with the License.\n*/\n"
    )
    return FileSpec(
        path="hack/boilerplate.go.txt",
        content=content,
        add_boilerplate=False,
        if_exists=IfExists.SKIP,
    )


@compiled_render("project.dockerignore")
def dockerignore() -> FileSpec:
    return FileSpec(
        path=".dockerignore",
        content="bin/\ntestbin/\nconfig/\ntest/\n.git/\n*.md\n",
        add_boilerplate=False,
        if_exists=IfExists.SKIP,
    )


@compiled_render("project.gitignore")
def gitignore() -> FileSpec:
    return FileSpec(
        path=".gitignore",
        content=(
            "# binaries\nbin/\n*.exe\n*.so\n*.dylib\n\n"
            "# test artifacts\n*.out\ntestbin/\n\n# editor state\n"
            "*.swp\n*.swo\n*~\n.idea/\n.vscode/\n"
        ),
        add_boilerplate=False,
        if_exists=IfExists.SKIP,
    )


@compiled_render("project.go_mod")
def go_mod(config: ProjectConfig) -> FileSpec:
    content = f"""module {config.repo}

go {GO_VERSION}

require (
\tgithub.com/go-logr/logr v1.2.3
\tgithub.com/spf13/cobra v1.6.1
\tk8s.io/api {K8S_VERSION}
\tk8s.io/apimachinery {K8S_VERSION}
\tk8s.io/client-go {K8S_VERSION}
\tsigs.k8s.io/controller-runtime {CONTROLLER_RUNTIME_VERSION}
\tsigs.k8s.io/yaml v1.3.0
)
"""
    return FileSpec(path="go.mod", content=content, add_boilerplate=False)


@compiled_render("project.main_go")
def main_go(config: ProjectConfig) -> FileSpec:
    election_id = leader_election_id(config)

    if config.component_config:
        # manager options come from a component-config file (reference
        # templates/main.go:236-257, the `{{ else }}` branch of
        # `{{ if not .ComponentConfig }}`)
        flags_block = '''\tvar configFile string

\tflag.StringVar(&configFile, "config", "",
\t\t"The controller will load its initial configuration from this file. "+
\t\t\t"Omit this flag to use the default configuration values. "+
\t\t\t"Command-line flags override configuration from this file.")'''
        manager_block = '''\tvar err error

\toptions := ctrl.Options{Scheme: scheme}

\tif configFile != "" {
\t\toptions, err = options.AndFrom(ctrl.ConfigFile().AtPath(configFile))
\t\tif err != nil {
\t\t\tsetupLog.Error(err, "unable to load the config file")
\t\t\tos.Exit(1)
\t\t}
\t}

\tmgr, err := ctrl.NewManager(ctrl.GetConfigOrDie(), options)'''
    else:
        flags_block = '''\tvar metricsAddr string
\tvar enableLeaderElection bool
\tvar probeAddr string

\tflag.StringVar(&metricsAddr, "metrics-bind-address", ":8080",
\t\t"The address the metric endpoint binds to.")
\tflag.StringVar(&probeAddr, "health-probe-bind-address", ":8081",
\t\t"The address the probe endpoint binds to.")
\tflag.BoolVar(&enableLeaderElection, "leader-elect", false,
\t\t"Enable leader election for controller manager. "+
\t\t\t"Enabling this will ensure there is only one active controller manager.")'''
        manager_block = f'''\tmgr, err := ctrl.NewManager(ctrl.GetConfigOrDie(), ctrl.Options{{
\t\tScheme:                 scheme,
\t\tMetricsBindAddress:     metricsAddr,
\t\tPort:                   9443,
\t\tHealthProbeBindAddress: probeAddr,
\t\tLeaderElection:         enableLeaderElection,
\t\tLeaderElectionID:       "{election_id}",
\t}})'''

    content = f'''package main

import (
\t"flag"
\t"os"

\t"k8s.io/apimachinery/pkg/runtime"
\tutilruntime "k8s.io/apimachinery/pkg/util/runtime"
\tclientgoscheme "k8s.io/client-go/kubernetes/scheme"
\t"k8s.io/client-go/rest"
\tctrl "sigs.k8s.io/controller-runtime"
\t"sigs.k8s.io/controller-runtime/pkg/healthz"
\t"sigs.k8s.io/controller-runtime/pkg/log/zap"
\t// +operator-builder:scaffold:imports
)

var (
\tscheme   = runtime.NewScheme()
\tsetupLog = ctrl.Log.WithName("setup")
)

func init() {{
\tutilruntime.Must(clientgoscheme.AddToScheme(scheme))
\t// +operator-builder:scaffold:scheme
}}

func main() {{
{flags_block}

\topts := zap.Options{{Development: true}}
\topts.BindFlags(flag.CommandLine)
\tflag.Parse()

\tctrl.SetLogger(zap.New(zap.UseFlagOptions(&opts)))

\t// only print a given warning the first time it is received
\t// (reference templates/main.go:229-234)
\trest.SetDefaultWarningHandler(
\t\trest.NewWarningWriter(os.Stderr, rest.WarningWriterOptions{{
\t\t\tDeduplicate: true,
\t\t}}),
\t)

{manager_block}
\tif err != nil {{
\t\tsetupLog.Error(err, "unable to start manager")
\t\tos.Exit(1)
\t}}

\t// +operator-builder:scaffold:reconcilers

\tif err := mgr.AddHealthzCheck("healthz", healthz.Ping); err != nil {{
\t\tsetupLog.Error(err, "unable to set up health check")
\t\tos.Exit(1)
\t}}

\tif err := mgr.AddReadyzCheck("readyz", healthz.Ping); err != nil {{
\t\tsetupLog.Error(err, "unable to set up ready check")
\t\tos.Exit(1)
\t}}

\tsetupLog.Info("starting manager")

\tif err := mgr.Start(ctrl.SetupSignalHandler()); err != nil {{
\t\tsetupLog.Error(err, "problem running manager")
\t\tos.Exit(1)
\t}}
}}
'''
    return FileSpec(path="main.go", content=content)


@compiled_render("project.dockerfile")
def dockerfile() -> FileSpec:
    content = f"""# Build the manager binary
FROM golang:{GO_VERSION} as builder

WORKDIR /workspace
# go.sum exists only after the first `go mod tidy`; the wildcard keeps the
# build working on a fresh scaffold
COPY go.mod go.su[m] ./
RUN go mod download

COPY main.go main.go
COPY apis/ apis/
COPY controllers/ controllers/
COPY internal/ internal/
COPY pkg/ pkg/

RUN CGO_ENABLED=0 GOOS=linux GOARCH=amd64 go build -a -o manager main.go

# Use distroless as minimal base image to package the manager binary
FROM gcr.io/distroless/static:nonroot
WORKDIR /
COPY --from=builder /workspace/manager .
USER 65532:65532

ENTRYPOINT ["/manager"]
"""
    return FileSpec(path="Dockerfile", content=content, add_boilerplate=False)


@compiled_render("project.makefile")
def makefile(config: ProjectConfig) -> FileSpec:
    cli_targets = ""
    if config.cli_root_command_name:
        cli = config.cli_root_command_name
        cli_targets = f"""
##@ Companion CLI

.PHONY: build-cli
build-cli: fmt vet ## Build the {cli} companion CLI.
\tgo build -o bin/{cli} cmd/{cli}/main.go

.PHONY: install-cli
install-cli: build-cli ## Install the {cli} companion CLI into GOBIN.
\tgo install ./cmd/{cli}
"""
    content = f"""# Image URL to use all building/pushing image targets
IMG ?= controller:latest
# ENVTEST_K8S_VERSION refers to the version of kubebuilder assets to be downloaded by envtest binary.
ENVTEST_K8S_VERSION = 1.26.1

GOBIN=$(shell go env GOBIN)
ifeq ($(GOBIN),)
GOBIN=$(shell go env GOPATH)/bin
endif

# Setting SHELL to bash allows bash commands to be executed by recipes.
SHELL = /usr/bin/env bash -o pipefail
.SHELLFLAGS = -ec

.PHONY: all
all: build

##@ General

.PHONY: help
help: ## Display this help.
\t@awk 'BEGIN {{FS = ":.*##"; printf "\\nUsage:\\n  make \\033[36m<target>\\033[0m\\n"}} /^[a-zA-Z_0-9-]+:.*?##/ {{ printf "  \\033[36m%-20s\\033[0m %s\\n", $$1, $$2 }} /^##@/ {{ printf "\\n\\033[1m%s\\033[0m\\n", substr($$0, 5) }} ' $(MAKEFILE_LIST)

##@ Development

.PHONY: manifests
manifests: controller-gen ## Regenerate CRDs and RBAC from code markers.
\t$(CONTROLLER_GEN) rbac:roleName=manager-role crd webhook paths="./..." output:crd:artifacts:config=config/crd/bases

.PHONY: generate
generate: controller-gen ## Generate deepcopy implementations.
\t$(CONTROLLER_GEN) object:headerFile="hack/boilerplate.go.txt" paths="./..."

.PHONY: fmt
fmt: ## Run go fmt against code.
\tgo fmt ./...

.PHONY: vet
vet: ## Run go vet against code.
\tgo vet ./...

.PHONY: test
test: manifests generate fmt vet envtest ## Run tests.
\tKUBEBUILDER_ASSETS="$(shell $(ENVTEST) use $(ENVTEST_K8S_VERSION) --bin-dir $(LOCALBIN) -p path)" go test ./... -coverprofile cover.out

.PHONY: test-e2e
test-e2e: ## Run e2e tests against the cluster in ~/.kube/config.
\tgo test ./test/e2e/... -tags e2e_test -v

##@ Build

.PHONY: build
build: generate fmt vet ## Build manager binary.
\tgo build -o bin/manager main.go

.PHONY: run
run: manifests generate fmt vet ## Run a controller from your host.
\tgo run ./main.go

.PHONY: docker-build
docker-build: test ## Build docker image with the manager.
\tdocker build -t $(IMG) .

.PHONY: docker-push
docker-push: ## Push docker image with the manager.
\tdocker push $(IMG)
{cli_targets}
##@ Deployment

.PHONY: install
install: manifests kustomize ## Install CRDs into the K8s cluster.
\t$(KUSTOMIZE) build config/crd | kubectl apply -f -

.PHONY: uninstall
uninstall: manifests kustomize ## Uninstall CRDs from the K8s cluster.
\t$(KUSTOMIZE) build config/crd | kubectl delete --ignore-not-found -f -

.PHONY: deploy
deploy: manifests kustomize ## Deploy controller to the K8s cluster.
\tcd config/manager && $(KUSTOMIZE) edit set image controller=$(IMG)
\t$(KUSTOMIZE) build config/default | kubectl apply -f -

.PHONY: undeploy
undeploy: ## Undeploy controller from the K8s cluster.
\t$(KUSTOMIZE) build config/default | kubectl delete --ignore-not-found -f -

##@ Build Dependencies

LOCALBIN ?= $(shell pwd)/bin
$(LOCALBIN):
\tmkdir -p $(LOCALBIN)

KUSTOMIZE ?= $(LOCALBIN)/kustomize
CONTROLLER_GEN ?= $(LOCALBIN)/controller-gen
ENVTEST ?= $(LOCALBIN)/setup-envtest

KUSTOMIZE_VERSION ?= v4.5.7
CONTROLLER_TOOLS_VERSION ?= v0.11.3

.PHONY: kustomize
kustomize: $(KUSTOMIZE)
$(KUSTOMIZE): $(LOCALBIN)
\ttest -s $(KUSTOMIZE) || GOBIN=$(LOCALBIN) go install sigs.k8s.io/kustomize/kustomize/v4@$(KUSTOMIZE_VERSION)

.PHONY: controller-gen
controller-gen: $(CONTROLLER_GEN)
$(CONTROLLER_GEN): $(LOCALBIN)
\ttest -s $(CONTROLLER_GEN) || GOBIN=$(LOCALBIN) go install sigs.k8s.io/controller-tools/cmd/controller-gen@$(CONTROLLER_TOOLS_VERSION)

.PHONY: envtest
envtest: $(ENVTEST)
$(ENVTEST): $(LOCALBIN)
\ttest -s $(ENVTEST) || GOBIN=$(LOCALBIN) go install sigs.k8s.io/controller-runtime/tools/setup-envtest@latest
"""
    return FileSpec(path="Makefile", content=content, add_boilerplate=False)


@compiled_render("project.readme")
def readme(config: ProjectConfig, workload_names: list[str]) -> FileSpec:
    cli_section = ""
    if config.cli_root_command_name:
        cli = config.cli_root_command_name
        cli_section = f"""
## Companion CLI

A companion CLI, `{cli}`, ships with this operator:

```bash
make build-cli
./bin/{cli} init    # print a sample custom resource manifest
./bin/{cli} generate --workload-manifest my-workload.yaml  # render child resources
./bin/{cli} version # print supported API versions
```
"""
    workloads = "\n".join(f"- {name}" for name in workload_names) or "- (none yet)"
    content = f"""# {config.repo.rsplit('/', 1)[-1]}

A Kubernetes operator generated by operator-forge.  It manages the following
workloads:

{workloads}

## Getting started

```bash
# install CRDs
make install

# run the controller locally
make run

# or deploy it to the cluster
make docker-build docker-push IMG=<registry>/<image>:<tag>
make deploy IMG=<registry>/<image>:<tag>
```

Create an instance of a workload from the generated sample:

```bash
kubectl apply -f config/samples/
```
{cli_section}
## Testing

```bash
make test       # unit + envtest suites
make test-e2e   # e2e suite against the current kubeconfig context
```

## Notes

Source manifests using YAML anchors/aliases are expanded during
generation — each alias becomes an independent copy, and merge keys
(`<<:`) are applied with standard YAML merge semantics.  The generated Go
object code and rendered child manifests therefore carry the expanded
form; the data is identical, only the sharing notation is gone.
"""
    return FileSpec(path="README.md", content=content, add_boilerplate=False)
