"""Admission-webhook scaffolding: ``create webhook``.

The reference binary inherits kubebuilder's ``create webhook``
(defaulting and validating admission webhooks) through the golangv3
bundle it registers (reference pkg/cli/init.go:27-41); the workload
plugin itself never scaffolds them, but the CLI surface exists and the
kubebuilder docs it points users at describe exactly this output.  This
module produces the same end state for operator-forge projects:

- a user-owned ``<kind>_webhook.go`` beside the API types implementing
  ``webhook.Defaulter`` and/or ``webhook.Validator`` (SKIP on
  re-scaffold, like the mutate/dependencies hooks),
- ``config/webhook/manifests.yaml`` with the Mutating/Validating
  WebhookConfiguration objects (kubebuilder derives these from
  ``//+kubebuilder:webhook`` markers via controller-gen at build time;
  operator-forge generates config directly, as it does for CRDs),
- the shared webhook Service / cert-manager tree and manager patch
  (reused from the conversion-webhook scaffolding),
- a ``main.go`` registration fragment per kind.
"""

from __future__ import annotations

import os

from ..context import ProjectConfig, WorkloadView
from ..machinery import FileSpec, Fragment, IfExists
from ...utils.names import to_file_name
from ..render import compiled_render


def webhook_path(view: WorkloadView, kind_of: str) -> str:
    """kubebuilder's serving path: /mutate-<group-dashed>-<version>-<kind>."""
    dashed = view.full_group.replace(".", "-")
    return f"/{kind_of}-{dashed}-{view.version}-{view.kind_lower}"


def webhook_file_path(view: WorkloadView) -> str:
    """The one place the stub's location is computed — the writer and
    the stale-stub check must agree on it."""
    return os.path.join(
        view.api_types_dir, f"{to_file_name(view.kind_lower)}_webhook.go"
    )


@compiled_render("admission.webhook_stub_file")
def webhook_stub_file(
    view: WorkloadView,
    defaulting: bool,
    validation: bool,
    force: bool = False,
) -> FileSpec:
    """The user-owned webhook implementation beside the API types
    (kubebuilder: api/<version>/<kind>_webhook.go)."""
    kind = view.kind
    logger = f"{view.kind_lower}log"
    imports = ['\tctrl "sigs.k8s.io/controller-runtime"']
    if validation:
        imports.insert(0, '\t"k8s.io/apimachinery/pkg/runtime"')
    imports.append('\tlogf "sigs.k8s.io/controller-runtime/pkg/log"')
    imports.append('\t"sigs.k8s.io/controller-runtime/pkg/webhook"')

    parts = [
        f"package {view.version}\n",
        "import (\n" + "\n".join(imports) + "\n)\n",
        f'// log is for logging in this package.\n'
        f'var {logger} = logf.Log.WithName("{view.kind_lower}-resource")\n',
        f"// SetupWebhookWithManager registers the webhook for {kind}\n"
        f"// with the manager.\n"
        f"func (r *{kind}) SetupWebhookWithManager(mgr ctrl.Manager) error {{\n"
        f"\treturn ctrl.NewWebhookManagedBy(mgr).\n"
        f"\t\tFor(r).\n"
        f"\t\tComplete()\n"
        f"}}\n",
    ]
    if defaulting:
        parts.append(
            f"//+kubebuilder:webhook:path={webhook_path(view, 'mutate')},"
            f"mutating=true,failurePolicy=fail,sideEffects=None,"
            f"groups={view.full_group},resources={view.plural.lower()},"
            f"verbs=create;update,versions={view.version},"
            f"name=m{view.kind_lower}.kb.io,admissionReviewVersions=v1\n\n"
            f"var _ webhook.Defaulter = &{kind}{{}}\n",
        )
        parts.append(
            f"// Default implements webhook.Defaulter so a webhook will be\n"
            f"// registered for the type.\n"
            f"func (r *{kind}) Default() {{\n"
            f'\t{logger}.Info("default", "name", r.Name)\n\n'
            f"\t// TODO: fill in defaulting logic.\n"
            f"}}\n",
        )
    if validation:
        parts.append(
            # delete is registered too: the scaffold emits a
            # ValidateDelete stub, so the webhook must actually be
            # CALLED on delete or a filled-in stub silently never runs
            f"//+kubebuilder:webhook:path={webhook_path(view, 'validate')},"
            f"mutating=false,failurePolicy=fail,sideEffects=None,"
            f"groups={view.full_group},resources={view.plural.lower()},"
            f"verbs=create;update;delete,versions={view.version},"
            f"name=v{view.kind_lower}.kb.io,admissionReviewVersions=v1\n\n"
            f"var _ webhook.Validator = &{kind}{{}}\n",
        )
        parts.append(
            f"// ValidateCreate implements webhook.Validator so a webhook\n"
            f"// will be registered for the type.\n"
            f"func (r *{kind}) ValidateCreate() error {{\n"
            f'\t{logger}.Info("validate create", "name", r.Name)\n\n'
            f"\t// TODO: fill in create validation logic.\n"
            f"\treturn nil\n"
            f"}}\n",
        )
        parts.append(
            f"// ValidateUpdate implements webhook.Validator so a webhook\n"
            f"// will be registered for the type.\n"
            f"func (r *{kind}) ValidateUpdate(old runtime.Object) error {{\n"
            f'\t{logger}.Info("validate update", "name", r.Name)\n\n'
            f"\t// TODO: fill in update validation logic.\n"
            f"\treturn nil\n"
            f"}}\n",
        )
        parts.append(
            f"// ValidateDelete implements webhook.Validator so a webhook\n"
            f"// will be registered for the type.\n"
            f"func (r *{kind}) ValidateDelete() error {{\n"
            f'\t{logger}.Info("validate delete", "name", r.Name)\n\n'
            f"\t// TODO: fill in delete validation logic.\n"
            f"\treturn nil\n"
            f"}}\n",
        )
    content = "\n".join(parts)
    # user-owned: preserved on re-scaffold, like mutate/dependencies
    # hooks — unless --force asks for regeneration (kubebuilder
    # semantics)
    return FileSpec(
        path=webhook_file_path(view),
        content=content,
        if_exists=IfExists.OVERWRITE if force else IfExists.SKIP,
    )


def stale_stubs(
    views: list[WorkloadView],
    output_dir: str,
    defaulting: bool,
    validation: bool,
) -> list[str]:
    """Existing user-owned stubs missing a requested interface.  The
    stub is SKIP-preserved, so scaffolding over it can't add the
    methods; silently emitting manifests for an unserved path would
    reject every write in-cluster (failurePolicy: Fail).  kubebuilder
    errors on the existing file; so do we."""
    problems = []
    for view in views:
        path = webhook_file_path(view)
        full = os.path.join(output_dir, path)
        if not os.path.exists(full):
            continue
        with open(full, encoding="utf-8") as fh:
            text = fh.read()
        if defaulting and "webhook.Defaulter" not in text:
            problems.append(
                f"{path}: exists without webhook.Defaulter — add the "
                f"Default() method yourself, or re-run with --force to "
                f"regenerate the file (discards your edits)"
            )
        if validation and "webhook.Validator" not in text:
            problems.append(
                f"{path}: exists without webhook.Validator — add the "
                f"Validate* methods yourself, or re-run with --force to "
                f"regenerate the file (discards your edits)"
            )
    return problems


def _webhook_entry(
    config: ProjectConfig, view: WorkloadView, kind_of: str
) -> str:
    """One entry of a WebhookConfiguration's ``webhooks`` list.  The
    validating entry also registers DELETE — the scaffold emits a
    ValidateDelete stub, which must actually be called on delete."""
    project = config.project_name
    prefix = "m" if kind_of == "mutate" else "v"
    delete_op = "" if kind_of == "mutate" else "\n    - DELETE"
    return f"""- admissionReviewVersions:
  - v1
  clientConfig:
    service:
      name: {project}-webhook-service
      namespace: {project}-system
      path: {webhook_path(view, kind_of)}
  failurePolicy: Fail
  name: {prefix}{view.kind_lower}.kb.io
  rules:
  - apiGroups:
    - {view.full_group}
    apiVersions:
    - {view.version}
    operations:
    - CREATE
    - UPDATE{delete_op}
    resources:
    - {view.plural.lower()}
  sideEffects: None
"""


@compiled_render("admission.webhook_manifests_file")
def webhook_manifests_file(
    config: ProjectConfig,
    views: list[WorkloadView],
    defaulting: bool,
    validation: bool,
) -> FileSpec:
    """config/webhook/manifests.yaml: the admission registration objects
    (kubebuilder emits these from controller-gen; generated directly
    here, with the cert-manager CA injection annotation inlined since no
    kustomize patch pipeline runs afterwards)."""
    project = config.project_name
    ca_annotation = (
        f"    cert-manager.io/inject-ca-from: "
        f"{project}-system/{project}-serving-cert"
    )
    docs = []
    if defaulting:
        entries = "".join(
            _webhook_entry(config, view, "mutate") for view in views
        )
        # metadata.name stays unprefixed: the kustomize namePrefix in
        # config/default adds the project prefix (inlined service/CA
        # names are NOT rewritten by kustomize, so those stay full)
        docs.append(
            f"""apiVersion: admissionregistration.k8s.io/v1
kind: MutatingWebhookConfiguration
metadata:
  name: mutating-webhook-configuration
  annotations:
{ca_annotation}
webhooks:
{entries}"""
        )
    if validation:
        entries = "".join(
            _webhook_entry(config, view, "validate") for view in views
        )
        docs.append(
            f"""apiVersion: admissionregistration.k8s.io/v1
kind: ValidatingWebhookConfiguration
metadata:
  name: validating-webhook-configuration
  annotations:
{ca_annotation}
webhooks:
{entries}"""
        )
    return FileSpec(
        path="config/webhook/manifests.yaml",
        content="---\n".join(docs),
        add_boilerplate=False,
    )


@compiled_render("admission.webhook_kustomization_file")
def webhook_kustomization_file() -> FileSpec:
    """config/webhook/kustomization.yaml listing the admission manifests
    next to the service (overwrites the conversion-only variant)."""
    return FileSpec(
        path="config/webhook/kustomization.yaml",
        content="""resources:
- manifests.yaml
- service.yaml
""",
        add_boilerplate=False,
    )


@compiled_render("admission.main_go_admission_fragments")
def main_go_admission_fragments(view: WorkloadView) -> list[Fragment]:
    """Register the kind's webhook with the manager.  The api-types
    import fragment is repeated defensively (fragment insertion is
    idempotent) so `create webhook` works even on a main.go scaffolded
    without this kind."""
    alias = view.api_import_alias
    return [
        Fragment(
            path="main.go",
            marker="imports",
            code=f'{alias} "{view.api_types_import}"',
        ),
        Fragment(
            path="main.go",
            marker="reconcilers",
            code=(
                f"if err := (&{alias}.{view.kind}{{}})."
                f"SetupWebhookWithManager(mgr); err != nil {{\n"
                f'\tsetupLog.Error(err, "unable to create webhook", '
                f'"webhook", "{view.kind}")\n'
                f"\tos.Exit(1)\n"
                f"}}\n"
            ),
        ),
    ]
