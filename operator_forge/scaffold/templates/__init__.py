"""Generated-code template bodies.

Equivalent of the reference's
internal/plugins/workload/v1/scaffolds/templates/** tree (SURVEY.md §2.2),
organized as Python modules that build Go/YAML/Make text from a
:class:`~operator_forge.scaffold.context.WorkloadView`.

A deliberate design difference from the reference: generated projects embed
their reconciliation runtime (``pkg/orchestrate``) instead of depending on
the external nukleros/operator-builder-tools module, so generated operators
are self-contained.
"""
