"""E2E test templates for generated projects.

Reference: internal/plugins/workload/v1/scaffolds/templates/test/e2e/
{e2e,workloads}.go — a suite (build tag ``e2e_test``) run against a real
cluster via kubeconfig: create each workload from its sample, wait for child
resources to converge, mutate the parent, delete, and verify teardown; wait
helpers use a 90s timeout with a 3s interval (reference e2e.go:117-122).
"""

from __future__ import annotations

from ...utils import to_file_name
from ..context import ProjectConfig, WorkloadView
from ..machinery import FileSpec


def e2e_files(
    views: list[WorkloadView], config: ProjectConfig
) -> list[FileSpec]:
    specs = [_common(views, config)]
    for view in views:
        specs.append(_workload_test(view))
    return specs


def _common(views: list[WorkloadView], config: ProjectConfig) -> FileSpec:
    api_imports = []
    schemes = []
    seen = set()
    for view in views:
        alias = view.api_import_alias
        if alias in seen:
            continue
        seen.add(alias)
        api_imports.append(f'\t{alias} "{view.api_types_import}"')
        schemes.append(
            f"\tif err := {alias}.AddToScheme(scheme.Scheme); err != nil {{\n"
            f"\t\tpanic(err)\n"
            f"\t}}"
        )

    content = f'''//go:build e2e_test

// Package e2e runs the operator's end-to-end suite against the cluster
// selected by the current kubeconfig context.  Typical flow:
//
//\tmake install          # install CRDs
//\tmake run &            # or deploy the controller in-cluster
//\tmake test-e2e
package e2e

import (
\t"context"
\t"fmt"
\t"os"
\t"testing"
\t"time"

\t"k8s.io/apimachinery/pkg/api/errors"
\t"k8s.io/apimachinery/pkg/apis/meta/v1/unstructured"
\t"k8s.io/client-go/kubernetes/scheme"
\tctrl "sigs.k8s.io/controller-runtime"
\t"sigs.k8s.io/controller-runtime/pkg/client"
\tsigsyaml "sigs.k8s.io/yaml"

{chr(10).join(api_imports)}
)

const (
\twaitTimeout  = 90 * time.Second
\twaitInterval = 3 * time.Second
)

var k8sClient client.Client

func TestMain(m *testing.M) {{
\tcfg, err := ctrl.GetConfig()
\tif err != nil {{
\t\tfmt.Println("unable to load kubeconfig:", err)
\t\tos.Exit(1)
\t}}

{chr(10).join(schemes)}

\tk8sClient, err = client.New(cfg, client.Options{{Scheme: scheme.Scheme}})
\tif err != nil {{
\t\tfmt.Println("unable to create client:", err)
\t\tos.Exit(1)
\t}}

\tos.Exit(m.Run())
}}

// waitFor polls condition until it returns true or the suite wait timeout
// elapses.
func waitFor(t *testing.T, what string, condition func() (bool, error)) {{
\tt.Helper()

\tdeadline := time.Now().Add(waitTimeout)

\tfor {{
\t\tok, err := condition()
\t\tif err != nil {{
\t\t\tt.Logf("condition %s errored: %v", what, err)
\t\t}}

\t\tif ok {{
\t\t\treturn
\t\t}}

\t\tif time.Now().After(deadline) {{
\t\t\tt.Fatalf("timed out waiting for %s", what)
\t\t}}

\t\ttime.Sleep(waitInterval)
\t}}
}}

// fromSampleYAML decodes a sample manifest into obj.
func fromSampleYAML(sample string, obj client.Object) error {{
\treturn sigsyaml.Unmarshal([]byte(sample), obj)
}}

// childExists reports whether the child resource described by gvk/name/ns
// exists in the cluster.
func childExists(ctx context.Context, group, version, kind, name, namespace string) (bool, error) {{
\tlive := &unstructured.Unstructured{{}}
\tlive.SetAPIVersion(apiVersionFor(group, version))
\tlive.SetKind(kind)

\terr := k8sClient.Get(ctx, client.ObjectKey{{Name: name, Namespace: namespace}}, live)
\tif err != nil {{
\t\tif errors.IsNotFound(err) {{
\t\t\treturn false, nil
\t\t}}

\t\treturn false, err
\t}}

\treturn true, nil
}}

func apiVersionFor(group, version string) string {{
\tif group == "" {{
\t\treturn version
\t}}

\treturn group + "/" + version
}}
'''
    return FileSpec(
        path="test/e2e/e2e_test.go", content=content, add_boilerplate=False
    )


def _workload_test(view: WorkloadView) -> FileSpec:
    kind = view.kind
    alias = view.api_import_alias
    pkg = view.package_name
    coll = view.collection
    is_component = view.is_component() and coll is not None

    if is_component:
        generate_children = f'''\tcollection := &{coll.api_import_alias}.{coll.kind}{{}}
\tif err := fromSampleYAML({coll.package_name}.Sample(false), collection); err != nil {{
\t\tt.Fatalf("unable to decode collection sample: %v", err)
\t}}

\tchildren, err := {pkg}.Generate(*workload, *collection)'''
    else:
        generate_children = f"\tchildren, err := {pkg}.Generate(*workload)"

    extra_imports = ""
    if is_component:
        if coll.api_types_import != view.api_types_import:
            extra_imports += (
                f'\t{coll.api_import_alias} "{coll.api_types_import}"\n'
            )
        extra_imports += f'\t{coll.package_name} "{coll.resources_import}"\n'

    content = f'''//go:build e2e_test

package e2e

import (
\t"context"
\t"testing"

\t"k8s.io/apimachinery/pkg/api/errors"
\t"sigs.k8s.io/controller-runtime/pkg/client"

\t{alias} "{view.api_types_import}"
\t{pkg} "{view.resources_import}"
{extra_imports})

// Test{kind}Lifecycle creates the {kind} sample, waits for its child
// resources to exist, updates the parent, deletes it, and verifies
// teardown.
func Test{kind}Lifecycle(t *testing.T) {{
\tctx := context.Background()

\tworkload := &{alias}.{kind}{{}}
\tif err := fromSampleYAML({pkg}.Sample(false), workload); err != nil {{
\t\tt.Fatalf("unable to decode sample: %v", err)
\t}}

\tif workload.GetNamespace() == "" {{
\t\tworkload.SetNamespace("default")
\t}}

\t// create
\tif err := k8sClient.Create(ctx, workload); err != nil {{
\t\tt.Fatalf("unable to create workload: %v", err)
\t}}

\tdefer func() {{
\t\t_ = k8sClient.Delete(ctx, workload)
\t}}()

\t// children converge
{generate_children}
\tif err != nil {{
\t\tt.Fatalf("unable to render children: %v", err)
\t}}

\tfor _, child := range children {{
\t\tchild := child
\t\tgvk := child.GetObjectKind().GroupVersionKind()

\t\tnamespace := child.GetNamespace()
\t\tif namespace == "" {{
\t\t\tnamespace = workload.GetNamespace()
\t\t}}

\t\twaitFor(t, "child "+gvk.Kind+"/"+child.GetName(), func() (bool, error) {{
\t\t\treturn childExists(ctx, gvk.Group, gvk.Version, gvk.Kind, child.GetName(), namespace)
\t\t}})
\t}}

\t// parent reports created
\twaitFor(t, "{kind} status.created", func() (bool, error) {{
\t\tlive := &{alias}.{kind}{{}}
\t\tif err := k8sClient.Get(ctx, client.ObjectKeyFromObject(workload), live); err != nil {{
\t\t\treturn false, err
\t\t}}

\t\treturn live.Status.Created, nil
\t}})

\t// delete and verify teardown
\tif err := k8sClient.Delete(ctx, workload); err != nil {{
\t\tt.Fatalf("unable to delete workload: %v", err)
\t}}

\twaitFor(t, "{kind} deletion", func() (bool, error) {{
\t\tlive := &{alias}.{kind}{{}}
\t\terr := k8sClient.Get(ctx, client.ObjectKeyFromObject(workload), live)
\t\tif errors.IsNotFound(err) {{
\t\t\treturn true, nil
\t\t}}

\t\treturn false, err
\t}})
}}
'''
    return FileSpec(
        path=f"test/e2e/{to_file_name(view.group)}_"
        f"{to_file_name(view.kind_lower)}_test.go",
        content=content,
        add_boilerplate=False,
    )
