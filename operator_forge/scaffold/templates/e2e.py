"""E2E test templates for generated projects.

Reference: internal/plugins/workload/v1/scaffolds/templates/test/e2e/
{e2e,workloads}.go — a suite (build tag ``e2e_test``) run against a real
cluster via kubeconfig: optional DEPLOY/DEPLOY_IN_CLUSTER make-driven
install (e2e.go:275-341), per-test namespaces (workloads.go:175-188),
create each workload from its sample, wait for children to converge,
repair child drift (e2e.go:815-853), scan controller logs for errors
(e2e.go:551-599,855-875), TEARDOWN-driven undeploy (e2e.go:330-341), and
wait helpers with a 90s timeout / 3s interval (e2e.go:117-122).

Beyond the reference: the update-parent test actually mutates a
marker-controlled spec field and waits for children to converge to the new
rendering — the reference leaves this TODO (workloads.go:147-152, its
issue #67) because it cannot predict which fields are safe to mutate; the
generator can, because it owns the marker-to-field mapping.
"""

from __future__ import annotations

from ...utils import to_file_name
from ...workload.fieldmarkers import FieldType
from ..context import ProjectConfig, WorkloadView
from ..machinery import FileSpec
from ..render import compiled_render


def e2e_files(
    views: list[WorkloadView], config: ProjectConfig
) -> list[FileSpec]:
    specs = [_common(views, config)]
    by_workload = {id(v.workload): v for v in views}

    def transitive_deps(workload, seen: set) -> list:
        """Dependency views in creation order (prerequisites first) —
        the TRANSITIVE closure: a dependency's own dependencies must
        also exist, or its DependencyHandler blocks and the chain
        deadlocks one level deeper."""
        ordered = []
        for dep in workload.get_dependencies():
            if id(dep) in seen or id(dep) not in by_workload:
                continue
            seen.add(id(dep))
            ordered.extend(transitive_deps(dep, seen))
            ordered.append(by_workload[id(dep)])
        return ordered

    for view in views:
        specs.append(
            _workload_test(view, transitive_deps(view.workload, set()))
        )
    return specs


def pick_update_field(view: WorkloadView):
    """The marker-controlled spec field the generated update-parent test
    mutates, as ``(go_path, FieldType)`` — int preferred (incrementing is
    always valid and visible), then string (suffixed; still valid for
    label/name-shaped values).  Bools are never picked: flipping a
    defaulted-true bool to false is erased by ``omitempty`` + the CRD
    default, so the test would hang waiting on a change the API server
    never sees.  Ints defaulting to -1 are skipped for the same reason
    (``++`` crosses the -1 -> 0 omitempty boundary).  None when the kind
    has no mutable leaf spec fields."""
    root = view.workload.get_api_spec_fields()
    if root is None:
        return None

    leaves: list[tuple] = []

    def walk(node, path):
        for child in node.children:
            # the injected collection reference is not marker-controlled;
            # mutating it would re-target the component, not its children
            if not path and child.manifest_name == "collection":
                continue
            if child.type == FieldType.STRUCT:
                walk(child, path + [child.name])
            else:
                leaves.append((child, ".".join(path + [child.name])))

    walk(root, [])

    for preferred in (FieldType.INT, FieldType.STRING):
        for child, path in leaves:
            if child.type != preferred:
                continue
            if preferred == FieldType.INT and child.default_value == -1:
                continue
            return path, preferred
    return None


def tester_namespace(view: WorkloadView) -> str:
    """Per-test namespace (reference workloads.go getTesterNamespace:
    test-<group>-<version>-<kind>); empty for cluster-scoped kinds."""
    if view.workload.is_cluster_scoped():
        return ""
    return "-".join(
        ["test", view.group.lower(), view.version.lower(),
         view.kind_lower]
    )


@compiled_render("e2e._common")
def _common(views: list[WorkloadView], config: ProjectConfig) -> FileSpec:
    api_imports = []
    schemes = []
    seen = set()
    for view in views:
        alias = view.api_import_alias
        if alias in seen:
            continue
        seen.add(alias)
        api_imports.append(f'\t{alias} "{view.api_types_import}"')
        schemes.append(
            f"\tif err := {alias}.AddToScheme(scheme.Scheme); err != nil {{\n"
            f"\t\tpanic(err)\n"
            f"\t}}"
        )

    project = config.project_name
    controller_ns = f"{project}-system"
    controller_deployment = f"{project}-controller-manager"

    content = f'''//go:build e2e_test

// Package e2e runs the operator's end-to-end suite against the cluster
// selected by the current kubeconfig context.  Environment flags drive
// optional install flows (reference e2e.go:275-341):
//
//\tDEPLOY=true             make install (CRDs) before the suite
//\tDEPLOY_IN_CLUSTER=true  docker-build/push + make deploy (with
//\t                        DEPLOY=true), and wait for the controller;
//\t                        also enables controller-log error scanning
//\tTEARDOWN=true           make undeploy (or uninstall) after the suite
//
// Without them, run `make install` and `make run &` first, then
// `make test-e2e`.
package e2e

import (
	"bytes"
	"context"
	"fmt"
	"io"
	"os"
	"os/exec"
	"strings"
	"testing"
	"time"

	corev1 "k8s.io/api/core/v1"
	"k8s.io/apimachinery/pkg/api/errors"
	metav1 "k8s.io/apimachinery/pkg/apis/meta/v1"
	"k8s.io/apimachinery/pkg/apis/meta/v1/unstructured"
	"k8s.io/client-go/kubernetes"
	"k8s.io/client-go/kubernetes/scheme"
	"k8s.io/client-go/rest"
	ctrl "sigs.k8s.io/controller-runtime"
	"sigs.k8s.io/controller-runtime/pkg/client"
	sigsyaml "sigs.k8s.io/yaml"

{chr(10).join(api_imports)}
)

const (
	waitTimeout  = 90 * time.Second
	waitInterval = 3 * time.Second

	controllerNamespace  = "{controller_ns}"
	controllerDeployment = "{controller_deployment}"
)

var (
	k8sClient  client.Client
	restConfig *rest.Config
)

func TestMain(m *testing.M) {{
	cfg, err := ctrl.GetConfig()
	if err != nil {{
		fmt.Println("unable to load kubeconfig:", err)
		os.Exit(1)
	}}

	restConfig = cfg

{chr(10).join(schemes)}

	k8sClient, err = client.New(cfg, client.Options{{Scheme: scheme.Scheme}})
	if err != nil {{
		fmt.Println("unable to create client:", err)
		os.Exit(1)
	}}

	if err := deployIfRequested(); err != nil {{
		fmt.Println("deploy failed:", err)
		os.Exit(1)
	}}

	code := m.Run()

	if err := teardownIfRequested(); err != nil {{
		fmt.Println("teardown failed:", err)
		os.Exit(1)
	}}

	os.Exit(code)
}}

// deployIfRequested runs the env-var-driven install flows (reference
// e2e.go:275-326): DEPLOY installs CRDs; DEPLOY_IN_CLUSTER additionally
// builds, pushes, and deploys the controller, then waits for it.
func deployIfRequested() error {{
	if os.Getenv("DEPLOY") == "true" {{
		if err := runMake("install"); err != nil {{
			return err
		}}
	}}

	if os.Getenv("DEPLOY_IN_CLUSTER") != "true" {{
		return nil
	}}

	if os.Getenv("DEPLOY") == "true" {{
		for _, target := range []string{{"docker-build", "docker-push", "deploy"}} {{
			if err := runMake(target); err != nil {{
				return err
			}}
		}}
	}}

	return waitForController()
}}

// teardownIfRequested undeploys (or uninstalls CRDs) after the suite
// (reference e2e.go:330-341, TEARDOWN).
func teardownIfRequested() error {{
	if os.Getenv("TEARDOWN") != "true" {{
		return nil
	}}

	if os.Getenv("DEPLOY_IN_CLUSTER") == "true" {{
		return runMake("undeploy")
	}}

	return runMake("uninstall")
}}

func runMake(target string) error {{
	command := exec.Command("make", "-C", "../..", target)
	if output, err := command.CombinedOutput(); err != nil {{
		return fmt.Errorf("'make %s' failed: %w\\n%s", target, err, output)
	}}

	return nil
}}

// waitForController blocks until the controller deployment reports at
// least one ready replica.
func waitForController() error {{
	deadline := time.Now().Add(waitTimeout)

	for {{
		deployment := &unstructured.Unstructured{{}}
		deployment.SetAPIVersion("apps/v1")
		deployment.SetKind("Deployment")

		err := k8sClient.Get(context.Background(), client.ObjectKey{{
			Name:      controllerDeployment,
			Namespace: controllerNamespace,
		}}, deployment)
		if err == nil {{
			ready, _, _ := unstructured.NestedInt64(deployment.Object, "status", "readyReplicas")
			if ready > 0 {{
				return nil
			}}
		}}

		if time.Now().After(deadline) {{
			return fmt.Errorf("timed out waiting for controller deployment (last error: %v)", err)
		}}

		time.Sleep(waitInterval)
	}}
}}

// waitFor polls condition until it returns true or the suite wait timeout
// elapses.
func waitFor(t *testing.T, what string, condition func() (bool, error)) {{
	t.Helper()

	deadline := time.Now().Add(waitTimeout)

	for {{
		ok, err := condition()
		if err != nil {{
			t.Logf("condition %s errored: %v", what, err)
		}}

		if ok {{
			return
		}}

		if time.Now().After(deadline) {{
			t.Fatalf("timed out waiting for %s", what)
		}}

		time.Sleep(waitInterval)
	}}
}}

// fromSampleYAML decodes a sample manifest into obj.
func fromSampleYAML(sample string, obj client.Object) error {{
	return sigsyaml.Unmarshal([]byte(sample), obj)
}}

// ensureNamespace creates the per-test namespace if it does not exist
// (reference workloads.go:175-188 runs each tester in its own namespace).
func ensureNamespace(t *testing.T, ctx context.Context, name string) {{
	t.Helper()

	if name == "" {{
		return
	}}

	namespace := &corev1.Namespace{{}}
	namespace.SetName(name)

	if err := k8sClient.Create(ctx, namespace); err != nil && !errors.IsAlreadyExists(err) {{
		t.Fatalf("unable to create namespace %s: %v", name, err)
	}}
}}

// childExists reports whether the child resource described by gvk/name/ns
// exists in the cluster.
func childExists(ctx context.Context, group, version, kind, name, namespace string) (bool, error) {{
	live := &unstructured.Unstructured{{}}
	live.SetAPIVersion(apiVersionFor(group, version))
	live.SetKind(kind)

	err := k8sClient.Get(ctx, client.ObjectKey{{Name: name, Namespace: namespace}}, live)
	if err != nil {{
		if errors.IsNotFound(err) {{
			return false, nil
		}}

		return false, err
	}}

	return true, nil
}}

// childConverged reports whether the live child contains every field of
// the desired rendering (server-side apply guarantees applied fields are
// reflected; extra server-defaulted fields are ignored).
func childConverged(ctx context.Context, desired client.Object, namespace string) (bool, error) {{
	rendered, ok := desired.(*unstructured.Unstructured)
	if !ok {{
		return true, nil
	}}

	live := &unstructured.Unstructured{{}}
	live.SetGroupVersionKind(desired.GetObjectKind().GroupVersionKind())

	if err := k8sClient.Get(ctx, client.ObjectKey{{
		Name:      desired.GetName(),
		Namespace: namespace,
	}}, live); err != nil {{
		if errors.IsNotFound(err) {{
			return false, nil
		}}

		return false, err
	}}

	for key, value := range rendered.Object {{
		switch key {{
		case "apiVersion", "kind", "metadata", "status":
			continue
		}}

		if !subsetMatch(value, live.Object[key]) {{
			return false, nil
		}}
	}}

	return true, nil
}}

// subsetMatch reports whether every leaf of desired is present and equal
// in live.  Lists match index-wise; numbers compare by value regardless of
// int/float representation.
func subsetMatch(desired, live interface{{}}) bool {{
	switch desiredTyped := desired.(type) {{
	case map[string]interface{{}}:
		liveMap, ok := live.(map[string]interface{{}})
		if !ok {{
			return false
		}}

		for key, value := range desiredTyped {{
			if !subsetMatch(value, liveMap[key]) {{
				return false
			}}
		}}

		return true
	case []interface{{}}:
		liveList, ok := live.([]interface{{}})
		if !ok || len(liveList) < len(desiredTyped) {{
			return false
		}}

		for i := range desiredTyped {{
			if !subsetMatch(desiredTyped[i], liveList[i]) {{
				return false
			}}
		}}

		return true
	default:
		if desired == live {{
			return true
		}}

		// normalize numeric representations (int vs int64 vs float64)
		return fmt.Sprintf("%v", desired) == fmt.Sprintf("%v", live)
	}}
}}

// controllerLogs returns the combined logs of every controller pod
// (reference getControllerLogs, e2e.go:551-599).
func controllerLogs(ctx context.Context) (string, error) {{
	clientset, err := kubernetes.NewForConfig(restConfig)
	if err != nil {{
		return "", fmt.Errorf("unable to create clientset: %w", err)
	}}

	pods, err := clientset.CoreV1().Pods(controllerNamespace).List(ctx, metav1.ListOptions{{
		LabelSelector: "control-plane=controller-manager",
	}})
	if err != nil {{
		return "", fmt.Errorf("unable to list controller pods: %w", err)
	}}

	buffer := new(bytes.Buffer)

	for i := range pods.Items {{
		pod := &pods.Items[i]

		for _, container := range pod.Spec.Containers {{
			request := clientset.CoreV1().Pods(pod.Namespace).GetLogs(
				pod.Name, &corev1.PodLogOptions{{Container: container.Name}},
			)

			stream, err := request.Stream(ctx)
			if err != nil {{
				return "", fmt.Errorf("unable to stream logs for %s/%s: %w", pod.Namespace, pod.Name, err)
			}}

			_, err = io.Copy(buffer, stream)

			stream.Close()

			if err != nil {{
				return "", fmt.Errorf("unable to read logs for %s/%s: %w", pod.Namespace, pod.Name, err)
			}}
		}}
	}}

	return buffer.String(), nil
}}

// assertNoControllerErrors fails the test when controller logs contain
// ERROR lines for the given controller (reference
// testControllerLogsNoErrors, e2e.go:855-875).  Only meaningful when the
// controller runs in-cluster.
func assertNoControllerErrors(t *testing.T, ctx context.Context, logSyntax string) {{
	t.Helper()

	if os.Getenv("DEPLOY_IN_CLUSTER") != "true" {{
		return
	}}

	logs, err := controllerLogs(ctx)
	if err != nil {{
		t.Fatalf("unable to fetch controller logs: %v", err)
	}}

	for _, line := range strings.Split(logs, "\\n") {{
		if strings.Contains(line, "ERROR") && strings.Contains(line, logSyntax) {{
			t.Errorf("controller error logged: %s", line)
		}}
	}}
}}

func apiVersionFor(group, version string) string {{
	if group == "" {{
		return version
	}}

	return group + "/" + version
}}
'''
    return FileSpec(
        path="test/e2e/e2e_test.go", content=content, add_boilerplate=False
    )


@compiled_render("e2e._workload_test")
def _workload_test(
    view: WorkloadView, dep_views: list[WorkloadView] | None = None
) -> FileSpec:
    kind = view.kind
    alias = view.api_import_alias
    pkg = view.package_name
    coll = view.collection
    is_component = view.is_component() and coll is not None
    cluster_scoped = view.workload.is_cluster_scoped()
    namespace = tester_namespace(view)
    log_syntax = f"controllers.{view.group}.{kind}"
    dep_views = dep_views or []

    if is_component:
        coll_ns = tester_namespace(coll)
        coll_ns_setup = ""
        if not coll.workload.is_cluster_scoped():
            coll_ns_setup = f'''\tensureNamespace(t, ctx, "{coll_ns}")

\tif collection.GetNamespace() == "" {{
\t\tcollection.SetNamespace("{coll_ns}")
\t}}
'''
        collection_setup = f'''\t// components resolve their collection before rendering; create it
\t// first (tolerating another test of this suite having done so)
\tcollection := &{coll.api_import_alias}.{coll.kind}{{}}
\tif err := fromSampleYAML({coll.package_name}.Sample(false), collection); err != nil {{
\t\tt.Fatalf("unable to decode collection sample: %v", err)
\t}}

{coll_ns_setup}
\tif err := k8sClient.Create(ctx, collection); err != nil && !errors.IsAlreadyExists(err) {{
\t\tt.Fatalf("unable to create collection: %v", err)
\t}}

'''
        generate_children = f"children, err := {pkg}.Generate(*workload, *collection)"
        generate_updated = f"{pkg}.Generate(*updated, *collection)"
    else:
        collection_setup = ""
        generate_children = f"children, err := {pkg}.Generate(*workload)"
        generate_updated = f"{pkg}.Generate(*updated)"

    # dependencies gate the reconciler's Dependency phase on another
    # workload kind reporting status.created (apis <kind>_types.go
    # GetDependencyWorkloads + orchestrate DependenciesSatisfied), and
    # each lifecycle test deletes its own workload at the end — so a
    # dependent kind's test must create its dependencies itself, in
    # each dependency's own tester namespace, tolerating earlier tests
    # having done so.  Without this the suite deadlocks on real
    # clusters whenever a dependency's test ran (and tore down) first.
    dependency_setup = ""
    for dep_view in dep_views:
        dep_kind = dep_view.kind
        dep_ns = tester_namespace(dep_view)
        ns_lines = ""
        if not dep_view.workload.is_cluster_scoped():
            ns_lines = f'''\tensureNamespace(t, ctx, "{dep_ns}")

\tif dependency{dep_kind}.GetNamespace() == "" {{
\t\tdependency{dep_kind}.SetNamespace("{dep_ns}")
\t}}

'''
        dependency_setup += f'''\t// {kind} depends on {dep_kind}: create it so the dependency
\t// phase can observe one reporting created
\tdependency{dep_kind} := &{dep_view.api_import_alias}.{dep_kind}{{}}
\tif err := fromSampleYAML({dep_view.package_name}.Sample(false), dependency{dep_kind}); err != nil {{
\t\tt.Fatalf("unable to decode {dep_kind} dependency sample: %v", err)
\t}}

{ns_lines}\tif err := k8sClient.Create(ctx, dependency{dep_kind}); err != nil && !errors.IsAlreadyExists(err) {{
\t\tt.Fatalf("unable to create {dep_kind} dependency: %v", err)
\t}}

'''

    # imports beyond the workload's own (dedup by alias: a dependency
    # may share the collection's version package)
    import_lines: dict = {}
    if is_component:
        if coll.api_types_import != view.api_types_import:
            import_lines[coll.api_import_alias] = coll.api_types_import
        import_lines[coll.package_name] = coll.resources_import
    for dep_view in dep_views:
        if dep_view.api_types_import != view.api_types_import:
            import_lines.setdefault(
                dep_view.api_import_alias, dep_view.api_types_import
            )
        import_lines.setdefault(
            dep_view.package_name, dep_view.resources_import
        )
    extra_imports = "".join(
        f'\t{alias_} "{path}"\n' for alias_, path in import_lines.items()
    )

    ns_setup = ""
    if not cluster_scoped:
        ns_setup = '''\tensureNamespace(t, ctx, namespace)
\tworkload.SetNamespace(namespace)
'''

    # -- update-parent block (beyond the reference; see module docstring) --
    picked = pick_update_field(view)
    if picked is not None:
        go_path, field_type = picked
        if field_type == FieldType.INT:
            mutation = f"updated.Spec.{go_path}++"
        else:
            mutation = (
                f'updated.Spec.{go_path} = updated.Spec.{go_path} + "x"'
            )
        update_block = f'''
\t// update the parent: mutate the marker-controlled field
\t// spec.{go_path} and wait for children to converge to the new
\t// rendering (reference testUpdateParentResource, e2e.go:815-833)
\tupdated := &{alias}.{kind}{{}}
\tif err := k8sClient.Get(ctx, client.ObjectKeyFromObject(workload), updated); err != nil {{
\t\tt.Fatalf("unable to fetch workload for update: %v", err)
\t}}

\t{mutation}

\tif err := k8sClient.Update(ctx, updated); err != nil {{
\t\tt.Fatalf("unable to update workload: %v", err)
\t}}

\texpected, err := {generate_updated}
\tif err != nil {{
\t\tt.Fatalf("unable to render updated children: %v", err)
\t}}

\tfor _, child := range expected {{
\t\tchild := child
\t\tchildNamespace := child.GetNamespace()
\t\tif childNamespace == "" {{
\t\t\tchildNamespace = workload.GetNamespace()
\t\t}}

\t\tif workload.GetNamespace() != "" && childNamespace != workload.GetNamespace() {{
\t\t\tcontinue // cross-namespace children reconcile without owner events
\t\t}}

\t\tgvk := child.GetObjectKind().GroupVersionKind()
\t\twaitFor(t, "updated child "+gvk.Kind+"/"+child.GetName(), func() (bool, error) {{
\t\t\treturn childConverged(ctx, child, childNamespace)
\t\t}})
\t}}
'''
    else:
        update_block = '''
\t// this kind has no marker-controlled leaf fields, so there is no
\t// spec mutation whose effect on children can be asserted
'''

    # adopt a pre-existing object instead of failing: another test of
    # this suite may have created it already — components pre-create
    # their collection AND their dependency workloads (see the
    # dependency setup above), so any kind can exist by the time its
    # own lifecycle test runs
    create_block = '''\t// create (adopting an object another test already created)
\tif err := k8sClient.Create(ctx, workload); err != nil {
\t\tif !errors.IsAlreadyExists(err) {
\t\t\tt.Fatalf("unable to create workload: %v", err)
\t\t}
\t}'''

    multi_test = ""
    if not cluster_scoped and not view.is_collection():
        # reference workloads.go:167-172 re-runs namespaced component
        # tests in a second namespace
        multi_test = f'''

// Test{kind}LifecycleMulti re-runs the lifecycle in a second namespace to
// verify the operator handles multiple instances of the same kind
// (reference workloads.go Test_..Multi).
func Test{kind}LifecycleMulti(t *testing.T) {{
\trun{kind}Lifecycle(t, "{namespace}-2")
}}'''

    content = f'''//go:build e2e_test

package e2e

import (
\t"context"
\t"testing"

\t"k8s.io/apimachinery/pkg/api/errors"
\t"sigs.k8s.io/controller-runtime/pkg/client"

\t{alias} "{view.api_types_import}"
\t{pkg} "{view.resources_import}"
{extra_imports})

// Test{kind}Lifecycle creates the {kind} sample in its own namespace,
// waits for children to converge, repairs child drift, updates the
// parent, scans controller logs, deletes it, and verifies teardown.
func Test{kind}Lifecycle(t *testing.T) {{
\trun{kind}Lifecycle(t, "{namespace}")
}}{multi_test}

func run{kind}Lifecycle(t *testing.T, namespace string) {{
\tctx := context.Background()

\tworkload := &{alias}.{kind}{{}}
\tif err := fromSampleYAML({pkg}.Sample(false), workload); err != nil {{
\t\tt.Fatalf("unable to decode sample: %v", err)
\t}}

{ns_setup}
{collection_setup}{dependency_setup}{create_block}

\tdefer func() {{
\t\t_ = k8sClient.Delete(ctx, workload)
\t}}()

\t// children converge
\t{generate_children}
\tif err != nil {{
\t\tt.Fatalf("unable to render children: %v", err)
\t}}

\tfor _, child := range children {{
\t\tchild := child
\t\tgvk := child.GetObjectKind().GroupVersionKind()

\t\tchildNamespace := child.GetNamespace()
\t\tif childNamespace == "" {{
\t\t\tchildNamespace = workload.GetNamespace()
\t\t}}

\t\twaitFor(t, "child "+gvk.Kind+"/"+child.GetName(), func() (bool, error) {{
\t\t\treturn childExists(ctx, gvk.Group, gvk.Version, gvk.Kind, child.GetName(), childNamespace)
\t\t}})
\t}}

\t// parent reports created
\twaitFor(t, "{kind} status.created", func() (bool, error) {{
\t\tlive := &{alias}.{kind}{{}}
\t\tif err := k8sClient.Get(ctx, client.ObjectKeyFromObject(workload), live); err != nil {{
\t\t\treturn false, err
\t\t}}

\t\treturn live.Status.Created, nil
\t}})

\t// child drift repair: delete an owned child and wait for the
\t// reconciler to restore it (reference testDeleteChildResource,
\t// e2e.go:794-813)
\tfor _, child := range children {{
\t\tchild := child

\t\tchildNamespace := child.GetNamespace()
\t\tif childNamespace == "" {{
\t\t\tchildNamespace = workload.GetNamespace()
\t\t}}

\t\tif workload.GetNamespace() != "" && childNamespace != workload.GetNamespace() {{
\t\t\tcontinue // only owner-watched children restore on drift
\t\t}}

\t\tgvk := child.GetObjectKind().GroupVersionKind()

\t\tdrifted := child.DeepCopyObject().(client.Object)
\t\tdrifted.SetNamespace(childNamespace)

\t\tif err := k8sClient.Delete(ctx, drifted); err != nil {{
\t\t\tt.Fatalf("unable to delete child for drift test: %v", err)
\t\t}}

\t\twaitFor(t, "restored child "+gvk.Kind+"/"+child.GetName(), func() (bool, error) {{
\t\t\treturn childExists(ctx, gvk.Group, gvk.Version, gvk.Kind, child.GetName(), childNamespace)
\t\t}})

\t\tbreak
\t}}
{update_block}
\t// controller logs carry no errors for this controller
\tassertNoControllerErrors(t, ctx, "{log_syntax}")

\t// delete and verify teardown
\tif err := k8sClient.Delete(ctx, workload); err != nil {{
\t\tt.Fatalf("unable to delete workload: %v", err)
\t}}

\twaitFor(t, "{kind} deletion", func() (bool, error) {{
\t\tlive := &{alias}.{kind}{{}}
\t\terr := k8sClient.Get(ctx, client.ObjectKeyFromObject(workload), live)
\t\tif errors.IsNotFound(err) {{
\t\t\treturn true, nil
\t\t}}

\t\treturn false, err
\t}})
}}
'''
    return FileSpec(
        path=f"test/e2e/{to_file_name(view.group)}_"
        f"{to_file_name(view.kind_lower)}_test.go",
        content=content,
        add_boilerplate=False,
    )
