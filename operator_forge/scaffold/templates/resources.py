"""Resources-package templates: resources.go, per-manifest child-resource
definitions, and the user-owned mutate/dependencies hooks.

Reference: internal/plugins/workload/v1/scaffolds/templates/api/resources/
{resources,definition}.go and templates/int/{mutate,dependencies}/
component.go.
"""

from __future__ import annotations

from ...gocodegen.generate import uses_sprintf
from ..context import WorkloadView
from ..machinery import FileSpec, IfExists
from .api import sample_yaml
from ..render import compiled_render


def _workload_args_decl(view: WorkloadView) -> str:
    """Argument list shared by create funcs: the parent workload and, for
    components, its collection."""
    args = [f"\tparent *{view.api_import_alias}.{view.kind},"]
    coll = view.collection
    if view.is_component() and coll is not None:
        args.append(f"\tcollection *{coll.api_import_alias}.{coll.kind},")
    elif view.is_collection():
        # a collection is its own collection; create funcs take it as both
        pass
    return "\n".join(args)


def _collection_import(view: WorkloadView) -> str:
    coll = view.collection
    if (
        view.is_component()
        and coll is not None
        # same group/version: the workload's own api import already covers it
        and coll.api_types_import != view.api_types_import
    ):
        return (
            f'\t{coll.api_import_alias} "{coll.api_types_import}"\n'
        )
    return ""


@compiled_render("resources.resources_file")
def resources_file(view: WorkloadView) -> FileSpec:
    """The resources.go file for a workload's resources package
    (reference templates/api/resources/resources.go:40-230)."""
    kind = view.kind
    alias = view.api_import_alias
    pkg = view.package_name
    coll = view.collection
    is_component = view.is_component() and coll is not None

    create_names, init_names = view.workload.get_manifests().func_names()

    sample_all = sample_yaml(view, required_only=False).rstrip("\n")
    sample_required = sample_yaml(view, required_only=True).rstrip("\n")

    func_sig_args = f"*{alias}.{kind},"
    call_args = "parent"
    generate_params = f"workloadObj {alias}.{kind}"
    generate_pass = "&workloadObj"
    if is_component:
        func_sig_args += f"\n\t*{coll.api_import_alias}.{coll.kind},"
        call_args = "parent, collection"
        generate_params = (
            f"\n\tworkloadObj {alias}.{kind},"
            f"\n\tcollectionObj {coll.api_import_alias}.{coll.kind},\n"
        )
        generate_pass = "&workloadObj, &collectionObj"

    create_entries = "\n".join(f"\t{name}," for name in create_names)
    init_entries = "\n".join(f"\t{name}," for name in init_names)

    seen_gvks = set()
    gvk_entries = []
    for child in view.workload.get_manifests().all_child_resources():
        key = (child.group, child.version, child.kind)
        if key not in seen_gvks:
            seen_gvks.add(key)
            gvk_entries.append(
                f'\t{{Group: "{child.group}", Version: "{child.version}", '
                f'Kind: "{child.kind}"}},'
            )
    gvk_block = "\n".join(gvk_entries)

    cli_block = ""
    cli_imports = ""
    if view.has_cli:
        cli_imports = '\t"fmt"\n\n\t"sigs.k8s.io/yaml"\n'
        if is_component:
            cli_sig = "workloadFile []byte, collectionFile []byte"
            cli_unmarshal = f'''\tvar workloadObj {alias}.{kind}
\tif err := yaml.Unmarshal(workloadFile, &workloadObj); err != nil {{
\t\treturn nil, fmt.Errorf("failed to unmarshal yaml into workload: %w", err)
\t}}

\tif err := orchestrate.Validate(&workloadObj); err != nil {{
\t\treturn nil, fmt.Errorf("error validating workload yaml: %w", err)
\t}}

\tvar collectionObj {coll.api_import_alias}.{coll.kind}
\tif err := yaml.Unmarshal(collectionFile, &collectionObj); err != nil {{
\t\treturn nil, fmt.Errorf("failed to unmarshal yaml into collection: %w", err)
\t}}

\tif err := orchestrate.Validate(&collectionObj); err != nil {{
\t\treturn nil, fmt.Errorf("error validating collection yaml: %w", err)
\t}}

\treturn Generate(workloadObj, collectionObj)'''
        elif view.is_collection():
            cli_sig = "collectionFile []byte"
            cli_unmarshal = f'''\tvar collectionObj {alias}.{kind}
\tif err := yaml.Unmarshal(collectionFile, &collectionObj); err != nil {{
\t\treturn nil, fmt.Errorf("failed to unmarshal yaml into collection: %w", err)
\t}}

\tif err := orchestrate.Validate(&collectionObj); err != nil {{
\t\treturn nil, fmt.Errorf("error validating collection yaml: %w", err)
\t}}

\treturn Generate(collectionObj)'''
        else:
            cli_sig = "workloadFile []byte"
            cli_unmarshal = f'''\tvar workloadObj {alias}.{kind}
\tif err := yaml.Unmarshal(workloadFile, &workloadObj); err != nil {{
\t\treturn nil, fmt.Errorf("failed to unmarshal yaml into workload: %w", err)
\t}}

\tif err := orchestrate.Validate(&workloadObj); err != nil {{
\t\treturn nil, fmt.Errorf("error validating workload yaml: %w", err)
\t}}

\treturn Generate(workloadObj)'''
        cli_block = f'''
// GenerateForCLI returns the child resources for this workload rendered
// from YAML manifest files (used by the companion CLI's generate command).
func GenerateForCLI({cli_sig}) ([]client.Object, error) {{
{cli_unmarshal}
}}
'''

    convert_block = _convert_workload_block(view)

    content = f'''package {pkg}

import (
{cli_imports}\t"k8s.io/apimachinery/pkg/runtime/schema"
\t"sigs.k8s.io/controller-runtime/pkg/client"

\t"{view.config.repo}/pkg/orchestrate"

\t{alias} "{view.api_types_import}"
{_collection_import(view)})

// ChildResourceGVKs is the static set of child resource kinds this
// workload's manifests define.  It is fixed at code generation —
// independent of include/exclude markers and spec contents — so teardown
// can enumerate annotated children even when the current spec renders none
// of a kind, or when a component's collection is gone.
var ChildResourceGVKs = []schema.GroupVersionKind{{
{gvk_block}
}}

// sample{kind} is a sample manifest containing all configurable fields.
const sample{kind} = `{sample_all}`

// sample{kind}Required is a sample manifest containing only required fields.
const sample{kind}Required = `{sample_required}`

// Sample returns the sample manifest for this custom resource.
func Sample(requiredOnly bool) string {{
\tif requiredOnly {{
\t\treturn sample{kind}Required
\t}}

\treturn sample{kind}
}}

// Generate returns the child resources that are associated with this
// workload given appropriate structured inputs.
func Generate({generate_params}) ([]client.Object, error) {{
\tresourceObjects := []client.Object{{}}

\tfor _, f := range CreateFuncs {{
\t\tresources, err := f({generate_pass})
\t\tif err != nil {{
\t\t\treturn nil, err
\t\t}}

\t\tresourceObjects = append(resourceObjects, resources...)
\t}}

\treturn resourceObjects, nil
}}
{cli_block}
// CreateFuncs is an array of functions called to render the child resources
// of this workload during reconciliation.
var CreateFuncs = []func(
\t{func_sig_args}
) ([]client.Object, error){{
{create_entries}
}}

// InitFuncs is an array of functions called prior to starting the controller
// manager.  CRD child resources are created here so the controller can own
// custom resources of those types at startup.
var InitFuncs = []func(
\t{func_sig_args}
) ([]client.Object, error){{
{init_entries}
}}
{convert_block}
'''
    return FileSpec(
        path=f"{view.resources_dir}/resources.go", content=content
    )


def _convert_workload_block(view: WorkloadView) -> str:
    kind = view.kind
    alias = view.api_import_alias
    coll = view.collection
    if view.is_component() and coll is not None:
        coll_type = f"{coll.api_import_alias}.{coll.kind}"
        return f'''
// ConvertWorkload converts generic workloads into the typed workload and
// collection for this package.
func ConvertWorkload(component, collection orchestrate.Workload) (
\t*{alias}.{kind},
\t*{coll_type},
\terror,
) {{
\tworkload, ok := component.(*{alias}.{kind})
\tif !ok {{
\t\treturn nil, nil, {alias}.ErrUnableToConvert{kind}
\t}}

\tcollectionObj, ok := collection.(*{coll_type})
\tif !ok {{
\t\treturn nil, nil, {coll.api_import_alias}.ErrUnableToConvert{coll.kind}
\t}}

\treturn workload, collectionObj, nil
}}'''
    return f'''
// ConvertWorkload converts a generic workload into the typed workload for
// this package.
func ConvertWorkload(component orchestrate.Workload) (*{alias}.{kind}, error) {{
\tworkload, ok := component.(*{alias}.{kind})
\tif !ok {{
\t\treturn nil, {alias}.ErrUnableToConvert{kind}
\t}}

\treturn workload, nil
}}'''


@compiled_render("resources.definition_files")
def definition_files(view: WorkloadView) -> list[FileSpec]:
    """One Go file per source manifest, each containing the create funcs for
    the manifest's child resources
    (reference templates/api/resources/definition.go:45-88)."""
    specs = []
    for manifest in view.workload.get_manifests():
        if not manifest.child_resources:
            continue
        specs.append(_definition_file(view, manifest))
    return specs


def _definition_file(view: WorkloadView, manifest) -> FileSpec:
    pkg = view.package_name
    args_decl = _workload_args_decl(view)
    needs_fmt = any(uses_sprintf(c.source_code) for c in manifest.child_resources)

    blocks = []
    for child in manifest.child_resources:
        rbac_markers = "\n".join(
            f"// {r.to_marker().removeprefix('// ')}"
            for r in (child.rbac or [])
        )
        const_decl = ""
        if child.name_constant():
            const_decl = (
                f'// {child.unique_name} holds the name of the {child.kind} '
                f'resource.\nconst {child.unique_name} = '
                f'"{child.name_constant()}"\n\n'
            )
        include = ""
        if child.include_code:
            include = "\n" + "\n".join(
                "\t" + line for line in child.include_code.split("\n")
            ) + "\n"
        namespace_default = ""
        if not view.workload.is_cluster_scoped():
            namespace_default = '''
\tif resourceObj.GetNamespace() == "" {
\t\tresourceObj.SetNamespace(parent.Namespace)
\t}
'''
        source = "\n".join(
            "\t" + line if line else "" for line in child.source_code.split("\n")
        )
        blocks.append(f'''{rbac_markers}

{const_decl}// {child.create_func_name()} creates the {child.name} {child.kind}
// resource for the workload.
func {child.create_func_name()}(
{args_decl}
) ([]client.Object, error) {{{include}
{source}
{namespace_default}
\treturn []client.Object{{resourceObj}}, nil
}}
''')

    fmt_import = '\t"fmt"\n\n' if needs_fmt else ""
    content = (
        f"package {pkg}\n\n"
        "import (\n"
        f"{fmt_import}"
        '\t"k8s.io/apimachinery/pkg/apis/meta/v1/unstructured"\n'
        '\t"sigs.k8s.io/controller-runtime/pkg/client"\n\n'
        f'\t{view.api_import_alias} "{view.api_types_import}"\n'
        f"{_collection_import(view)})\n\n" + "\n".join(blocks)
    )
    return FileSpec(
        path=f"{view.resources_dir}/{manifest.source_filename}",
        content=content,
    )


@compiled_render("resources.mutate_hook")
def mutate_hook(view: WorkloadView) -> FileSpec:
    """User-owned mutation hook, never overwritten on re-scaffold
    (reference templates/int/mutate/component.go, SkipFile)."""
    kind = view.kind
    args_decl = _workload_args_decl(view)
    content = f'''package mutate

import (
\t"sigs.k8s.io/controller-runtime/pkg/client"

\t{view.api_import_alias} "{view.api_types_import}"
{_collection_import(view)})

// {kind}Mutate mutates a child resource of the {kind} workload prior to
// apply.  This file is scaffolded once and owned by you: edit it to inject
// custom mutation logic.  Returning an empty slice drops the resource.
func {kind}Mutate(
\toriginal client.Object,
{args_decl}
) ([]client.Object, error) {{
\treturn []client.Object{{original}}, nil
}}
'''
    return FileSpec(
        path=f"internal/mutate/{view.kind_lower}.go",
        content=content,
        if_exists=IfExists.SKIP,
    )


@compiled_render("resources.dependencies_hook")
def dependencies_hook(view: WorkloadView) -> FileSpec:
    """User-owned dependency-check hook, never overwritten on re-scaffold
    (reference templates/int/dependencies/component.go, SkipFile)."""
    kind = view.kind
    content = f'''package dependencies

import (
\t"{view.config.repo}/pkg/orchestrate"
)

// {kind}CheckReady performs custom dependency checks for the {kind}
// workload before resources are created.  This file is scaffolded once and
// owned by you: edit it to gate reconciliation on external conditions.
func {kind}CheckReady(r orchestrate.Reconciler, req *orchestrate.Request) (bool, error) {{
\treturn true, nil
}}
'''
    return FileSpec(
        path=f"internal/dependencies/{view.kind_lower}.go",
        content=content,
        if_exists=IfExists.SKIP,
    )
