"""Companion-CLI templates: the generated cobra CLI that ships with the
operator (init / generate / version commands).

Reference: internal/plugins/workload/v1/scaffolds/templates/cli/
{main,cmd_root,cmd_init,cmd_init_sub,cmd_generate,cmd_generate_sub,
cmd_version,cmd_version_sub}.go.  Capability contract (per SURVEY.md §2.2 and
docs/companion-cli.md as corrected by the code): ``init`` prints sample CR
manifests (``-r`` for required-only), ``generate`` renders child resources
from CR manifest files, ``version`` prints the CLI version and supported API
versions.

Design deviation from the reference (documented): instead of marker-based
fragment insertion into the root command, per-workload subcommand files live
in the same package as their parent command and self-register via Go
``init()`` — re-scaffolding is a plain overwrite and stays idempotent.

Layout for a standalone workload (single workload, direct commands):
    cmd/<root>/main.go
    cmd/<root>/commands/root.go
    cmd/<root>/commands/initcmd/init.go          (+ <kind>.go)
    cmd/<root>/commands/generatecmd/generate.go  (+ <kind>.go)
    cmd/<root>/commands/versioncmd/version.go    (+ <kind>.go)

For collections, every workload (the collection and each component) gets a
named subcommand under init/generate/version.
"""

from __future__ import annotations

from ...utils import to_file_name
from ..context import ProjectConfig, WorkloadView
from ..machinery import FileSpec
from ..render import compiled_render


def cli_files(
    views: list[WorkloadView], config: ProjectConfig
) -> list[FileSpec]:
    if not config.cli_root_command_name:
        return []
    root = config.cli_root_command_name
    specs = [
        _main_go(root, config),
        _root_go(root, config),
        _parent_cmd(root, config, "initcmd", "init",
                    "Print sample custom resource manifests"),
        _parent_cmd(root, config, "generatecmd", "generate",
                    "Generate child resource manifests from a workload"),
        _parent_cmd(root, config, "versioncmd", "version",
                    "Print version and supported API versions"),
    ]
    for view in views:
        specs.append(_init_sub(root, view))
        specs.append(_generate_sub(root, view))
        specs.append(_version_sub(root, view))
    return specs


def _cmd_name(view: WorkloadView) -> str:
    """Subcommand name for a workload: its configured companion subcommand
    name, defaulting to the lowercase kind."""
    if view.workload.companion_sub_cmd.has_name():
        return view.workload.companion_sub_cmd.name
    return view.kind_lower


def _cmd_description(view: WorkloadView) -> str:
    if view.workload.companion_sub_cmd.has_description():
        return view.workload.companion_sub_cmd.description
    return f"Manage {view.kind_lower} workload"


@compiled_render("companion_cli._main_go")
def _main_go(root: str, config: ProjectConfig) -> FileSpec:
    content = f'''package main

import (
\t"os"

\t"{config.repo}/cmd/{root}/commands"
)

func main() {{
\tif err := commands.NewRootCommand().Execute(); err != nil {{
\t\tos.Exit(1)
\t}}
}}
'''
    return FileSpec(path=f"cmd/{root}/main.go", content=content)


@compiled_render("companion_cli._root_go")
def _root_go(root: str, config: ProjectConfig) -> FileSpec:
    description = config.cli_root_command_description or f"Manage {root} workloads"
    content = f'''package commands

import (
\t"github.com/spf13/cobra"

\t"{config.repo}/cmd/{root}/commands/generatecmd"
\t"{config.repo}/cmd/{root}/commands/initcmd"
\t"{config.repo}/cmd/{root}/commands/versioncmd"
)

// NewRootCommand assembles the {root} command tree.
func NewRootCommand() *cobra.Command {{
\troot := &cobra.Command{{
\t\tUse:   "{root}",
\t\tShort: "{description}",
\t\tLong:  "{description}",
\t}}

\troot.AddCommand(
\t\tinitcmd.Command(),
\t\tgeneratecmd.Command(),
\t\tversioncmd.Command(),
\t)

\treturn root
}}
'''
    return FileSpec(path=f"cmd/{root}/commands/root.go", content=content)


@compiled_render("companion_cli._parent_cmd")
def _parent_cmd(
    root: str, config: ProjectConfig, pkg: str, use: str, short: str
) -> FileSpec:
    extra = ""
    if pkg == "versioncmd":
        extra = (
            "\n// cliVersion is stamped at build time via\n"
            '// -ldflags "-X .../versioncmd.cliVersion=v1.2.3".\n'
            'var cliVersion = "dev"\n'
        )
    content = f'''package {pkg}

import (
\t"github.com/spf13/cobra"
)
{extra}
// subcommands are registered by the per-workload files in this package via
// init(), keeping re-scaffolding a plain overwrite.
var subcommands []func() *cobra.Command

// Command builds the `{use}` command with all registered workload
// subcommands attached.
func Command() *cobra.Command {{
\tcmd := &cobra.Command{{
\t\tUse:   "{use}",
\t\tShort: "{short}",
\t}}

\tfor _, build := range subcommands {{
\t\tcmd.AddCommand(build())
\t}}

\treturn cmd
}}
'''
    return FileSpec(
        path=f"cmd/{root}/commands/{pkg}/{use}.go", content=content
    )


@compiled_render("companion_cli._init_sub")
def _init_sub(root: str, view: WorkloadView) -> FileSpec:
    """Per-workload `init` subcommand: prints the sample CR manifest
    (reference templates/cli/cmd_init_sub.go)."""
    name = _cmd_name(view)
    content = f'''package initcmd

import (
\t"fmt"

\t"github.com/spf13/cobra"

\t{view.package_name} "{view.resources_import}"
)

func init() {{
\tsubcommands = append(subcommands, new{view.kind}SubCommand)
}}

// new{view.kind}SubCommand prints a sample {view.kind} manifest.
func new{view.kind}SubCommand() *cobra.Command {{
\tvar requiredOnly bool

\tcmd := &cobra.Command{{
\t\tUse:   "{name}",
\t\tShort: "Print a sample {view.kind} manifest",
\t\tRunE: func(cmd *cobra.Command, args []string) error {{
\t\t\tfmt.Println({view.package_name}.Sample(requiredOnly))

\t\t\treturn nil
\t\t}},
\t}}

\tcmd.Flags().BoolVarP(
\t\t&requiredOnly, "required-only", "r", false,
\t\t"print only required fields",
\t)

\treturn cmd
}}
'''
    return FileSpec(
        path=f"cmd/{root}/commands/initcmd/"
        f"{to_file_name(view.group)}_{to_file_name(view.kind_lower)}.go",
        content=content,
    )


@compiled_render("companion_cli._generate_sub")
def _generate_sub(root: str, view: WorkloadView) -> FileSpec:
    """Per-workload `generate` subcommand: renders child resources from CR
    manifest files (reference templates/cli/cmd_generate_sub.go:49-332)."""
    name = _cmd_name(view)
    coll = view.collection
    is_component = view.is_component() and coll is not None

    if is_component:
        flags = '''\tcmd.Flags().StringVarP(
\t\t&workloadManifest, "workload-manifest", "w", "",
\t\t"path to the workload manifest file",
\t)
\t_ = cmd.MarkFlagRequired("workload-manifest")

\tcmd.Flags().StringVarP(
\t\t&collectionManifest, "collection-manifest", "c", "",
\t\t"path to the collection manifest file",
\t)
\t_ = cmd.MarkFlagRequired("collection-manifest")'''
        vars_decl = "\tvar workloadManifest, collectionManifest string"
        load = '''\t\t\tworkloadBytes, err := os.ReadFile(workloadManifest)
\t\t\tif err != nil {
\t\t\t\treturn fmt.Errorf("unable to read workload manifest: %w", err)
\t\t\t}

\t\t\tcollectionBytes, err := os.ReadFile(collectionManifest)
\t\t\tif err != nil {
\t\t\t\treturn fmt.Errorf("unable to read collection manifest: %w", err)
\t\t\t}
'''
        call = (
            f"{view.package_name}.GenerateForCLI(workloadBytes, "
            "collectionBytes)"
        )
    elif view.is_collection():
        flags = '''\tcmd.Flags().StringVarP(
\t\t&collectionManifest, "collection-manifest", "c", "",
\t\t"path to the collection manifest file",
\t)
\t_ = cmd.MarkFlagRequired("collection-manifest")'''
        vars_decl = "\tvar collectionManifest string"
        load = '''\t\t\tcollectionBytes, err := os.ReadFile(collectionManifest)
\t\t\tif err != nil {
\t\t\t\treturn fmt.Errorf("unable to read collection manifest: %w", err)
\t\t\t}
'''
        call = f"{view.package_name}.GenerateForCLI(collectionBytes)"
    else:
        flags = '''\tcmd.Flags().StringVarP(
\t\t&workloadManifest, "workload-manifest", "w", "",
\t\t"path to the workload manifest file",
\t)
\t_ = cmd.MarkFlagRequired("workload-manifest")'''
        vars_decl = "\tvar workloadManifest string"
        load = '''\t\t\tworkloadBytes, err := os.ReadFile(workloadManifest)
\t\t\tif err != nil {
\t\t\t\treturn fmt.Errorf("unable to read workload manifest: %w", err)
\t\t\t}
'''
        call = f"{view.package_name}.GenerateForCLI(workloadBytes)"

    content = f'''package generatecmd

import (
\t"fmt"
\t"os"

\t"github.com/spf13/cobra"
\t"sigs.k8s.io/yaml"

\t{view.package_name} "{view.resources_import}"
)

func init() {{
\tsubcommands = append(subcommands, new{view.kind}SubCommand)
}}

// new{view.kind}SubCommand renders the child resources of a {view.kind}.
func new{view.kind}SubCommand() *cobra.Command {{
{vars_decl}

\tcmd := &cobra.Command{{
\t\tUse:   "{name}",
\t\tShort: "{_cmd_description(view)}",
\t\tRunE: func(cmd *cobra.Command, args []string) error {{
{load}
\t\t\tresources, err := {call}
\t\t\tif err != nil {{
\t\t\t\treturn err
\t\t\t}}

\t\t\tfor _, resource := range resources {{
\t\t\t\tout, err := yaml.Marshal(resource)
\t\t\t\tif err != nil {{
\t\t\t\t\treturn fmt.Errorf("unable to marshal resource: %w", err)
\t\t\t\t}}

\t\t\t\tfmt.Println("---")
\t\t\t\tfmt.Print(string(out))
\t\t\t}}

\t\t\treturn nil
\t\t}},
\t}}

{flags}

\treturn cmd
}}
'''
    return FileSpec(
        path=f"cmd/{root}/commands/generatecmd/"
        f"{to_file_name(view.group)}_{to_file_name(view.kind_lower)}.go",
        content=content,
    )


@compiled_render("companion_cli._version_sub")
def _version_sub(root: str, view: WorkloadView) -> FileSpec:
    """Per-workload `version` subcommand
    (reference templates/cli/cmd_version_sub.go)."""
    name = _cmd_name(view)
    content = f'''package versioncmd

import (
\t"fmt"

\t"github.com/spf13/cobra"
)

func init() {{
\tsubcommands = append(subcommands, new{view.kind}SubCommand)
}}

// new{view.kind}SubCommand prints the CLI version and the supported API
// versions for {view.kind}.
func new{view.kind}SubCommand() *cobra.Command {{
\treturn &cobra.Command{{
\t\tUse:   "{name}",
\t\tShort: "Print version information for {view.kind}",
\t\tRunE: func(cmd *cobra.Command, args []string) error {{
\t\t\tfmt.Printf("CLI version: %s\\n", cliVersion)
\t\t\tfmt.Printf("supported API versions for {view.kind}: %v\\n",
\t\t\t\t[]string{{"{view.version}"}})

\t\t\treturn nil
\t\t}},
\t}}
}}
'''
    return FileSpec(
        path=f"cmd/{root}/commands/versioncmd/"
        f"{to_file_name(view.group)}_{to_file_name(view.kind_lower)}.go",
        content=content,
    )
