"""Controller templates: per-kind reconciler, phase wiring, and the envtest
suite test.

Reference: internal/plugins/workload/v1/scaffolds/templates/controller/
{controller,phases,controller_suitetest}.go.
"""

from __future__ import annotations

from ...utils import to_file_name
from ..context import WorkloadView
from ..machinery import FileSpec
from ..render import compiled_render


@compiled_render("controller.controller_file")
def controller_file(view: WorkloadView) -> FileSpec:
    kind = view.kind
    alias = view.api_import_alias
    pkg = view.package_name
    coll = view.collection
    is_component = view.is_component() and coll is not None

    rbac_markers = "\n".join(
        r.to_marker() for r in view.workload.get_rbac_rules()
    )
    child_rbac = []
    seen = set()
    for child in view.workload.get_manifests().all_child_resources():
        for rule in child.rbac or []:
            marker = rule.to_marker()
            if marker not in seen:
                seen.add(marker)
                child_rbac.append(marker)
    all_rbac = "\n".join([rbac_markers] + child_rbac)

    coll_import = ""
    if is_component and coll.api_types_import != view.api_types_import:
        coll_import = (
            f'\t{coll.api_import_alias} "{coll.api_types_import}"\n'
        )

    # -- NewRequest -----------------------------------------------------
    if is_component:
        new_request = f'''// NewRequest builds a reconciliation request, fetching the workload and its
// collection.  On ErrCollectionNotFound the partially-built request (with
// the workload set) is returned alongside the error so Reconcile can
// release a deleting workload whose collection is gone.
func (r *{kind}Reconciler) NewRequest(ctx context.Context, request ctrl.Request) (*orchestrate.Request, error) {{
\tworkload := &{alias}.{kind}{{}}

\tif err := r.Get(ctx, request.NamespacedName, workload); err != nil {{
\t\treturn nil, err
\t}}

\treq := &orchestrate.Request{{
\t\tContext:  ctx,
\t\tWorkload: workload,
\t\tLog:      r.Log.WithValues("{view.kind_lower}", request.NamespacedName),
\t}}

\tcollection, err := r.GetCollection(ctx, workload)
\tif err != nil {{
\t\treturn req, err
\t}}

\treq.Collection = collection

\treturn req, nil
}}

// GetCollection returns the collection for a component workload: the
// explicitly referenced collection when spec.collection is set, otherwise
// the single collection in the cluster (erroring unless exactly one exists).
func (r *{kind}Reconciler) GetCollection(
\tctx context.Context,
\tworkload *{alias}.{kind},
) (*{coll.api_import_alias}.{coll.kind}, error) {{
\tvar collectionList {coll.api_import_alias}.{coll.kind}List

\tname, namespace := workload.Spec.Collection.Name, workload.Spec.Collection.Namespace

\tif name != "" {{
\t\tcollection := &{coll.api_import_alias}.{coll.kind}{{}}

\t\tif err := r.Get(ctx, types.NamespacedName{{Name: name, Namespace: namespace}}, collection); err != nil {{
\t\t\tif apierrs.IsNotFound(err) {{
\t\t\t\treturn nil, orchestrate.ErrCollectionNotFound
\t\t\t}}

\t\t\treturn nil, err
\t\t}}

\t\treturn collection, nil
\t}}

\tif err := r.List(ctx, &collectionList); err != nil {{
\t\treturn nil, err
\t}}

\tif len(collectionList.Items) != 1 {{
\t\treturn nil, orchestrate.ErrCollectionNotFound
\t}}

\treturn &collectionList.Items[0], nil
}}
'''
        get_resources_convert = f'''\tworkload, collection, err := {pkg}.ConvertWorkload(req.Workload, req.Collection)
\tif err != nil {{
\t\treturn nil, err
\t}}

\tresources, err := {pkg}.Generate(*workload, *collection)'''
        mutate_call = f"mutate.{kind}Mutate(resource, workload, collection)"
        collection_watch = f'''
\t// watch the collection kind, update-only, enqueueing just the
\t// components the changed collection affects
\tif err := c.Watch(
\t\t&source.Kind{{Type: &{coll.api_import_alias}.{coll.kind}{{}}}},
\t\thandler.EnqueueRequestsFromMapFunc(r.requestsForCollection),
\t\torchestrate.CollectionPredicates(),
\t); err != nil {{
\t\treturn err
\t}}
'''
        requests_for_all = f'''
// requestsForCollection enqueues the components a collection change
// affects: those referencing it explicitly via spec.collection, and those
// with no explicit reference (they resolve the cluster's singleton
// collection, so any collection change may concern them).  This replaces
// the reference's per-request dynamic watch
// (EnqueueRequestOnCollectionChange, controller.go:286-340) with one
// static watch filtered per component — same targeting, without unbounded
// watch registration.
func (r *{kind}Reconciler) requestsForCollection(object client.Object) []reconcile.Request {{
\tvar list {alias}.{kind}List

\tif err := r.List(context.Background(), &list); err != nil {{
\t\tr.Log.Error(err, "unable to list {view.plural} for collection watch")

\t\treturn nil
\t}}

\trequests := []reconcile.Request{{}}

\tfor i := range list.Items {{
\t\tcomponent := &list.Items[i]

\t\tname := component.Spec.Collection.Name
\t\tnamespace := component.Spec.Collection.Namespace

\t\tif name != "" && name != object.GetName() {{
\t\t\tcontinue
\t\t}}

\t\tif name != "" && namespace != "" && namespace != object.GetNamespace() {{
\t\t\tcontinue
\t\t}}

\t\trequests = append(requests, reconcile.Request{{NamespacedName: types.NamespacedName{{
\t\t\tName:      component.GetName(),
\t\t\tNamespace: component.GetNamespace(),
\t\t}}}})
\t}}

\treturn requests
}}
'''
        collection_requeue = f'''\t\tif errors.Is(err, orchestrate.ErrCollectionNotFound) {{
\t\t\tif req != nil && req.Deleting() {{
\t\t\t\t// teardown needs only the static child-kind list and the
\t\t\t\t// owner annotation, not the collection: run the delete
\t\t\t\t// phases so children are torn down and the finalizer
\t\t\t\t// released instead of blocking deletion forever
\t\t\t\treturn r.Phases.HandleExecution(r, req)
\t\t\t}}

\t\t\treturn ctrl.Result{{Requeue: true}}, nil
\t\t}}

'''
        errors_import = '\t"errors"\n'
    else:
        new_request = f'''// NewRequest builds a reconciliation request for the workload.
func (r *{kind}Reconciler) NewRequest(ctx context.Context, request ctrl.Request) (*orchestrate.Request, error) {{
\tworkload := &{alias}.{kind}{{}}

\tif err := r.Get(ctx, request.NamespacedName, workload); err != nil {{
\t\treturn nil, err
\t}}

\treturn &orchestrate.Request{{
\t\tContext:  ctx,
\t\tWorkload: workload,
\t\tLog:      r.Log.WithValues("{view.kind_lower}", request.NamespacedName),
\t}}, nil
}}
'''
        get_resources_convert = f'''\tworkload, err := {pkg}.ConvertWorkload(req.Workload)
\tif err != nil {{
\t\treturn nil, err
\t}}

\tresources, err := {pkg}.Generate(*workload)'''
        mutate_call = f"mutate.{kind}Mutate(resource, workload)"
        collection_watch = ""
        requests_for_all = ""
        collection_requeue = ""
        errors_import = ""

    component_only_imports = ""
    if is_component:
        component_only_imports = (
            '\t"k8s.io/apimachinery/pkg/types"\n'
        )
    reconcile_pkg_import = (
        '\t"sigs.k8s.io/controller-runtime/pkg/reconcile"\n'
        if is_component
        else ""
    )
    reconcile_imports = (
        '\t"context"\n'
        f"{errors_import}\n"
        '\tapierrs "k8s.io/apimachinery/pkg/api/errors"\n'
        '\t"k8s.io/apimachinery/pkg/apis/meta/v1/unstructured"\n'
        '\t"k8s.io/apimachinery/pkg/runtime"\n'
        '\t"k8s.io/apimachinery/pkg/runtime/schema"\n'
        f"{component_only_imports}"
        '\t"k8s.io/client-go/tools/record"\n'
        '\tctrl "sigs.k8s.io/controller-runtime"\n'
        '\t"sigs.k8s.io/controller-runtime/pkg/client"\n'
        '\t"sigs.k8s.io/controller-runtime/pkg/controller"\n'
        '\t"sigs.k8s.io/controller-runtime/pkg/handler"\n'
        f"{reconcile_pkg_import}"
        '\t"sigs.k8s.io/controller-runtime/pkg/source"\n\n'
        '\t"github.com/go-logr/logr"\n\n'
        f'\t"{view.config.repo}/internal/dependencies"\n'
        f'\t"{view.config.repo}/internal/mutate"\n'
        f'\t"{view.config.repo}/pkg/orchestrate"\n\n'
        f'\t{alias} "{view.api_types_import}"\n'
        f'\t{pkg} "{view.resources_import}"\n'
        f"{coll_import}"
    )

    content = f'''package {view.group}

import (
{reconcile_imports})

// {kind}Reconciler reconciles a {kind} object.
type {kind}Reconciler struct {{
\tclient.Client

\tName         string
\tLog          logr.Logger
\tController   controller.Controller
\tEvents       record.EventRecorder
\tFieldManager string
\tScheme       *runtime.Scheme
\tPhases       *orchestrate.Registry

\twatches map[string]bool
}}

// New{kind}Reconciler returns a configured reconciler for the {kind} kind.
func New{kind}Reconciler(mgr ctrl.Manager) *{kind}Reconciler {{
\treconciler := &{kind}Reconciler{{
\t\tName:         "{kind}",
\t\tClient:       mgr.GetClient(),
\t\tEvents:       mgr.GetEventRecorderFor("{kind}-Controller"),
\t\tFieldManager: "{view.kind_lower}-reconciler",
\t\tLog:          ctrl.Log.WithName("controllers").WithName("{view.group}").WithName("{kind}"),
\t\tScheme:       mgr.GetScheme(),
\t\tPhases:       &orchestrate.Registry{{}},
\t\twatches:      map[string]bool{{}},
\t}}

\torchestrate.RegisterDefaultPhases(reconciler.Phases)

\treturn reconciler
}}

{all_rbac}

// Namespaces are listed and watched to ensure they exist before resources
// are deployed into them.
// +kubebuilder:rbac:groups=core,resources=namespaces,verbs=list;watch

// Reconcile moves the current state of the cluster closer to the desired
// state through the registered phase state machine.
func (r *{kind}Reconciler) Reconcile(ctx context.Context, request ctrl.Request) (ctrl.Result, error) {{
\treq, err := r.NewRequest(ctx, request)
\tif err != nil {{
{collection_requeue}\t\tif !apierrs.IsNotFound(err) {{
\t\t\treturn ctrl.Result{{}}, err
\t\t}}

\t\treturn ctrl.Result{{}}, nil
\t}}

\treturn r.Phases.HandleExecution(r, req)
}}

{new_request}
// GetResources renders this workload's child resources, running each through
// the user mutation hook.
func (r *{kind}Reconciler) GetResources(req *orchestrate.Request) ([]client.Object, error) {{
{get_resources_convert}
\tif err != nil {{
\t\treturn nil, err
\t}}

\tmutated := []client.Object{{}}

\tfor _, resource := range resources {{
\t\tresults, err := {mutate_call}
\t\tif err != nil {{
\t\t\treturn nil, err
\t\t}}

\t\tmutated = append(mutated, results...)
\t}}

\treturn mutated, nil
}}

// CheckDependencies runs the user-owned dependency hook.
func (r *{kind}Reconciler) CheckDependencies(req *orchestrate.Request) (bool, error) {{
\treturn dependencies.{kind}CheckReady(r, req)
}}

// GetChildGVKs returns the static set of child resource kinds this
// workload can create, fixed at code generation.  Teardown sweeps these
// kinds for owner-annotated children, so deletion never depends on a
// successful render.
func (r *{kind}Reconciler) GetChildGVKs() []schema.GroupVersionKind {{
\treturn {pkg}.ChildResourceGVKs
}}

// EnsureWatch begins watching a child resource kind exactly once so drift on
// child resources re-triggers reconciliation.
func (r *{kind}Reconciler) EnsureWatch(req *orchestrate.Request, resource client.Object) error {{
\tif r.Controller == nil {{
\t\treturn nil
\t}}

\tgvk := resource.GetObjectKind().GroupVersionKind()

\tkey := gvk.String()
\tif r.watches[key] {{
\t\treturn nil
\t}}

\twatched := &unstructured.Unstructured{{}}
\twatched.SetGroupVersionKind(gvk)

\tif err := r.Controller.Watch(
\t\t&source.Kind{{Type: watched}},
\t\t&handler.EnqueueRequestForOwner{{OwnerType: &{alias}.{kind}{{}}, IsController: true}},
\t); err != nil {{
\t\treturn err
\t}}

\tr.watches[key] = true

\treturn nil
}}

// GetLogger returns the reconciler's logger.
func (r *{kind}Reconciler) GetLogger() logr.Logger {{
\treturn r.Log
}}

// GetEventRecorder returns the reconciler's event recorder.
func (r *{kind}Reconciler) GetEventRecorder() record.EventRecorder {{
\treturn r.Events
}}

// GetFieldManager returns the server-side-apply field manager name.
func (r *{kind}Reconciler) GetFieldManager() string {{
\treturn r.FieldManager
}}

// GetScheme returns the runtime scheme.
func (r *{kind}Reconciler) GetScheme() *runtime.Scheme {{
\treturn r.Scheme
}}
{requests_for_all}
// SetupWithManager registers the reconciler with the manager.  The event
// filter skips status-only updates on the primary workload so the
// controller's own status writes do not re-trigger reconciliation
// (reference controller.go:426-440).
func (r *{kind}Reconciler) SetupWithManager(mgr ctrl.Manager) error {{
\tc, err := ctrl.NewControllerManagedBy(mgr).
\t\tWithEventFilter(orchestrate.WorkloadPredicates()).
\t\tFor(&{alias}.{kind}{{}}).
\t\tBuild(r)
\tif err != nil {{
\t\treturn err
\t}}

\tr.Controller = c
{collection_watch}
\treturn nil
}}
'''
    return FileSpec(path=view.controller_file, content=content)


@compiled_render("controller.reconcile_test_file")
def reconcile_test_file(view: WorkloadView) -> FileSpec:
    """A real envtest case per kind: create the sample CR and require the
    reconciler to register its finalizer, run its create phases, and record
    phase conditions.  Goes beyond the reference, whose scaffolded suite
    test is harness-only (templates/controller/controller_suitetest.go)."""
    kind = view.kind
    alias = view.api_import_alias
    pkg = view.package_name
    coll = view.collection
    is_component = view.is_component() and coll is not None

    collection_setup = ""
    extra_imports = ""
    apierrs_import = '\tapierrs "k8s.io/apimachinery/pkg/api/errors"\n'
    if is_component:
        if coll.api_types_import != view.api_types_import:
            extra_imports += (
                f'\t{coll.api_import_alias} "{coll.api_types_import}"\n'
            )
        extra_imports += f'\t{coll.package_name} "{coll.resources_import}"\n'
        coll_ns_default = ""
        if not coll.workload.is_cluster_scoped():
            coll_ns_default = '''
\tif collection.GetNamespace() == "" {
\t\tcollection.SetNamespace("default")
\t}
'''
        collection_setup = f'''\t// components resolve their collection before rendering; create it
\t// first (tolerating an earlier test of this group having done so)
\tif err := {coll.api_import_alias}.AddToScheme(scheme.Scheme); err != nil {{
\t\tt.Fatalf("unable to register collection scheme: %v", err)
\t}}

\tcollection := &{coll.api_import_alias}.{coll.kind}{{}}
\tif err := sigsyaml.Unmarshal([]byte({coll.package_name}.Sample(false)), collection); err != nil {{
\t\tt.Fatalf("unable to decode collection sample: %v", err)
\t}}
{coll_ns_default}
\tif err := k8sClient.Create(ctx, collection); err != nil && !apierrs.IsAlreadyExists(err) {{
\t\tt.Fatalf("unable to create collection: %v", err)
\t}}

'''

    ns_default = ""
    if not view.workload.is_cluster_scoped():
        ns_default = '''
\tif workload.GetNamespace() == "" {
\t\tworkload.SetNamespace("default")
\t}
'''

    content = f'''package {view.group}

import (
\t"context"
\t"testing"
\t"time"

{apierrs_import}\t"k8s.io/client-go/kubernetes/scheme"
\tctrl "sigs.k8s.io/controller-runtime"
\t"sigs.k8s.io/controller-runtime/pkg/client"
\tsigsyaml "sigs.k8s.io/yaml"

\t{alias} "{view.api_types_import}"
\t{pkg} "{view.resources_import}"
{extra_imports})

// Test{kind}Reconcile drives the {kind} reconciler against envtest: the
// sample CR is created and the reconciler must register its teardown
// finalizer, run its create phases, and record phase conditions.  Child
// readiness (and therefore status.created) is deliberately not asserted:
// envtest runs no workload controllers, so children such as Deployments
// never report ready.
func Test{kind}Reconcile(t *testing.T) {{
\tctx, cancel := context.WithCancel(context.Background())
\tdefer cancel()

\tmgr, err := ctrl.NewManager(cfg, ctrl.Options{{
\t\tScheme:             scheme.Scheme,
\t\tMetricsBindAddress: "0",
\t}})
\tif err != nil {{
\t\tt.Fatalf("unable to create manager: %v", err)
\t}}

\tif err := New{kind}Reconciler(mgr).SetupWithManager(mgr); err != nil {{
\t\tt.Fatalf("unable to set up reconciler: %v", err)
\t}}

\tgo func() {{
\t\t_ = mgr.Start(ctx)
\t}}()

{collection_setup}\tworkload := &{alias}.{kind}{{}}
\tif err := sigsyaml.Unmarshal([]byte({pkg}.Sample(false)), workload); err != nil {{
\t\tt.Fatalf("unable to decode sample: %v", err)
\t}}
{ns_default}
\t// tolerate an earlier test of this suite having created the same
\t// object: a collection kind's sample is pre-created by its
\t// components' tests (see the collection setup above)
\tif err := k8sClient.Create(ctx, workload); err != nil && !apierrs.IsAlreadyExists(err) {{
\t\tt.Fatalf("unable to create workload: %v", err)
\t}}

\tdeadline := time.Now().Add(90 * time.Second)

\tfor {{
\t\tlive := &{alias}.{kind}{{}}

\t\terr := k8sClient.Get(ctx, client.ObjectKeyFromObject(workload), live)
\t\tif err == nil && len(live.GetFinalizers()) > 0 && len(live.Status.Conditions) > 0 {{
\t\t\tbreak
\t\t}}

\t\tif time.Now().After(deadline) {{
\t\t\tt.Fatalf("timed out waiting for the reconciler to act (last get error: %v)", err)
\t\t}}

\t\ttime.Sleep(250 * time.Millisecond)
\t}}
}}
'''
    return FileSpec(
        path=f"controllers/{view.group}/"
        f"{to_file_name(view.kind_lower)}_controller_test.go",
        content=content,
    )


@compiled_render("controller.suite_test_file")
def suite_test_file(view: WorkloadView, kinds_in_group: list[str]) -> FileSpec:
    """Envtest-based suite test per controller group
    (reference templates/controller/controller_suitetest.go:31-171)."""
    content = f'''package {view.group}

import (
\t"os"
\t"path/filepath"
\t"testing"

\t"k8s.io/client-go/kubernetes/scheme"
\t"k8s.io/client-go/rest"
\tctrl "sigs.k8s.io/controller-runtime"
\t"sigs.k8s.io/controller-runtime/pkg/client"
\t"sigs.k8s.io/controller-runtime/pkg/envtest"
\tlogf "sigs.k8s.io/controller-runtime/pkg/log"
\t"sigs.k8s.io/controller-runtime/pkg/log/zap"

\t{view.api_import_alias} "{view.api_types_import}"
)

// These tests use envtest: a real API server and etcd without nodes.
// Run them with `make test`.

var (
\tcfg       *rest.Config
\tk8sClient client.Client
\ttestEnv   *envtest.Environment
)

func TestMain(m *testing.M) {{
\tlogf.SetLogger(zap.New(zap.UseDevMode(true)))

\ttestEnv = &envtest.Environment{{
\t\tCRDDirectoryPaths:     []string{{filepath.Join("..", "..", "config", "crd", "bases")}},
\t\tErrorIfCRDPathMissing: true,
\t}}

\tvar err error

\tcfg, err = testEnv.Start()
\tif err != nil || cfg == nil {{
\t\tpanic("unable to start test environment: " + errString(err))
\t}}

\tif err := {view.api_import_alias}.AddToScheme(scheme.Scheme); err != nil {{
\t\tpanic("unable to register scheme: " + err.Error())
\t}}

\tk8sClient, err = client.New(cfg, client.Options{{Scheme: scheme.Scheme}})
\tif err != nil {{
\t\tpanic("unable to create client: " + err.Error())
\t}}

\tcode := m.Run()

\tif err := testEnv.Stop(); err != nil {{
\t\tpanic("unable to stop test environment: " + err.Error())
\t}}

\tos.Exit(code)
}}

func errString(err error) string {{
\tif err == nil {{
\t\treturn "unknown error"
\t}}

\treturn err.Error()
}}

var _ = ctrl.Log
'''
    return FileSpec(
        path=f"controllers/{view.group}/suite_test.go", content=content
    )
