"""Scaffolding machinery: file writing with if-exists policies and
marker-based fragment insertion.

Equivalent of the kubebuilder ``machinery`` package the reference relies on
(Template execution with IfExistsAction, and Inserter templates targeting
``+kubebuilder:scaffold:*``-style markers; see SURVEY.md §2.2).  Markers in
generated files look like::

    // +operator-builder:scaffold:imports

Fragments are inserted immediately above their marker, each exactly once
(re-scaffolding is idempotent).
"""

from __future__ import annotations

import enum
import os
from dataclasses import dataclass, field as dc_field
from typing import Optional

MARKER_PREFIX = "+operator-builder:scaffold:"


class ScaffoldError(Exception):
    pass


class IfExists(enum.Enum):
    """What to do when the target file already exists.

    Mirrors kubebuilder machinery's IfExistsAction: user-owned hook files are
    SKIP so regeneration never clobbers user edits (e.g. the reference's
    mutate/dependencies templates, templates/int/mutate/component.go:34)."""

    OVERWRITE = "overwrite"
    SKIP = "skip"
    ERROR = "error"


@dataclass
class FileSpec:
    path: str  # relative to the project root
    content: str
    if_exists: IfExists = IfExists.OVERWRITE
    # .go files get the boilerplate header prepended unless they provide one
    add_boilerplate: bool = True


@dataclass
class Fragment:
    """A code fragment inserted at a named marker inside an existing file."""

    path: str
    marker: str  # marker name, e.g. "imports"
    code: str


def marker_line(marker: str, comment_prefix: str = "//") -> str:
    return f"{comment_prefix} {MARKER_PREFIX}{marker}"


@dataclass
class Scaffold:
    """Executes file specs + fragments into an output directory."""

    output_dir: str
    boilerplate: str = ""
    written: list[str] = dc_field(default_factory=list)
    skipped: list[str] = dc_field(default_factory=list)
    # dry-run mode: classify without touching disk; see `changes`
    dry_run: bool = False
    # (action, path) pairs: create / overwrite / unchanged / preserve /
    # fragment — populated in dry-run mode only
    changes: list = dc_field(default_factory=list)

    def execute(
        self,
        specs: list[FileSpec],
        fragments: Optional[list[Fragment]] = None,
    ) -> None:
        for spec in specs:
            self._write(spec)
        for fragment in fragments or []:
            self._insert(fragment)

    # -- files ----------------------------------------------------------

    def _write(self, spec: FileSpec) -> None:
        target = os.path.join(self.output_dir, spec.path)
        exists = os.path.exists(target)
        if exists:
            if spec.if_exists == IfExists.SKIP:
                self.skipped.append(spec.path)
                if self.dry_run:
                    self.changes.append(("preserve", spec.path))
                return
            if spec.if_exists == IfExists.ERROR:
                raise ScaffoldError(f"file already exists: {spec.path}")
        content = spec.content
        if (
            spec.add_boilerplate
            and self.boilerplate
            and spec.path.endswith(".go")
            and not content.startswith(self.boilerplate)
        ):
            content = self.boilerplate.rstrip("\n") + "\n\n" + content
        if not content.endswith("\n"):
            content += "\n"
        if self.dry_run:
            if not exists:
                self.changes.append(("create", spec.path))
            else:
                with open(target, "r", encoding="utf-8") as handle:
                    current = handle.read()
                self.changes.append(
                    ("unchanged" if current == content else "overwrite", spec.path)
                )
            self.written.append(spec.path)
            return
        os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
        with open(target, "w", encoding="utf-8") as handle:
            handle.write(content)
        self.written.append(spec.path)

    # -- fragments ------------------------------------------------------

    @staticmethod
    def _fragment_present(lines: list[str], code: str) -> bool:
        """Idempotency: the fragment is already inserted when every
        non-blank fragment line appears in the file."""
        fragment_lines = [l for l in code.rstrip("\n").split("\n") if l.strip()]
        return bool(fragment_lines) and all(
            any(l.strip() == existing.strip() for existing in lines)
            for l in fragment_lines
        )

    def _find_marker(self, lines: list[str], fragment: Fragment) -> int | None:
        needle = MARKER_PREFIX + fragment.marker
        for i, line in enumerate(lines):
            if needle in line and line.lstrip().startswith(("//", "#")):
                return i
        return None

    def _insert(self, fragment: Fragment) -> None:
        target = os.path.join(self.output_dir, fragment.path)
        if self.dry_run:
            # a target pending creation in this same run can't be
            # evaluated against disk; anything else gets the real run's
            # error checks so the dry run predicts failures too
            if fragment.path in self.written:
                self.changes.append(("fragment", fragment.path))
                return
            if not os.path.exists(target):
                raise ScaffoldError(
                    f"cannot insert at marker {fragment.marker!r}: file "
                    f"{fragment.path} does not exist"
                )
            with open(target, "r", encoding="utf-8") as handle:
                existing_lines = handle.read().split("\n")
            if self._find_marker(existing_lines, fragment) is None:
                raise ScaffoldError(
                    f"marker {fragment.marker!r} not found in {fragment.path}"
                )
            if not self._fragment_present(existing_lines, fragment.code):
                self.changes.append(("fragment", fragment.path))
            return
        if not os.path.exists(target):
            raise ScaffoldError(
                f"cannot insert at marker {fragment.marker!r}: file "
                f"{fragment.path} does not exist"
            )
        with open(target, "r", encoding="utf-8") as handle:
            content = handle.read()

        lines = content.split("\n")
        marker_idx = self._find_marker(lines, fragment)
        if marker_idx is None:
            raise ScaffoldError(
                f"marker {fragment.marker!r} not found in {fragment.path}"
            )

        code = fragment.code.rstrip("\n")
        if self._fragment_present(lines, code):
            return

        indent = lines[marker_idx][: len(lines[marker_idx]) - len(lines[marker_idx].lstrip())]
        inserted = [indent + l if l.strip() else l for l in code.split("\n")]
        lines[marker_idx:marker_idx] = inserted
        with open(target, "w", encoding="utf-8") as handle:
            handle.write("\n".join(lines))
