"""Scaffolding machinery: file writing with if-exists policies and
marker-based fragment insertion.

Equivalent of the kubebuilder ``machinery`` package the reference relies on
(Template execution with IfExistsAction, and Inserter templates targeting
``+kubebuilder:scaffold:*``-style markers; see SURVEY.md §2.2).  Markers in
generated files look like::

    // +operator-builder:scaffold:imports

Fragments are inserted immediately above their marker, each exactly once
(re-scaffolding is idempotent).
"""

from __future__ import annotations

import enum
import os
import re
import threading
from dataclasses import dataclass, field as dc_field
from typing import Optional

from ..perf import parallel_map, spans

MARKER_PREFIX = "+operator-builder:scaffold:"

#: directories already swept for stale publish temps, once per process
#: (a per-publish glob would rescan a growing directory for every file
#: written — O(entries²) on the cold codegen path the <1% overhead
#: bars guard).  Unlocked on purpose: a racing double-sweep is two
#: harmless listdir/remove passes (ENOENT is swallowed), and after a
#: fork the inherited entries stay valid — the parent already swept
#: them.
_swept_dirs: set = set()
#: the suffix carries a tool-unique marker on purpose: the sweeper may
#: only ever match its OWN litter — a bare ``.tmp-<pid>-<tid>`` would
#: also match (and delete) a user's unrelated file that happens to fit
#: the pattern in a tree the scaffold publishes into
_TMP_MARKER = ".operator-forge-tmp"
_STALE_TMP = re.compile(re.escape(_TMP_MARKER) + r"-(\d+)-\d+$")


def _pid_alive(pid: int) -> bool:
    """Whether ``pid`` is a live process.  ``EPERM`` means alive but
    owned by someone else; only a definite ``ProcessLookupError`` (or
    an impossible pid) reads as dead."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True
    return True


def _sweep_stale_temps(directory: str) -> None:
    """Remove write-sideways temps a hard-killed attempt left behind
    (they never reached their ``os.replace``).  Only temps from OTHER,
    DEAD pids are stale: parallel per-file writes publish siblings into
    the same directory concurrently, so a same-pid temp is in-flight by
    definition (thread death without process death runs _publish's
    cleanup path), and an other-pid temp whose writer is still running
    (two terminals publishing into one tree, a detached serve handler)
    is in-flight too — removing it would fail that process's
    ``os.replace``.  Pid recycling can make true litter look alive;
    that litter just waits for a later sweep, which is fine — temps are
    never adopted (SKIP policies check the target path and publishes
    are atomic), so one sweep on first contact with each directory is
    enough, and litter from THIS process dying lands in the next
    process's first sweep."""
    if directory in _swept_dirs:
        return
    try:
        entries = os.listdir(directory)
    except OSError:
        # not created yet (or transiently unlistable): nothing swept,
        # so don't latch — the next publish retries the listing
        return
    _swept_dirs.add(directory)
    own_pid = str(os.getpid())
    for name in entries:
        match = _STALE_TMP.search(name)
        if (
            match
            and match.group(1) != own_pid
            and not _pid_alive(int(match.group(1)))
        ):
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass


def _publish(target: str, content: str) -> None:
    """Atomically publish ``content`` at ``target``: write sideways,
    then rename.  A write interrupted mid-stream (a crashed worker, a
    pool teardown killing its siblings, a hard process kill) must never
    leave a torn file behind — a preserve-on-exists policy or a
    crash-retried batch group would adopt it, breaking the recovery
    byte-identity contract."""
    if "\x00" in content:
        # generated text never contains NUL; one slipping through means
        # a render-lowering sentinel escaped a probe render — fail the
        # write loudly instead of publishing corrupt output
        raise ScaffoldError(
            f"NUL byte in generated content for {target}: "
            "render-lowering sentinel leaked into a production render"
        )
    _sweep_stale_temps(os.path.dirname(target) or ".")
    tmp = f"{target}{_TMP_MARKER}-{os.getpid()}-{threading.get_ident()}"
    try:
        with open(tmp, "w", encoding="utf-8") as handle:
            handle.write(content)
        os.replace(tmp, target)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


class ScaffoldError(Exception):
    pass


class IfExists(enum.Enum):
    """What to do when the target file already exists.

    Mirrors kubebuilder machinery's IfExistsAction: user-owned hook files are
    SKIP so regeneration never clobbers user edits (e.g. the reference's
    mutate/dependencies templates, templates/int/mutate/component.go:34)."""

    OVERWRITE = "overwrite"
    SKIP = "skip"
    ERROR = "error"


@dataclass
class FileSpec:
    path: str  # relative to the project root
    content: str
    if_exists: IfExists = IfExists.OVERWRITE
    # .go files get the boilerplate header prepended unless they provide one
    add_boilerplate: bool = True


@dataclass
class Fragment:
    """A code fragment inserted at a named marker inside an existing file."""

    path: str
    marker: str  # marker name, e.g. "imports"
    code: str


def marker_line(marker: str, comment_prefix: str = "//") -> str:
    return f"{comment_prefix} {MARKER_PREFIX}{marker}"


@dataclass
class Scaffold:
    """Executes file specs + fragments into an output directory."""

    output_dir: str
    boilerplate: str = ""
    written: list[str] = dc_field(default_factory=list)
    skipped: list[str] = dc_field(default_factory=list)
    # dry-run mode: classify without touching disk; see `changes`
    dry_run: bool = False
    # (action, path) pairs: create / overwrite / unchanged / preserve /
    # fragment — populated in dry-run mode only
    changes: list = dc_field(default_factory=list)
    # the last executed plan, retained so callers (the pipeline cache)
    # can persist exactly what this scaffold would replay
    specs: list = dc_field(default_factory=list)
    fragments: list = dc_field(default_factory=list)
    # directories already created this scaffold — os.makedirs walks and
    # stats every path component, which dominates write time on slow
    # filesystems when repeated per file
    _made_dirs: set = dc_field(default_factory=set, repr=False)

    def execute(
        self,
        specs: list[FileSpec],
        fragments: Optional[list[Fragment]] = None,
    ) -> None:
        from . import render

        specs = list(specs)
        fragments = list(fragments or [])
        self.specs = specs
        self.fragments = fragments
        with spans.span("write"):
            paths = [spec.path for spec in specs]
            if self.dry_run or len(set(paths)) < len(paths):
                # duplicate paths are order-dependent (a later spec must
                # observe the earlier write), and dry runs are pure
                # bookkeeping — both take the serial path
                outcomes = [self._write_one(spec) for spec in specs]
            else:
                # unique targets are independent: render+write in a
                # thread pool, collect outcomes in spec order so the
                # written/skipped/changes lists are deterministic
                outcomes = parallel_map(self._write_one, specs)
            for outcome in outcomes:
                self._record(outcome)
        with spans.span("fragment"):
            if fragments and not self.dry_run and render.mode() != "ref":
                self._insert_fused(fragments)
            else:
                # the pinned reference path: one read → splice →
                # publish per fragment (and the dry-run classifier)
                for fragment in fragments:
                    self._insert(fragment)
        # persist freshly lowered render programs while the process is
        # still alive — pool workers and later cold processes hydrate
        # from these manifests instead of re-lowering (the same
        # mid-process flush point gocheck uses after a suite run);
        # no-op when nothing new was lowered or the cache is off
        render.flush_lowered()

    # -- files ----------------------------------------------------------

    def _ensure_dir(self, directory: str) -> None:
        if not directory or directory in self._made_dirs:
            return
        os.makedirs(directory, exist_ok=True)
        # set mutation is atomic under the GIL and a duplicate makedirs
        # (exist_ok) is harmless, so no lock is needed for worker threads
        self._made_dirs.add(directory)

    def _write_one(self, spec: FileSpec) -> tuple:
        """Write (or classify, in dry-run) one spec; returns a
        ``(status, path, change-or-None)`` outcome and touches no shared
        state, so it is safe to run on a worker thread."""
        target = os.path.join(self.output_dir, spec.path)
        exists = os.path.exists(target)
        if exists:
            if spec.if_exists == IfExists.SKIP:
                change = ("preserve", spec.path) if self.dry_run else None
                return ("skipped", spec.path, change)
            if spec.if_exists == IfExists.ERROR:
                raise ScaffoldError(f"file already exists: {spec.path}")
        content = spec.content
        if (
            spec.add_boilerplate
            and self.boilerplate
            and spec.path.endswith(".go")
            and not content.startswith(self.boilerplate)
        ):
            content = self.boilerplate.rstrip("\n") + "\n\n" + content
        if not content.endswith("\n"):
            content += "\n"
        if self.dry_run:
            if not exists:
                change = ("create", spec.path)
            else:
                with open(target, "r", encoding="utf-8") as handle:
                    current = handle.read()
                change = (
                    "unchanged" if current == content else "overwrite",
                    spec.path,
                )
            return ("written", spec.path, change)
        if exists:
            # incremental re-scaffold: leave byte-identical targets
            # untouched (a read costs less than a rewrite, and an
            # unchanged tree is the common warm-cache case).  Compared
            # as bytes: text mode would normalize CRLF and miss a
            # mangled file that needs restoring.
            try:
                with open(target, "rb") as handle:
                    if handle.read() == content.encode("utf-8"):
                        return ("written", spec.path, None)
            except OSError:
                pass
        else:
            self._ensure_dir(os.path.dirname(target))
        _publish(target, content)
        return ("written", spec.path, None)

    def _record(self, outcome: tuple) -> None:
        status, path, change = outcome
        if status == "skipped":
            self.skipped.append(path)
        else:
            self.written.append(path)
        if change is not None:
            self.changes.append(change)

    # -- fragments ------------------------------------------------------

    @staticmethod
    def _fragment_present(lines: list[str], code: str) -> bool:
        """Idempotency: the fragment is already inserted when every
        non-blank fragment line appears in the file.  The file's
        stripped lines build ONE set (a per-fragment-line linear scan
        was O(fragment_lines × file_lines) on every insert)."""
        fragment_lines = [l for l in code.rstrip("\n").split("\n") if l.strip()]
        if not fragment_lines:
            return False
        stripped = {existing.strip() for existing in lines}
        return all(l.strip() in stripped for l in fragment_lines)

    def _find_marker(self, lines: list[str], fragment: Fragment) -> int | None:
        needle = MARKER_PREFIX + fragment.marker
        for i, line in enumerate(lines):
            if needle in line and line.lstrip().startswith(("//", "#")):
                return i
        return None

    def _insert(self, fragment: Fragment) -> None:
        target = os.path.join(self.output_dir, fragment.path)
        if self.dry_run:
            # a target pending creation in this same run can't be
            # evaluated against disk; anything else gets the real run's
            # error checks so the dry run predicts failures too
            if fragment.path in self.written:
                self.changes.append(("fragment", fragment.path))
                return
            if not os.path.exists(target):
                raise ScaffoldError(
                    f"cannot insert at marker {fragment.marker!r}: file "
                    f"{fragment.path} does not exist"
                )
            with open(target, "r", encoding="utf-8") as handle:
                existing_lines = handle.read().split("\n")
            if self._find_marker(existing_lines, fragment) is None:
                raise ScaffoldError(
                    f"marker {fragment.marker!r} not found in {fragment.path}"
                )
            if not self._fragment_present(existing_lines, fragment.code):
                self.changes.append(("fragment", fragment.path))
            return
        if not os.path.exists(target):
            raise ScaffoldError(
                f"cannot insert at marker {fragment.marker!r}: file "
                f"{fragment.path} does not exist"
            )
        with open(target, "r", encoding="utf-8") as handle:
            content = handle.read()

        lines = content.split("\n")
        marker_idx = self._find_marker(lines, fragment)
        if marker_idx is None:
            raise ScaffoldError(
                f"marker {fragment.marker!r} not found in {fragment.path}"
            )

        code = fragment.code.rstrip("\n")
        if self._fragment_present(lines, code):
            return

        indent = lines[marker_idx][: len(lines[marker_idx]) - len(lines[marker_idx].lstrip())]
        inserted = [indent + l if l.strip() else l for l in code.split("\n")]
        lines[marker_idx:marker_idx] = inserted
        _publish(target, "\n".join(lines))

    def _insert_fused(self, fragments: list[Fragment]) -> None:
        """All fragments in one pass: each target file is read ONCE,
        every splice lands on the in-memory line list, and each dirty
        target publishes ONCE — where the serial reference re-reads,
        re-splits, and re-publishes the whole file per fragment.

        Byte-equivalent to the serial path by construction: fragments
        apply in list order against the same evolving file state the
        serial path would re-read (splices at one marker stack in
        order, later presence checks see earlier insertions), files
        never spring into or out of existence mid-loop (specs are all
        published before fragments run), and on the serial path's
        error points — missing target, missing marker — every splice
        a PRIOR fragment already made is published before the raise,
        exactly the state the per-fragment publisher leaves behind."""
        lines_by_target: dict[str, list[str]] = {}
        sets_by_target: dict[str, set[str]] = {}
        dirty: list[str] = []  # insertion-ordered dirty targets

        def flush_dirty() -> None:
            for path in dirty:
                _publish(
                    os.path.join(self.output_dir, path),
                    "\n".join(lines_by_target[path]),
                )

        for fragment in fragments:
            lines = lines_by_target.get(fragment.path)
            if lines is None:
                target = os.path.join(self.output_dir, fragment.path)
                if not os.path.exists(target):
                    flush_dirty()
                    raise ScaffoldError(
                        f"cannot insert at marker {fragment.marker!r}: "
                        f"file {fragment.path} does not exist"
                    )
                with open(target, "r", encoding="utf-8") as handle:
                    lines = handle.read().split("\n")
                lines_by_target[fragment.path] = lines
                sets_by_target[fragment.path] = {
                    l.strip() for l in lines
                }
            marker_idx = self._find_marker(lines, fragment)
            if marker_idx is None:
                flush_dirty()
                raise ScaffoldError(
                    f"marker {fragment.marker!r} not found in "
                    f"{fragment.path}"
                )
            code = fragment.code.rstrip("\n")
            fragment_lines = [
                l for l in code.split("\n") if l.strip()
            ]
            stripped = sets_by_target[fragment.path]
            if fragment_lines and all(
                l.strip() in stripped for l in fragment_lines
            ):
                continue
            marker_line_ = lines[marker_idx]
            indent = marker_line_[
                : len(marker_line_) - len(marker_line_.lstrip())
            ]
            inserted = [
                indent + l if l.strip() else l for l in code.split("\n")
            ]
            lines[marker_idx:marker_idx] = inserted
            stripped.update(l.strip() for l in inserted)
            if fragment.path not in dirty:
                dirty.append(fragment.path)
        flush_dirty()
