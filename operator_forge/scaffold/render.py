"""Compiled render programs: the second execution tier for codegen.

The template layer renders by composing Python f-strings over a
:class:`~operator_forge.scaffold.context.WorkloadView` — every render
re-walks the whole interpolation tree, re-evaluates every view property,
and re-branches every conditional block, even though a 40-component
monorepo renders the same template 40 times with only a handful of
context fields changing.  This module applies the PR 11 tiering
playbook (walk -> closures -> bytecode, ``gocheck/compiler.py``) to the
emission path: each template render is lowered ONCE per context shape
into a flat *render program* — precompiled segment concatenation over a
constant pool, where static text segments interleave with context-field
slot reads — and later renders with the same shape execute the program
instead of re-walking the f-string tree.

Lowering is record-and-replay with a sentinel probe:

1. the reference renderer runs with the real context (output ``O1``);
2. the template runs AGAIN against recording proxies whose string
   fields carry unique sentinel values.  Every branch-feeding operation
   (equality, ordering, truthiness, ``startswith``/``endswith``,
   membership) computes on the REAL values — so the probe follows the
   same branches as the reference render — and is recorded as a
   replayable *guard*; string fields flowing into the output carry
   their sentinels through (f-strings, ``join``, ``os.path.join``, and
   ``+`` all preserve the sentinel bytes);
3. the probe output is split on the sentinels into constant segments
   and slot reads (attribute paths, with pure derived transforms like
   ``.lower()`` encoded as replayable path steps); anything the probe
   cannot follow — slicing, ``split``, dict-keying a field, an
   unexpected exception — aborts lowering;
4. the program is executed against the real context and compared to
   ``O1`` byte-for-byte.  Any mismatch (an operation the proxies could
   not observe) permanently deopts the template.

A program hit requires every recorded guard to replay to the same
outcome against the new context, so a program never executes for a
context whose branch decisions could differ from the lowering context.
Templates outside the subset deopt PERMANENTLY to the reference
renderer (``render.deopt``) — the tier is an accelerator, never a
correctness risk: the standing contract (byte-identity to a cache-off
serial reference recompute across cache modes x workers x jobs) is
asserted by tests/test_render_programs.py and the bench identity guard.

Manifest transforms and the gocodegen document emitter lower through
:func:`lowered_blob` — their output is a pure function of the manifest
bytes, so the "program" is the pickled result keyed by content hash
(the pickle roundtrip returns fresh copies, the same ownership contract
``perf.cache.memoized`` gives).

Programs are picklable and persist in cache manifests under the
``render.lower`` namespace, exactly as ``gocheck/compiler.py`` persists
its bytecode in ``gocheck.lower``: cold processes and pool workers
hydrate *executable* programs on first use (``render.hydrated``)
instead of re-lowering.  The registry is process-level (a JIT code
cache), deliberately NOT cleared by ``perf.cache.reset()`` — programs
key on content shape, not cache state.  Counters surface in
``metrics.tier_report()``: ``render.lowered`` / ``render.hydrated`` /
``render.executed`` / ``render.deopt``.

``OPERATOR_FORGE_RENDER=ref|program`` selects the backend (default
``program``); ``ref`` pins the original renderer as the reference.
"""

from __future__ import annotations

import enum
import functools
import hashlib
import inspect
import keyword as _keyword
import os
import pickle
import threading
import itertools as _itertools
from dataclasses import dataclass
from itertools import islice as _islice

from ..perf import spans

_MODES = ("ref", "program")
DEFAULT_MODE = "program"

_forced = None


def mode() -> str:
    if _forced is not None:
        return _forced
    raw = os.environ.get("OPERATOR_FORGE_RENDER", DEFAULT_MODE)
    raw = raw.strip().lower()
    return raw if raw in _MODES else DEFAULT_MODE


def set_mode(value=None) -> None:
    """Programmatic override (``None`` restores env-driven selection)."""
    global _forced
    if value is not None and value not in _MODES:
        raise ValueError(f"unknown render mode {value!r}; known: {_MODES}")
    _forced = value


# -- program model --------------------------------------------------------
#
# An op list is a tuple whose elements are either an ``int`` (index into
# the constant pool) or a ``tuple`` (a slot: a context path).  A path is
# ``(arg_index, step, ...)`` where each step is a ``str`` (attribute
# read), an ``int`` (sequence index), or ``("@", name, *args)`` (a pure
# method call — ``.lower()``, a no-arg accessor, a const-arg
# ``.replace``).  Guards are replayable predicates over the same paths;
# a program's signature is the tuple of its guard outcomes at lowering
# time.


@dataclass(frozen=True)
class Program:
    """One lowered render: flat ops over a constant pool, plus the
    guard list + signature that scope which contexts may execute it.
    Pure data — pickles into ``render.lower`` manifests."""

    template_id: str
    pool: tuple          # constant-pool text segments
    guards: tuple        # replayable guard descriptors
    sig: tuple           # expected guard outcomes
    result: tuple        # result tree: ("s",ops) | ("f",...) | ("g",...) | ("L",(...))
    shape: str           # content hash of (guards, sig) — the registry key


class _OutOfSubset(Exception):
    """Internal: the probe hit an operation it cannot record/replay —
    the template (or this shape of it) stays on the reference path."""


# sentinel bytes can never appear in rendered text (templates emit
# UTF-8 Go/YAML/Make text, never NUL bytes), so a surviving "\x00" in a
# constant segment always means a MANGLED sentinel — lowering aborts
_SENTINEL = "\x00#%d#\x00"
import re as _re  # noqa: E402

_SENT_RE = _re.compile("\x00#(\\d+)#\x00")


# -- registry -------------------------------------------------------------

_lock = threading.Lock()
_programs: dict = {}      # template_id -> list[Program]
_blobs: dict = {}         # (template_id, digest) -> pickled bytes
_deopted: set = set()     # template ids pinned to the reference renderer
_dirty: set = set()       # template ids whose manifest needs persisting
_hydrated: set = set()    # template ids whose manifest was consulted
_runners: dict = {}       # (template_id, shape) -> compiled runner

# program hits tally lock-free on the hot path (a GIL-atomic list-cell
# bump, the same acceptable-race contract as gocheck's _reused_pending)
# and reconcile into the real ``render.executed`` counter at
# :func:`flush_counters` boundaries (tier reports, manifest flushes).
_executed_pending = [0]


def flush_counters() -> None:
    """Reconcile the lock-free execution tally into ``render.executed``."""
    from ..perf import metrics

    pending, _executed_pending[0] = _executed_pending[0], 0
    if pending:
        metrics.counter("render.executed").inc(pending)


def reset() -> None:
    """Test isolation: drop every program, blob, deopt pin, and
    hydration memo.  NOT wired into ``perf.cache.reset()`` on purpose —
    programs are keyed on content shape, not cache state, and survive
    cache resets exactly like the process's own compiled code."""
    with _lock:
        _programs.clear()
        _blobs.clear()
        _deopted.clear()
        _dirty.clear()
        _hydrated.clear()
        _runners.clear()
        _executed_pending[0] = 0


def deopted() -> frozenset:
    return frozenset(_deopted)


def _deopt(template_id: str) -> None:
    from ..perf import metrics

    with _lock:
        if template_id in _deopted:
            return
        _deopted.add(template_id)
        _programs.pop(template_id, None)
    metrics.counter("render.deopt").inc()


# -- recording proxies ----------------------------------------------------


# sentinel ids are allocated from ONE process-wide counter, never per
# session: a probe that outlives its session (a memoized helper cached
# it by string equality — _ProbeStr hashes and compares as its REAL
# value, so ``lru_cache`` keyed on a field value can capture and later
# return one) then carries a sid no other session will ever allocate,
# so its sentinel surfaces as "unknown" during lowering instead of
# silently aliasing another session's slot
_sid_counter = _itertools.count()

# the session currently recording a probe render on this thread (probe
# renders are per-template-first-call and never nest)
_active = threading.local()


def _active_session():
    return getattr(_active, "sess", None)


class _Session:
    """One lowering attempt: allocates sentinels, records guards, and
    caches object wrappers by identity so a real object reached through
    two paths wraps once (its first path is the replayed one)."""

    def __init__(self):
        self.guards: list = []
        self.sig: list = []
        self.slots: dict = {}      # sentinel id -> path
        self.wrappers: dict = {}   # id(real) -> wrapper
        self.pins: list = []       # keep reals alive so ids stay unique

    def check_live(self) -> bool:
        """True when this session is the one actively probing on this
        thread; False for a stale proxy surfacing in a PRODUCTION
        render (behave plainly, record nothing); raises when a stale
        proxy surfaces inside ANOTHER session's probe render — its
        paths are meaningless there and the lowering must abort."""
        active = _active_session()
        if active is self:
            return True
        if active is not None:
            raise _OutOfSubset("stale probe in a live probe render")
        return False

    def record(self, guard: tuple, outcome) -> None:
        if self.check_live():
            self.guards.append(guard)
            self.sig.append(outcome)

    def probe_str(self, real: str, path: tuple) -> "_ProbeStr":
        sid = next(_sid_counter)
        probe = _ProbeStr(_SENTINEL % sid)
        probe._real = real
        probe._path = path
        probe._sess = self
        self.slots[sid] = path
        return probe

    def classify(self, real, path: tuple, depth: int = 0):
        """Wrap ``real`` for the probe render: strings become sentinel
        probes (slots), scalars become value guards, sequences and
        objects become recording wrappers."""
        if not self.check_live():
            return real
        if type(real) is str:
            return self.probe_str(real, path)
        if real is None:
            self.record(("isnone", path), True)
            return None
        if isinstance(real, (bool, int, float, enum.Enum)):
            self.record(("val", path), real)
            return real
        if isinstance(real, (list, tuple)):
            self.record(("len", path), len(real))
            return _RecSeq(real, path, self)
        if isinstance(real, str):
            # a str SUBCLASS carries behavior the probe can't model
            raise _OutOfSubset(f"str subclass at {path!r}")
        if callable(real) and not isinstance(real, type):
            return _RecCall(real, path, self)
        if depth > 12:
            raise _OutOfSubset(f"wrap depth at {path!r}")
        wrapper = self.wrappers.get(id(real))
        if wrapper is None:
            self.record(("isnone", path), False)
            wrapper = _Rec(real, path, self)
            self.wrappers[id(real)] = wrapper
            self.pins.append(real)
        return wrapper


def _plain(value):
    """The real value behind a possibly-wrapped one, or raise."""
    if isinstance(value, _ProbeStr):
        return value._real
    if isinstance(value, (_Rec, _RecSeq, _RecCall)):
        raise _OutOfSubset("object-valued operand")
    return value


def _operand_key(value, sess):
    """How a guard references its right-hand operand: by path when it
    is a probe of the SAME session, by literal otherwise (a foreign
    session's paths mean nothing here — pin its real value instead)."""
    if isinstance(value, _ProbeStr):
        if value._sess is sess:
            return ("p", value._path)
        return ("l", value._real)
    if isinstance(value, (_Rec, _RecSeq, _RecCall)):
        raise _OutOfSubset("object-valued operand")
    if isinstance(value, tuple):
        return ("l", tuple(_plain(v) for v in value))
    return ("l", value)


class _ProbeStr(str):
    """A string field under probe: its buffer is the sentinel (so
    output flow is observable), its comparisons run on the REAL value
    (so branches match the reference render) and record guards."""

    _real: str
    _path: tuple
    _sess: "_Session"

    # -- recorded predicates (replayable guards) ----------------------

    def _cmp(self, op, other, fn):
        if isinstance(other, (_Rec, _RecSeq, _RecCall)):
            return NotImplemented
        if isinstance(other, _ProbeStr):
            out = fn(self._real, other._real)
            self._sess.record(
                (op, self._path, _operand_key(other, self._sess)), out
            )
            return out
        if isinstance(other, str):
            out = fn(self._real, other)
            self._sess.record((op, self._path, ("l", other)), out)
            return out
        return NotImplemented

    def __eq__(self, other):
        return self._cmp("eq", other, lambda a, b: a == b)

    def __ne__(self, other):
        out = self.__eq__(other)
        return NotImplemented if out is NotImplemented else not out

    def __lt__(self, other):
        return self._cmp("lt", other, lambda a, b: a < b)

    def __le__(self, other):
        return self._cmp("le", other, lambda a, b: a <= b)

    def __gt__(self, other):
        return self._cmp("gt", other, lambda a, b: a > b)

    def __ge__(self, other):
        return self._cmp("ge", other, lambda a, b: a >= b)

    def __hash__(self):
        # hash the REAL value: set/dict membership then lands in the
        # real value's bucket and resolves through __eq__, which
        # records — `alias in seen` over probe strings stays in-subset
        return hash(self._real)

    def __bool__(self):
        out = bool(self._real)
        self._sess.record(("truthy", self._path), out)
        return out

    def __contains__(self, item):
        key = _operand_key(item, self._sess)
        needle = item._real if isinstance(item, _ProbeStr) else item
        out = needle in self._real
        self._sess.record(("contains", self._path, key), out)
        return out

    def startswith(self, prefix, *extra):
        if extra:
            raise _OutOfSubset("startswith with bounds")
        key = _operand_key(prefix, self._sess)
        real_prefix = (
            prefix._real if isinstance(prefix, _ProbeStr) else prefix
        )
        out = self._real.startswith(real_prefix)
        self._sess.record(("sw", self._path, key), out)
        return out

    def endswith(self, suffix, *extra):
        if extra:
            raise _OutOfSubset("endswith with bounds")
        key = _operand_key(suffix, self._sess)
        real_suffix = (
            suffix._real if isinstance(suffix, _ProbeStr) else suffix
        )
        out = self._real.endswith(real_suffix)
        self._sess.record(("ew", self._path, key), out)
        return out

    # -- output flow ---------------------------------------------------

    def __format__(self, spec):
        if not self._sess.check_live():
            # stale probe in a production render: format the real value
            return format(self._real, spec)
        if not spec:
            return str.__str__(self)  # sentinel flows into the output
        # width/fill depends on the real length: fold the formatted
        # real into the signature and emit it as constant text
        out = format(self._real, spec)
        self._sess.record(
            ("val", self._path + (("@", "__format__", spec),)), out
        )
        return out

    def __str__(self):
        if not self._sess.check_live():
            return self._real
        return str.__str__(self)


def _derived(name):
    """Pure const-arg transforms stay slots: the result is a fresh
    probe whose path appends a replayable ``("@", name, *args)`` step."""

    def method(self, *args):
        plain_args = []
        for arg in args:
            if not isinstance(arg, (str, int)) or isinstance(
                arg, _ProbeStr
            ):
                raise _OutOfSubset(f"str.{name} argument")
            plain_args.append(arg)
        real = getattr(self._real, name)(*plain_args)
        if not self._sess.check_live():
            return real  # stale probe in production: plain result
        return self._sess.probe_str(
            real, self._path + (("@", name) + tuple(plain_args),)
        )

    return method


for _name in (
    "lower", "upper", "strip", "lstrip", "rstrip", "title",
    "capitalize", "casefold", "replace", "removeprefix", "removesuffix",
):
    setattr(_ProbeStr, _name, _derived(_name))


def _raising(name):
    def method(self, *args, **kwargs):
        raise _OutOfSubset(f"str.{name}")

    return method


for _name in (
    "split", "rsplit", "join", "format", "format_map", "encode",
    "zfill", "rjust", "ljust", "center", "find", "rfind", "index",
    "rindex", "count", "partition", "rpartition", "splitlines",
    "expandtabs", "translate", "swapcase", "__getitem__", "__iter__",
    "__mod__", "__rmod__", "__mul__", "__rmul__",
):
    setattr(_ProbeStr, _name, _raising(_name))
del _name


class _Rec:
    """Recording wrapper over one context object: every attribute read
    is classified (slot / guard / nested wrapper) under an extended
    path.  Properties evaluate on the REAL object, so derived values
    (``controller_file``, ``plural``) surface as single slots."""

    __slots__ = ("_real", "_path", "_sess")

    def __init__(self, real, path, sess):
        object.__setattr__(self, "_real", real)
        object.__setattr__(self, "_path", path)
        object.__setattr__(self, "_sess", sess)

    def __getattr__(self, name):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        real = getattr(self._real, name)
        return self._sess.classify(real, self._path + (name,))

    def __setattr__(self, name, value):
        if not self._sess.check_live():
            return setattr(self._real, name, value)
        raise _OutOfSubset("attribute write during probe")

    def __bool__(self):
        out = bool(self._real)
        self._sess.record(("truthy", self._path), out)
        return out

    def __iter__(self):
        # custom iterable containers (rbac.Rules): guard the item
        # count, classify items under iteration-index steps
        items = list(self._real)
        self._sess.record(("ilen", self._path), len(items))
        for i, value in enumerate(items):
            yield self._sess.classify(value, self._path + (("#", i),))


class _RecCall:
    """A bound method under probe: const-arg calls replay as path
    steps; wrapper-valued arguments are outside the subset."""

    __slots__ = ("_real", "_path", "_sess")

    def __init__(self, real, path, sess):
        self._real = real
        self._path = path
        self._sess = sess

    def __call__(self, *args, **kwargs):
        if not self._sess.check_live():
            return self._real(*args, **kwargs)
        if kwargs:
            raise _OutOfSubset("keyword call during probe")
        plain_args = []
        for arg in args:
            if isinstance(
                arg, (_ProbeStr, _Rec, _RecSeq, _RecCall)
            ) or not isinstance(
                arg, (str, int, float, bool, type(None))
            ):
                raise _OutOfSubset("call argument during probe")
            plain_args.append(arg)
        out = self._real(*plain_args)
        assert isinstance(self._path[-1], str)
        step = ("@", self._path[-1]) + tuple(plain_args)
        return self._sess.classify(out, self._path[:-1] + (step,))


class _RecSeq:
    """Recording wrapper over a list/tuple: length is guarded at wrap
    time; elements classify under indexed paths."""

    __slots__ = ("_real", "_path", "_sess")

    def __init__(self, real, path, sess):
        self._real = real
        self._path = path
        self._sess = sess

    def __len__(self):
        return len(self._real)

    def __bool__(self):
        return bool(self._real)

    def __iter__(self):
        for i, value in enumerate(self._real):
            yield self._sess.classify(value, self._path + (i,))

    def __getitem__(self, index):
        if not self._sess.check_live():
            return self._real[index]
        if not isinstance(index, int):
            raise _OutOfSubset("sequence slice during probe")
        if index < 0:
            index += len(self._real)
        return self._sess.classify(
            self._real[index], self._path + (index,)
        )

    def __contains__(self, item):
        key = _operand_key(item, self._sess)
        needle = item._real if isinstance(item, _ProbeStr) else item
        out = needle in self._real
        self._sess.record(("in", self._path, key), out)
        return out

    def __getattr__(self, name):
        # list SUBCLASSES carry domain methods (ManifestCollection's
        # all_child_resources); delegate like _Rec does
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        real = getattr(self._real, name)
        return self._sess.classify(real, self._path + (name,))


# -- path / guard replay --------------------------------------------------


def _resolve(args: tuple, path: tuple):
    cur = args[path[0]]
    for step in path[1:]:
        kind = type(step)
        if kind is str:
            cur = getattr(cur, step)
        elif kind is int:
            cur = cur[step]
        elif step[0] == "@":  # ("@", name, *const_args)
            cur = getattr(cur, step[1])(*step[2:])
        else:  # ("#", i) — i-th element of a custom iterable
            cur = next(_islice(iter(cur), step[1], None))
    return cur


def _operand(args: tuple, key: tuple):
    return _resolve(args, key[1]) if key[0] == "p" else key[1]


def _guard_outcome(args: tuple, guard: tuple):
    kind = guard[0]
    if kind == "val":
        return _resolve(args, guard[1])
    if kind == "eq":
        return _resolve(args, guard[1]) == _operand(args, guard[2])
    if kind == "truthy":
        return bool(_resolve(args, guard[1]))
    if kind == "isnone":
        return _resolve(args, guard[1]) is None
    if kind == "len":
        return len(_resolve(args, guard[1]))
    if kind == "ilen":
        return sum(1 for _ in iter(_resolve(args, guard[1])))
    if kind == "sw":
        return _resolve(args, guard[1]).startswith(
            _operand(args, guard[2])
        )
    if kind == "ew":
        return _resolve(args, guard[1]).endswith(_operand(args, guard[2]))
    if kind in ("contains", "in"):
        return _operand(args, guard[2]) in _resolve(args, guard[1])
    if kind == "lt":
        return _resolve(args, guard[1]) < _operand(args, guard[2])
    if kind == "le":
        return _resolve(args, guard[1]) <= _operand(args, guard[2])
    if kind == "gt":
        return _resolve(args, guard[1]) > _operand(args, guard[2])
    if kind == "ge":
        return _resolve(args, guard[1]) >= _operand(args, guard[2])
    raise ValueError(f"unknown guard kind {kind!r}")


def program_sig(program: Program, args: tuple):
    """Replay the program's guards against ``args``; ``None`` when a
    guard cannot even be evaluated (structurally different context)."""
    try:
        return tuple(_guard_outcome(args, g) for g in program.guards)
    except Exception:
        return None


# -- lowering (probe output -> program) -----------------------------------


def _intern_const(pool: list, pool_map: dict, text: str) -> int:
    if "\x00" in text:
        raise _OutOfSubset("mangled sentinel in constant segment")
    idx = pool_map.get(text)
    if idx is None:
        idx = pool_map[text] = len(pool)
        pool.append(text)
    return idx


def _lower_text(sess: _Session, text, pool: list, pool_map: dict) -> tuple:
    # read the raw buffer: lowering runs AFTER the session deactivates,
    # where _ProbeStr.__str__ would hand back the real value and erase
    # the sentinel — str.__str__ sees the sentinel bytes themselves
    s = str.__str__(text) if isinstance(text, str) else str(text)
    ops = []
    last = 0
    for match in _SENT_RE.finditer(s):
        if match.start() > last:
            ops.append(
                _intern_const(pool, pool_map, s[last:match.start()])
            )
        path = sess.slots.get(int(match.group(1)))
        if path is None:
            raise _OutOfSubset("unknown sentinel")
        ops.append(path)
        last = match.end()
    if last < len(s):
        ops.append(_intern_const(pool, pool_map, s[last:]))
    return tuple(ops)


def _lower_result(sess: _Session, value, pool: list, pool_map: dict):
    from .machinery import FileSpec, Fragment

    if isinstance(value, str):
        return ("s", _lower_text(sess, value, pool, pool_map))
    if isinstance(value, FileSpec):
        return (
            "f",
            _lower_text(sess, value.path, pool, pool_map),
            _lower_text(sess, value.content, pool, pool_map),
            value.if_exists.value,
            bool(value.add_boilerplate),
        )
    if isinstance(value, Fragment):
        return (
            "g",
            _lower_text(sess, value.path, pool, pool_map),
            _lower_text(sess, value.marker, pool, pool_map),
            _lower_text(sess, value.code, pool, pool_map),
        )
    if isinstance(value, (list, tuple)):
        return (
            "L",
            tuple(
                _lower_result(sess, item, pool, pool_map)
                for item in value
            ),
        )
    raise _OutOfSubset(f"result type {type(value).__name__}")


# -- execution ------------------------------------------------------------

_MISSING = object()


def _exec_ops(ops: tuple, args: tuple, pool: tuple, cache: dict) -> str:
    parts = []
    for op in ops:
        if type(op) is int:
            parts.append(pool[op])
        else:
            value = cache.get(op, _MISSING)
            if value is _MISSING:
                value = _resolve(args, op)
                if type(value) is not str:
                    value = str(value)
                cache[op] = value
            parts.append(value)
    return "".join(parts)


def _exec_result(node, args: tuple, pool: tuple, cache: dict):
    from .machinery import FileSpec, Fragment, IfExists

    kind = node[0]
    if kind == "s":
        return _exec_ops(node[1], args, pool, cache)
    if kind == "f":
        return FileSpec(
            path=_exec_ops(node[1], args, pool, cache),
            content=_exec_ops(node[2], args, pool, cache),
            if_exists=IfExists(node[3]),
            add_boilerplate=node[4],
        )
    if kind == "g":
        return Fragment(
            path=_exec_ops(node[1], args, pool, cache),
            marker=_exec_ops(node[2], args, pool, cache),
            code=_exec_ops(node[3], args, pool, cache),
        )
    # "L"
    return [
        _exec_result(item, args, pool, cache) for item in node[1]
    ]


def execute(program: Program, args: tuple):
    """Run a program against real context args.  Slot paths resolve
    once per unique path per execution (a template reading
    ``view.kind`` nine times costs one property evaluation here)."""
    return _exec_result(program.result, args, program.pool, {})


# -- runner compilation ----------------------------------------------------
#
# The interpreter above is the semantic reference, but walking paths
# per guard per render costs more than the f-string tree it replaces.
# Production renders go through a RUNNER: straight-line Python source
# generated once per (template, shape) — every unique path prefix is a
# single local, custom iterables materialize once, the guard signature
# inlines into one tuple comparison, and each text builds in a single
# ``join`` — then ``compile()``d, exactly how ``gocheck/compiler.py``
# turns lowered spans into bytecode scanners.  Guard-phase failures
# (structurally different context) return ``_NO_MATCH``; result-phase
# failures propagate and deopt the template.

_NO_MATCH = object()


def _compile_runner(program: Program):
    from .machinery import FileSpec, Fragment, IfExists

    consts: list = []

    def lit(value) -> str:
        consts.append(value)
        return f"_L[{len(consts) - 1}]"

    lines: list = []
    names: dict = {}   # path -> local variable name
    mats: dict = {}    # path -> local holding list(iter(value))
    counter = [0]

    def fresh() -> str:
        counter[0] += 1
        return f"v{counter[0]}"

    def safe(name: str) -> str:
        if not name.isidentifier() or _keyword.iskeyword(name):
            raise _OutOfSubset(f"unsafe name {name!r}")
        return name

    def ensure(path: tuple) -> str:
        var = names.get(path)
        if var is not None:
            return var
        if len(path) == 1:
            var = fresh()
            lines.append(f"{var} = args[{path[0]}]")
        else:
            step = path[-1]
            kind = type(step)
            var = fresh()
            if kind is str:
                lines.append(f"{var} = {ensure(path[:-1])}.{safe(step)}")
            elif kind is int:
                lines.append(f"{var} = {ensure(path[:-1])}[{step}]")
            elif step[0] == "@":
                call_args = ", ".join(lit(a) for a in step[2:])
                lines.append(
                    f"{var} = {ensure(path[:-1])}"
                    f".{safe(step[1])}({call_args})"
                )
            else:  # ("#", i)
                lines.append(f"{var} = {ensure_mat(path[:-1])}[{step[1]}]")
        names[path] = var
        return var

    def ensure_mat(path: tuple) -> str:
        var = mats.get(path)
        if var is None:
            src = ensure(path)
            var = fresh()
            lines.append(f"{var} = list(iter({src}))")
            mats[path] = var
        return var

    def operand_expr(key: tuple) -> str:
        return ensure(key[1]) if key[0] == "p" else lit(key[1])

    def guard_expr(guard: tuple) -> str:
        kind = guard[0]
        if kind == "val":
            return ensure(guard[1])
        if kind == "isnone":
            return f"({ensure(guard[1])} is None)"
        if kind == "truthy":
            return f"bool({ensure(guard[1])})"
        if kind == "len":
            return f"len({ensure(guard[1])})"
        if kind == "ilen":
            return f"len({ensure_mat(guard[1])})"
        left = ensure(guard[1])
        right = operand_expr(guard[2])
        if kind == "eq":
            return f"({left} == {right})"
        if kind == "sw":
            return f"{left}.startswith({right})"
        if kind == "ew":
            return f"{left}.endswith({right})"
        if kind in ("contains", "in"):
            return f"({right} in {left})"
        op = {"lt": "<", "le": "<=", "gt": ">", "ge": ">="}.get(kind)
        if op is None:
            raise _OutOfSubset(f"unknown guard kind {kind!r}")
        return f"({left} {op} {right})"

    def ops_expr(ops: tuple) -> str:
        parts = [
            lit(program.pool[op]) if type(op) is int
            else f"str({ensure(op)})"
            for op in ops
        ]
        if not parts:
            return "''"
        if len(parts) == 1:
            return parts[0]
        return f"''.join(({', '.join(parts)}))"

    def result_expr(node: tuple) -> str:
        kind = node[0]
        if kind == "s":
            return ops_expr(node[1])
        if kind == "f":
            return (
                f"_FileSpec(path={ops_expr(node[1])},"
                f" content={ops_expr(node[2])},"
                f" if_exists={lit(IfExists(node[3]))},"
                f" add_boilerplate={bool(node[4])!r})"
            )
        if kind == "g":
            return (
                f"_Fragment(path={ops_expr(node[1])},"
                f" marker={ops_expr(node[2])},"
                f" code={ops_expr(node[3])})"
            )
        return f"[{', '.join(result_expr(item) for item in node[1])}]"

    sig_parts = [guard_expr(g) for g in program.guards]
    guard_lines = list(lines)
    del lines[:]
    returned = result_expr(program.result)
    sig_tuple = (
        "(" + ", ".join(sig_parts) + ("," if len(sig_parts) == 1 else "")
        + ")"
    )
    src = [
        "def _run(args):",
        "    try:",
    ]
    src.extend("        " + line for line in guard_lines)
    src.append(f"        if {sig_tuple} != {lit(program.sig)}:")
    src.append("            return _NO_MATCH")
    src.append("    except Exception:")
    src.append("        return _NO_MATCH")
    src.extend("    " + line for line in lines)
    src.append(f"    return {returned}")
    namespace: dict = {
        "_L": tuple(consts),
        "_FileSpec": FileSpec,
        "_Fragment": Fragment,
        "_NO_MATCH": _NO_MATCH,
    }
    exec(  # noqa: S102 — source is generated from our own program data
        compile(
            "\n".join(src),
            f"<render:{program.template_id}:{program.shape}>",
            "exec",
        ),
        namespace,
    )
    return namespace["_run"]


def _runner(program: Program):
    """The compiled runner for a program, built once per process and
    shared across threads (keyed by (template, shape), exactly like the
    interpreter registry)."""
    key = (program.template_id, program.shape)
    run = _runners.get(key)
    if run is None:
        run = _compile_runner(program)
        with _lock:
            _runners[key] = run
    return run


# -- the decorator --------------------------------------------------------


def _shape_of(guards: tuple, sig: tuple) -> str:
    try:
        payload = pickle.dumps((guards, sig), protocol=4)
    except Exception:
        payload = repr((guards, sig)).encode("utf-8")
    return hashlib.sha256(payload).hexdigest()[:20]


# memoized string->string helpers (utils.names, marker-pattern
# compilation) hash and compare probe strings as their REAL values, so
# a probe render can deposit probes into their caches — and a later
# render (probe OR production) would get them back.  Every probe render
# is therefore followed by a flush of these caches; the global sid
# counter turns any probe that slips through an unregistered cache into
# an unknown sentinel (deopt) rather than a mis-aliased slot.
_probe_cache_clears: list = []
_default_clears = None


def register_probe_cache(clear) -> None:
    """Register a ``cache_clear`` callable to run after every probe
    render (for memoized helpers that may capture probe strings)."""
    _probe_cache_clears.append(clear)


def _clear_probe_caches() -> None:
    global _default_clears
    if _default_clears is None:
        from ..utils import names

        clears = [
            names.to_title.cache_clear,
            names.title_words.cache_clear,
            names.to_pascal_case.cache_clear,
            names.to_file_name.cache_clear,
            names.to_package_name.cache_clear,
        ]
        try:
            from ..workload.fieldmarkers import _compile_replace

            clears.append(_compile_replace.cache_clear)
        except Exception:
            pass
        _default_clears = clears
    for clear in _default_clears:
        clear()
    for clear in _probe_cache_clears:
        try:
            clear()
        except Exception:
            pass


def _lower_and_run(template_id: str, fn, flat: tuple):
    from ..perf import metrics

    ref_out = fn(*flat)
    with spans.span("render.lower"):
        try:
            sess = _Session()
            _active.sess = sess
            try:
                wrapped = tuple(
                    sess.classify(value, (i,))
                    for i, value in enumerate(flat)
                )
                probe_out = fn(*wrapped)
            finally:
                _active.sess = None
                _clear_probe_caches()
            pool: list = []
            pool_map: dict = {}
            result = _lower_result(sess, probe_out, pool, pool_map)
            guards = tuple(sess.guards)
            sig = tuple(sess.sig)
            program = Program(
                template_id=template_id,
                pool=tuple(pool),
                guards=guards,
                sig=sig,
                result=result,
                shape=_shape_of(guards, sig),
            )
            # the hard gate: both execution backends (the compiled
            # runner production uses, and the interpretive reference
            # semantics) must reproduce the reference output
            # byte-for-byte for the lowering context, and the guards
            # must replay deterministically.  A runner returning
            # _NO_MATCH here means the just-recorded signature does
            # not replay — equally disqualifying.
            if _runner(program)(flat) != ref_out:
                raise _OutOfSubset("runner verify mismatch")
            if execute(program, flat) != ref_out:
                raise _OutOfSubset("verify mismatch")
            if program_sig(program, flat) != sig:
                raise _OutOfSubset("non-deterministic guards")
        except Exception:
            _deopt(template_id)
            return ref_out
    with _lock:
        if template_id not in _deopted:
            known = _programs.setdefault(template_id, [])
            if all(p.shape != program.shape for p in known):
                known.append(program)
                _dirty.add(template_id)
    metrics.counter("render.lowered").inc()
    return ref_out


def compiled_render(template_id: str, subset: bool = True):
    """Wrap a template function with the program tier.  ``subset=False``
    declares the template out-of-subset up front (impure renders that
    read the output tree): it deopts on first call and pins to the
    reference renderer."""

    def decorate(fn):
        try:
            signature = inspect.signature(fn)
        except (TypeError, ValueError):
            return fn
        # the hot path binds positionally without inspect: a render
        # call passing every parameter positionally IS the bound tuple
        n_params = len(signature.parameters)
        positional_ok = all(
            p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            for p in signature.parameters.values()
        )

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if mode() != "program" or template_id in _deopted:
                return fn(*args, **kwargs)
            if not subset:
                _deopt(template_id)
                return fn(*args, **kwargs)
            if positional_ok and not kwargs and len(args) == n_params:
                flat = args
            else:
                try:
                    bound = signature.bind(*args, **kwargs)
                    bound.apply_defaults()
                    flat = tuple(bound.arguments.values())
                except TypeError:
                    return fn(*args, **kwargs)
            for value in flat:
                # a decorated template called from another template's
                # PROBE render sees recording proxies: run the raw
                # function so the callee inlines into the caller's
                # program instead of confusing its own tier
                if isinstance(value, (_ProbeStr, _Rec, _RecSeq, _RecCall)):
                    return fn(*args, **kwargs)
            if template_id not in _hydrated:
                _hydrate(template_id)
            try:
                for program in _programs.get(template_id, ()):
                    out = _runner(program)(flat)
                    if out is not _NO_MATCH:
                        _executed_pending[0] += 1
                        return out
            except Exception:
                _deopt(template_id)
                return fn(*flat)
            return _lower_and_run(template_id, fn, flat)

        wrapper.__render_template_id__ = template_id
        wrapper.__wrapped__ = fn
        return wrapper

    return decorate


# -- content-hash blob programs (transforms / emitters) -------------------


def lowered_blob(template_id: str, key_parts: tuple, compute):
    """The compile-once-per-content-hash tier for pure transforms whose
    output is fully determined by their input bytes (the manifest
    marker pass, the gocodegen document emitter).  The lowered artifact
    is the pickled result; execution is the unpickle — every caller
    owns a fresh copy, matching ``perf.cache.memoized`` semantics."""
    if mode() != "program" or template_id in _deopted:
        return compute()
    if _active_session() is not None:
        # inside another template's PROBE render the key parts (and the
        # computed value) may carry sentinel probes — computing plainly
        # keeps the caller's lowering observable and the blob store
        # free of probe-keyed junk
        return compute()
    from ..perf import metrics
    from ..perf.cache import hash_parts

    try:
        # canonical tagged hashing, never pickle: pickle bytes vary
        # with object identity (a string shared between two slots
        # memoizes into a back-reference), so the same logical doc
        # would key differently across processes and defeat hydration
        digest = hash_parts(key_parts)
    except Exception:
        return compute()
    _hydrate(template_id)
    blob = _blobs.get((template_id, digest))
    if blob is not None:
        try:
            value = pickle.loads(blob)
        except Exception:
            _deopt(template_id)
            return compute()
        metrics.counter("render.executed").inc()
        return value
    value = compute()
    try:
        blob = pickle.dumps(value, protocol=4)
    except Exception:
        _deopt(template_id)
        return value
    with _lock:
        if template_id not in _deopted:
            _blobs[(template_id, digest)] = blob
            _dirty.add(template_id)
    metrics.counter("render.lowered").inc()
    return value


# -- cross-process manifests (``render.lower``) ---------------------------

_RENDER_STAGE = "render.lower"


def _manifest_key(template_id: str) -> str:
    from ..perf.cache import __version__, hash_parts

    # the generator version salts every key: a persisted program must
    # never replay an older generator's emission
    return hash_parts(_RENDER_STAGE, __version__, template_id)


def _hydrate(template_id: str) -> int:
    """Install every program a previous process persisted for this
    template.  One manifest lookup per template per process (negative
    results memoized); a no-op with the cache off."""
    if template_id in _hydrated:
        return 0
    from ..perf import cache as pf_cache
    from ..perf import metrics

    cache = pf_cache.get_cache()
    if cache.mode() == "off":
        return 0
    with _lock:
        if template_id in _hydrated:
            return 0
        _hydrated.add(template_id)
    manifest = cache.get(_RENDER_STAGE, _manifest_key(template_id))
    if manifest is pf_cache.MISS or not isinstance(manifest, tuple):
        return 0
    if len(manifest) != 2:
        return 0
    programs, blobs = manifest
    count = 0
    with spans.span("render.hydrate"):
        with _lock:
            if template_id in _deopted:
                return 0
            known = _programs.setdefault(template_id, [])
            shapes = {p.shape for p in known}
            for program in programs if isinstance(programs, tuple) else ():
                if (
                    isinstance(program, Program)
                    and program.template_id == template_id
                    and program.shape not in shapes
                ):
                    known.append(program)
                    shapes.add(program.shape)
                    count += 1
            if not known:
                _programs.pop(template_id, None)
            for digest, blob in (
                blobs.items() if isinstance(blobs, dict) else ()
            ):
                key = (template_id, digest)
                if isinstance(blob, bytes) and key not in _blobs:
                    _blobs[key] = blob
                    count += 1
    if count:
        metrics.counter("render.hydrated").inc(count)
    return count


def flush_lowered() -> int:
    """Persist dirty template manifests (merged with any previously
    recorded programs for the same template) into the ``render.lower``
    namespace.  Called at process exit and from tests; cheap no-op when
    nothing new was lowered.  Returns the manifests written."""
    from ..perf import cache as pf_cache

    cache = pf_cache.get_cache()
    if cache.mode() == "off":
        return 0
    with _lock:
        dirty = {
            tid: (
                tuple(_programs.get(tid, ())),
                {
                    digest: blob
                    for (btid, digest), blob in _blobs.items()
                    if btid == tid
                },
            )
            for tid in _dirty
            if tid not in _deopted
        }
        _dirty.clear()
    written = 0
    for tid, (programs, blobs) in dirty.items():
        if not programs and not blobs:
            continue
        key = _manifest_key(tid)
        previous = cache.get(_RENDER_STAGE, key, record_stats=False)
        merged_programs = {p.shape: p for p in programs}
        merged_blobs = dict(blobs)
        if (
            previous is not pf_cache.MISS
            and isinstance(previous, tuple)
            and len(previous) == 2
        ):
            prev_programs, prev_blobs = previous
            for program in (
                prev_programs if isinstance(prev_programs, tuple) else ()
            ):
                if (
                    isinstance(program, Program)
                    and program.shape not in merged_programs
                ):
                    merged_programs[program.shape] = program
            for digest, blob in (
                prev_blobs.items() if isinstance(prev_blobs, dict) else ()
            ):
                merged_blobs.setdefault(digest, blob)
        value = (
            tuple(
                merged_programs[shape]
                for shape in sorted(merged_programs)
            ),
            merged_blobs,
        )
        if previous is not pf_cache.MISS and value == previous:
            continue
        cache.put(_RENDER_STAGE, key, value)
        written += 1
    return written


def _flush_at_exit() -> None:
    try:
        if flush_lowered():
            import sys

            remote = sys.modules.get("operator_forge.perf.remote")
            if remote is not None:
                remote.flush()
    except Exception:
        pass  # exit paths never raise over a best-effort persist


import atexit  # noqa: E402

atexit.register(_flush_at_exit)
