"""YAML inspector: find registered markers attached to YAML elements.

Reference: internal/markers/inspect/yaml.go:22-101.  Walks every mapping
entry and sequence item of each document, feeds the element's comments
(head + line + foot) to the marker parser, and pairs results with the
element so the caller can rewrite values and comments in place.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Union

from ..yamldoc import Document, MapEntry, Mapping, Scalar, SeqItem, Sequence
from ..yamldoc.load import load_documents
from .registry import Registry

Element = Union[MapEntry, SeqItem]


@dataclass
class InspectResult:
    obj: Any  # the inflated marker object
    marker_text: str  # exact marker substring (for comment rewriting)
    element: Element  # the owning mapping entry or sequence item
    document: Document

    @property
    def value_node(self):
        """The YAML node the marker governs (the entry's value or the item's
        node) — the reference's ``result.Nodes[1]``
        (internal/workload/v1/markers/markers.go:189-195)."""
        if isinstance(self.element, MapEntry):
            return self.element.value
        return self.element.node


def _walk_elements(node) -> list[Element]:
    out: list[Element] = []
    if isinstance(node, Mapping):
        for entry in node.entries:
            out.append(entry)
            out.extend(_walk_elements(entry.value))
    elif isinstance(node, Sequence):
        for item in node.items:
            out.append(item)
            out.extend(_walk_elements(item.node))
    return out


def inspect_documents(
    documents: list[Document], registry: Registry
) -> tuple[list[InspectResult], list[str]]:
    """Inspect already-loaded documents.  Returns (results, warnings)."""
    results: list[InspectResult] = []
    warnings: list[str] = []
    for doc in documents:
        if doc.root is None:
            continue
        doc_comment_sources: list[tuple[Optional[Element], str]] = [
            (None, "\n".join(doc.head_comments))
        ]
        for element in _walk_elements(doc.root):
            doc_comment_sources.append((element, element.all_comment_text()))
        for element, text in doc_comment_sources:
            if not text:
                continue
            parsed, warns = registry.parse_text(text)
            warnings.extend(warns)
            if element is None:
                # document-level comments can't govern a value; report markers
                # found there as warnings rather than silently dropping them
                for p in parsed:
                    warnings.append(
                        f"marker {p.text!r} found in document-level comment "
                        "has no associated value"
                    )
                continue
            for p in parsed:
                results.append(
                    InspectResult(
                        obj=p.obj,
                        marker_text=p.text,
                        element=element,
                        document=doc,
                    )
                )
    return results, warnings


def inspect_yaml(
    text: str, registry: Registry
) -> tuple[list[Document], list[InspectResult], list[str]]:
    """Load ``text`` and inspect it.  Returns (documents, results, warnings)."""
    documents = load_documents(text)
    results, warnings = inspect_documents(documents, registry)
    return documents, results, warnings
