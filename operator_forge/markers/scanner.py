"""Marker scanner: extracts raw markers from comment text.

A faithful re-implementation of the reference's state-function lexer
(internal/markers/lexer/state.go:15-317) as a single-pass scanner.  The
grammar it accepts is documented in the package docstring.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional, Union

# characters that terminate a scope or argument-name token
# (internal/markers/lexer/state.go:72-76)
_TOKEN_EXCEPTIONS = set(':= "\'`,+{}[]();\n')
# naked string values additionally allow ';' (state.go:286-291)
_NAKED_EXCEPTIONS = set(':= "\'`,+{}[]()\n')


def _run_pattern(exceptions: set) -> "re.Pattern[str]":
    """Precompiled longest-run scan up to any terminator character —
    replaces the per-character loop (module-level patterns, matching the
    style of gocheck/structural.py)."""
    return re.compile("[^" + re.escape("".join(sorted(exceptions))) + "]*")


_TOKEN_RUN_RE = _run_pattern(_TOKEN_EXCEPTIONS)
_NAKED_RUN_RE = _run_pattern(_NAKED_EXCEPTIONS)
_BREAK_RUN_RE = _run_pattern(set(" \n"))
# quoted-value bodies: longest run up to the closing delimiter or a
# newline (the newline branch keeps its per-case handling)
_QUOTED_RUN_RES = {q: _run_pattern({q, "\n"}) for q in ('"', "'", "`")}

Literal = Union[str, int, float, bool]


class ScanError(Exception):
    """A malformed argument inside a recognized marker shape."""


@dataclass
class RawMarker:
    scopes: list[str]
    args: list[tuple[str, Literal]]
    text: str  # exact marker substring, for comment rewriting

    @property
    def scope_path(self) -> str:
        return ":".join(self.scopes)


@dataclass
class ScanResult:
    markers: list[RawMarker] = field(default_factory=list)
    warnings: list[str] = field(default_factory=list)


class _Scanner:
    def __init__(self, text: str):
        self.text = text
        self.pos = 0
        self.result = ScanResult()

    # -- primitives -----------------------------------------------------

    def peek(self) -> str:
        return self.text[self.pos] if self.pos < len(self.text) else ""

    def at_end(self) -> bool:
        return self.pos >= len(self.text)

    def take_run(self, pattern: "re.Pattern[str]") -> str:
        match = pattern.match(self.text, self.pos)
        self.pos = match.end()
        return match.group()

    # -- top level ------------------------------------------------------

    def scan(self) -> ScanResult:
        # whole-buffer candidate discovery: jump straight to each '+'
        # with str.find instead of advancing per character — the
        # overwhelming majority of comment text contains no markers,
        # and find() skips it at C speed.  Semantics are unchanged: a
        # '+' not followed by a letter is plain comment text, and the
        # next find() resumes right after it (re-examining a following
        # '+' exactly as the per-char loop did).
        text = self.text
        n = len(text)
        while True:
            idx = text.find("+", self.pos)
            if idx == -1:
                self.pos = n
                return self.result
            self.pos = idx + 1
            if self.peek().isalpha():
                self._scan_marker(idx)

    # -- marker body ----------------------------------------------------

    def _scan_marker(self, start: int) -> None:
        """Scan scopes then arguments; emits a RawMarker or a warning."""
        scopes: list[str] = []
        while True:
            token = self.take_run(_TOKEN_RUN_RE)
            nxt = self.peek()
            if token and nxt == ":":
                scopes.append(token)
                self.pos += 1
                continue
            if token and nxt in ("", " ", "\n"):
                # e.g. "+optional" — a word, not a scoped marker
                if not scopes:
                    self.result.warnings.append(
                        f"marker without scope found at position {start}"
                    )
                    return
                # flag-style first argument: implicit =true
                self._finish(start, scopes, [(token, True)])
                return
            if token and nxt == "=":
                if not scopes:
                    self.result.warnings.append(
                        f"marker without scope found at position {start}"
                    )
                    return
                self.pos += 1
                args = [(token, self._scan_value())]
                self._scan_more_args(start, scopes, args)
                return
            # anything else: not a marker shape
            self.result.warnings.append(
                f"invalid marker found at position {start}"
            )
            self._skip_to_break()
            return

    def _scan_more_args(
        self, start: int, scopes: list[str], args: list[tuple[str, Literal]]
    ) -> None:
        while True:
            nxt = self.peek()
            if nxt == ",":
                self.pos += 1
                name = self.take_run(_TOKEN_RUN_RE)
                if not name:
                    raise ScanError(
                        f"malformed argument at position {self.pos} in marker "
                        f"{self.text[start:self.pos]!r}"
                    )
                if self.peek() == "=":
                    self.pos += 1
                    args.append((name, self._scan_value()))
                elif self.peek() in ("", " ", "\n", ","):
                    args.append((name, True))
                else:
                    raise ScanError(
                        f"malformed argument {name!r} at position {self.pos}"
                    )
            elif nxt in ("", " ", "\n"):
                self._finish(start, scopes, args)
                return
            else:
                raise ScanError(
                    f"malformed argument at position {self.pos} in marker "
                    f"{self.text[start:self.pos]!r}"
                )

    def _finish(
        self, start: int, scopes: list[str], args: list[tuple[str, Literal]]
    ) -> None:
        self.result.markers.append(
            RawMarker(scopes=scopes, args=args, text=self.text[start : self.pos])
        )

    def _skip_to_break(self) -> None:
        self.pos = _BREAK_RUN_RE.match(self.text, self.pos).end()

    # -- literals -------------------------------------------------------

    def _scan_value(self) -> Literal:
        ch = self.peek()
        if ch in ('"', "'", "`"):
            return self._scan_quoted(ch)
        if ch.isdigit() or ch in ".-":
            return self._scan_number()
        if self._try_consume("true"):
            return True
        if self._try_consume("false"):
            return False
        naked = self.take_run(_NAKED_RUN_RE)
        if not naked:
            raise ScanError(f"malformed argument at position {self.pos}")
        return naked

    def _try_consume(self, word: str) -> bool:
        end = self.pos + len(word)
        if self.text[self.pos : end] == word:
            follower = self.text[end : end + 1]
            if follower == "" or follower in " \n,":
                self.pos = end
                return True
        return False

    def _scan_quoted(self, quote: str) -> str:
        opened_at = self.pos
        self.pos += 1
        out: list[str] = []
        run = _QUOTED_RUN_RES[quote]
        while True:
            # one regex run to the next delimiter or newline instead of
            # a per-character append loop
            match = run.match(self.text, self.pos)
            out.append(match.group())
            self.pos = match.end()
            if self.at_end():
                raise ScanError(
                    f"unmatched string delimiter {quote} at position {opened_at}"
                )
            ch = self.text[self.pos]
            if ch == quote:
                self.pos += 1
                return "".join(out)
            # ch == "\n"
            if quote != "`":
                raise ScanError(
                    f"unmatched string delimiter {quote} at position "
                    f"{opened_at}"
                )
            # backtick strings may continue across comment lines; the
            # comment prefix of the next line is not part of the value
            # (internal/markers/lexer/state.go:201-210)
            out.append(ch)
            self.pos += 1
            self._skip_comment_prefix()

    def _skip_comment_prefix(self) -> None:
        mark = self.pos
        while self.peek() in " \t":
            self.pos += 1
        if self.peek() == "#":
            self.pos += 1
        elif self.text[self.pos : self.pos + 2] == "//":
            self.pos += 2
        else:
            self.pos = mark

    def _scan_number(self) -> Union[int, float]:
        start = self.pos
        is_float = self.peek() == "."
        self.pos += 1
        while not self.at_end():
            ch = self.text[self.pos]
            if ch in ".eE-":
                is_float = True
                self.pos += 1
                continue
            if ch.isdigit():
                self.pos += 1
                continue
            break
        raw = self.text[start : self.pos]
        try:
            return float(raw) if is_float else int(raw)
        except ValueError as exc:
            kind = "float" if is_float else "integer"
            raise ScanError(
                f"invalid {kind} literal {raw!r} before position {self.pos}"
            ) from exc


def scan_text(text: str) -> ScanResult:
    """Scan arbitrary comment text for raw markers."""
    return _Scanner(text).scan()
