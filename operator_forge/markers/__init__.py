"""Generic marker engine.

Reference: internal/markers/{lexer,parser,marker,inspect} (SURVEY.md L1).
Markers are annotations embedded in comments of YAML (or Go) sources with the
shape::

    +scope[:scope...]:arg[=value][,arg[=value]...]

- scopes are colon-separated identifiers; the chain must match a registered
  definition (e.g. ``+operator-builder:field``);
- argument values are quoted strings (single/double/backtick, backtick
  allowing multi-line continuation across comment lines), integers, floats,
  booleans, or naked strings; an argument without ``=value`` is a boolean
  flag implicitly set to ``true`` (internal/markers/lexer/state.go:96-101);
- a space or end of line terminates the marker;
- text in comments that does not form a well-formed marker yields warnings,
  never errors (internal/markers/lexer/lexer.go warnings contract), while
  malformed arguments *within* a recognized marker are errors.

Modules:
- :mod:`scanner`: hand-written scanner producing raw markers from text;
- :mod:`registry`: dataclass-reflection marker definitions + registry
  (reference internal/markers/marker/marker.go:28-88);
- :mod:`inspector`: walks yamldoc trees, parsing every element's comments
  (reference internal/markers/inspect/yaml.go:22-101).
"""

from .scanner import RawMarker, ScanError, scan_text  # noqa: F401
from .registry import (  # noqa: F401
    Definition,
    MarkerError,
    Registry,
    define,
    marker_arg,
)
from .inspector import InspectResult, inspect_documents, inspect_yaml  # noqa: F401
