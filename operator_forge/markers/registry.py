"""Marker definitions and registry via dataclass reflection.

Reference: internal/markers/marker/{marker,argument,registry}.go.  A marker
definition binds a scope path (e.g. ``operator-builder:field``) to a dataclass
whose fields describe the accepted arguments:

- python field ``collection_field`` maps to marker argument
  ``collectionField`` (override with ``marker_arg(name=...)``);
- fields without a default are required arguments;
- argument values are converted according to the field annotation: ``str``,
  ``int``, ``bool``, ``float``, ``typing.Any`` (preserves the literal type),
  ``Optional[...]`` of those, or any class providing a
  ``from_marker_arg(value)`` classmethod (the analogue of the reference's
  ``Unmarshaler`` interface, internal/markers/parser/unmarshal.go).
"""

from __future__ import annotations

import dataclasses
import typing
from dataclasses import dataclass
from typing import Any, Optional

from .scanner import Literal, RawMarker, ScanResult, scan_text


class MarkerError(Exception):
    """A recognized marker with invalid arguments."""


def marker_arg(
    *, name: Optional[str] = None, default: Any = dataclasses.MISSING
) -> Any:
    """Declare a dataclass field with an explicit marker-argument name."""
    metadata = {"marker_name": name} if name else {}
    if default is dataclasses.MISSING:
        return dataclasses.field(metadata=metadata)
    return dataclasses.field(default=default, metadata=metadata)


def _camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.capitalize() for p in parts[1:])


@dataclass
class ArgSpec:
    name: str
    attr: str
    required: bool
    annotation: Any

    def convert(self, value: Literal) -> Any:
        ann = self.annotation
        origin = typing.get_origin(ann)
        if origin is typing.Union:
            members = [a for a in typing.get_args(ann) if a is not type(None)]
            ann = members[0] if len(members) == 1 else Any
        if ann is Any or ann is object:
            return value
        if ann is str:
            if not isinstance(value, str):
                raise MarkerError(
                    f"argument {self.name!r} expects a string, got {value!r}"
                )
            return value
        if ann is bool:
            if not isinstance(value, bool):
                raise MarkerError(
                    f"argument {self.name!r} expects a bool, got {value!r}"
                )
            return value
        if ann is int:
            if isinstance(value, bool) or not isinstance(value, int):
                raise MarkerError(
                    f"argument {self.name!r} expects an int, got {value!r}"
                )
            return value
        if ann is float:
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                raise MarkerError(
                    f"argument {self.name!r} expects a float, got {value!r}"
                )
            return float(value)
        if hasattr(ann, "from_marker_arg"):
            return ann.from_marker_arg(value)
        raise MarkerError(
            f"argument {self.name!r} has unsupported annotation {ann!r}"
        )


@dataclass
class Definition:
    scope_path: str  # colon-joined scopes without the leading '+'
    cls: type
    specs: dict[str, ArgSpec]

    def inflate(self, raw: RawMarker) -> Any:
        """Build a typed marker object from a raw scanned marker."""
        kwargs: dict[str, Any] = {}
        for arg_name, value in raw.args:
            spec = self.specs.get(arg_name)
            if spec is None:
                raise MarkerError(
                    f"unknown argument {arg_name!r} for marker "
                    f"+{self.scope_path} in {raw.text!r}"
                )
            kwargs[spec.attr] = spec.convert(value)
        for spec in self.specs.values():
            if spec.required and spec.attr not in kwargs:
                raise MarkerError(
                    f"missing required argument {spec.name!r} for marker "
                    f"+{self.scope_path} in {raw.text!r}"
                )
        return self.cls(**kwargs)


def define(prefix: str, cls: type) -> Definition:
    """Create a Definition for ``cls`` registered under ``prefix``.

    ``prefix`` may include the leading ``+`` (as the reference constants do,
    e.g. ``+operator-builder:field``); it is stripped for matching.
    """
    scope_path = prefix.lstrip("+")
    hints = typing.get_type_hints(cls)
    specs: dict[str, ArgSpec] = {}
    for f in dataclasses.fields(cls):
        if not f.init or f.metadata.get("marker_skip"):
            continue
        name = f.metadata.get("marker_name") or _camel(f.name)
        required = (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        )
        specs[name] = ArgSpec(
            name=name,
            attr=f.name,
            required=required,
            annotation=hints.get(f.name, Any),
        )
    return Definition(scope_path=scope_path, cls=cls, specs=specs)


@dataclass
class ParsedMarker:
    obj: Any
    text: str  # the exact marker substring from the source comment


class Registry:
    """Scope-path -> Definition registry (reference
    internal/markers/marker/registry.go:8-42)."""

    def __init__(self) -> None:
        self._defs: dict[str, Definition] = {}

    def add(self, definition: Definition) -> None:
        self._defs[definition.scope_path] = definition

    def lookup(self, scope_path: str) -> Optional[Definition]:
        return self._defs.get(scope_path)

    def parse_text(self, text: str) -> tuple[list[ParsedMarker], list[str]]:
        """Scan ``text`` and inflate every registered marker found.

        Returns (parsed markers, warnings).  Unregistered markers become
        warnings; malformed arguments raise :class:`~.scanner.ScanError` or
        :class:`MarkerError`.
        """
        result: ScanResult = scan_text(text)
        parsed: list[ParsedMarker] = []
        warnings = list(result.warnings)
        for raw in result.markers:
            definition = self.lookup(raw.scope_path)
            if definition is None:
                warnings.append(f"unknown marker +{raw.scope_path}")
                continue
            parsed.append(ParsedMarker(obj=definition.inflate(raw), text=raw.text))
        return parsed, warnings
