"""``python -m operator_forge`` entrypoint."""

import sys

from .cli.main import main

sys.exit(main())
