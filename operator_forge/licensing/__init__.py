"""License management for generated projects.

Reference: internal/license/license.go.
- :func:`update_project_license` writes the project ``LICENSE`` file;
- :func:`update_source_header` writes ``hack/boilerplate.go.txt`` with the
  header applied to newly scaffolded ``.go`` files;
- :func:`update_existing_source_headers` rewrites the header of every
  existing ``.go`` file by replacing everything above the ``package``
  declaration (reference license.go:71-96);
- license source may be a local path or an http(s) URL
  (reference license.go:98-125).
"""

from __future__ import annotations

import os
import urllib.request


class LicenseError(Exception):
    pass


def _read_source(path_or_url: str) -> str:
    if path_or_url.startswith(("http://", "https://")):
        try:
            with urllib.request.urlopen(path_or_url, timeout=30) as response:
                return response.read().decode("utf-8")
        except Exception as exc:
            raise LicenseError(
                f"unable to fetch license from {path_or_url}: {exc}"
            ) from exc
    try:
        with open(path_or_url, "r", encoding="utf-8") as handle:
            return handle.read()
    except OSError as exc:
        raise LicenseError(
            f"unable to read license file {path_or_url}: {exc}"
        ) from exc


def update_project_license(project_dir: str, source: str) -> str:
    """Write LICENSE from a local path or URL.  Returns the target path."""
    content = _read_source(source)
    target = os.path.join(project_dir, "LICENSE")
    with open(target, "w", encoding="utf-8") as handle:
        handle.write(content)
    return target


def boilerplate_from_source(source: str) -> str:
    """Build a Go comment-block boilerplate from raw license-header text."""
    content = _read_source(source).rstrip("\n")
    if content.lstrip().startswith(("/*", "//")):
        return content + "\n"
    return "/*\n" + content + "\n*/\n"


def update_source_header(project_dir: str, source: str) -> str:
    """Write hack/boilerplate.go.txt from a local path or URL."""
    content = boilerplate_from_source(source)
    target = os.path.join(project_dir, "hack", "boilerplate.go.txt")
    os.makedirs(os.path.dirname(target), exist_ok=True)
    with open(target, "w", encoding="utf-8") as handle:
        handle.write(content)
    return target


def update_existing_source_headers(project_dir: str, source: str) -> list[str]:
    """Replace the header (everything above ``package``) of every tracked
    ``.go`` file with the new boilerplate.  Returns the rewritten paths."""
    boilerplate = boilerplate_from_source(source)
    rewritten = []
    for root, dirs, files in os.walk(project_dir):
        dirs[:] = [d for d in dirs if d not in (".git", "bin", "vendor")]
        for name in files:
            if not name.endswith(".go"):
                continue
            path = os.path.join(root, name)
            with open(path, "r", encoding="utf-8") as handle:
                lines = handle.read().split("\n")
            package_idx = None
            for i, line in enumerate(lines):
                if line.startswith("package "):
                    package_idx = i
                    break
            if package_idx is None:
                continue
            body = "\n".join(lines[package_idx:])
            with open(path, "w", encoding="utf-8") as handle:
                handle.write(boilerplate + "\n" + body)
            rewritten.append(path)
    return rewritten
