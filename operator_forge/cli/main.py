"""The operator-forge CLI.

Reference: pkg/cli/init.go:26-58 (command assembly), the workload plugin's
init/create-api subcommands (internal/plugins/workload/v1/{init,api}.go),
`init-config` (pkg/cli/init_config.go) and `update license`
(pkg/cli/{update,license}.go).

Commands:
- ``operator-forge init --workload-config <path> [--repo <module>]``
- ``operator-forge create api [--workload-config <path>]``
- ``operator-forge init-config <standalone|collection|component>``
- ``operator-forge update license --project-license/--source-header-license``
- ``operator-forge completion <bash|zsh>``
- ``operator-forge version``
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import threading

from operator_forge.utils import yamlcompat as pyyaml

from .. import __version__
from .. import licensing
from ..perf import cache as perfcache
from ..perf import spans
from ..scaffold.api import scaffold_api, scaffold_webhook
from ..scaffold.context import DEFAULT_LAYOUT, ProjectConfig
from ..scaffold.machinery import Scaffold, ScaffoldError
from ..scaffold.project import scaffold_init
from ..workload import config as wconfig
from ..workload.create_api import CreateAPIError
from ..workload.create_api import create_api as run_create_api
from ..workload.create_api import init_workloads
from . import init_config as init_config_mod


class CLIError(Exception):
    pass


def _parse_bool(value: str) -> bool:
    if value.lower() in ("true", "1", "yes", "y"):
        return True
    if value.lower() in ("false", "0", "no", "n"):
        return False
    raise argparse.ArgumentTypeError(f"expected true/false, got {value!r}")


def _load_project(output_dir: str) -> ProjectConfig:
    project_path = os.path.join(output_dir, "PROJECT")
    if not os.path.exists(project_path):
        raise CLIError(
            "no PROJECT file found; run `operator-forge init` first"
        )
    with open(project_path, "r", encoding="utf-8") as handle:
        data = pyyaml.safe_load(handle.read()) or {}
    return ProjectConfig.from_dict(data)


def _boilerplate_text(output_dir: str) -> str:
    path = os.path.join(output_dir, "hack", "boilerplate.go.txt")
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as handle:
            return handle.read()
    return ""


def _default_repo(workload_name: str) -> str:
    return f"github.com/example/{workload_name}"


# Plugin registry: kubebuilder-style keys (`name/vN`, short names match
# the first dot-segment) the reference CLI accepts (pkg/cli/init.go:
# 27-53 registers the go/v3 bundle as default plus golangv2 and
# declarative/v1 as selectable alternatives).  operator-forge's
# generator IS the bundle; the kubebuilder-only alternative layouts are
# recognized and refused with the reason.
_PLUGIN_BUNDLE_KEY = DEFAULT_LAYOUT

_PLUGINS: dict = {
    # full name -> {version -> disposition}
    "go.operator-forge.io": {"v3": "bundle"},
    "workload.operator-forge.io": {"v1": "bundle"},
    "license.operator-forge.io": {"v1": "bundle"},
    "config.operator-forge.io": {"v1": "bundle"},
    # reference-compatible spellings
    "go.kubebuilder.io": {"v3": "bundle", "v2": "legacy"},
    "kustomize.common.kubebuilder.io": {"v1": "bundle"},
    "workload.operator-builder.io": {"v1": "bundle"},
    "license.operator-builder.io": {"v1": "bundle"},
    "config.operator-builder.io": {"v1": "bundle"},
    "declarative.go.kubebuilder.io": {"v1": "declarative"},
}

_PLUGIN_REFUSALS = {
    "legacy": (
        "scaffolds the legacy kubebuilder go/v2 layout; operator-forge "
        "generates only the go/v3 layout (omit --plugins, or pass go/v3)"
    ),
    "declarative": (
        "is kubebuilder's declarative-pattern scaffold; operator-forge's "
        "workload generator renders and reconciles your manifests "
        "directly, subsuming it (omit --plugins, or pass go/v3)"
    ),
}


def resolve_plugins(spec: str) -> str:
    """Resolve a ``--plugins`` value (comma-separated kubebuilder-style
    keys) to the bundle layout key, with kubebuilder's matching rules:
    full name, or short name = first dot-segment, optional ``/vN``.
    Raises CLIError for unknown keys and for recognized-but-unsupported
    alternative layouts."""
    keys = [k.strip() for k in spec.split(",") if k.strip()]
    if not keys:
        raise CLIError(f"invalid --plugins value {spec!r}: no plugin keys")
    for key in keys:
        name, _sep, version = key.partition("/")
        matches = [
            full for full in _PLUGINS
            if full == name or full.split(".", 1)[0] == name
        ]
        if not matches:
            raise CLIError(
                f"no plugin could be resolved with key {key!r}"
            )
        # among short-name matches, prefer one that has the requested
        # version (so `go/v2` finds go.kubebuilder.io's v2 refusal, not
        # a missing-version error on go.operator-forge.io)
        full = next(
            (c for c in matches if version and version in _PLUGINS[c]),
            matches[0],
        )
        versions = _PLUGINS[full]
        if version:
            if version not in versions:
                raise CLIError(
                    f"no plugin {full!r} version {version!r}; known: "
                    + ", ".join(sorted(versions))
                )
            disposition = versions[version]
        else:
            # unversioned: prefer the supported bundle version
            disposition = (
                "bundle" if "bundle" in versions.values()
                else next(iter(versions.values()))
            )
        if disposition != "bundle":
            raise CLIError(
                f"plugin {key!r} {_PLUGIN_REFUSALS[disposition]}"
            )
    return _PLUGIN_BUNDLE_KEY


def _dep_globs(processor, include_manifests: bool) -> list:
    """The glob patterns a parsed config resolved — part of the plan
    cache's dependency snapshot, so a NEW file matching a component or
    manifest glob invalidates even though no recorded file changed."""
    globs = []
    for p in processor.get_processors():
        workload = p.workload
        base = os.path.dirname(p.path)
        for pattern in getattr(workload, "component_files", ()):
            globs.append(("files", os.path.join(base, pattern)))
        if include_manifests:
            for pattern in workload.spec.resources:
                globs.append(("manifests", os.path.join(base, pattern)))
    return globs


def cmd_init(args: argparse.Namespace) -> int:
    # resolve plugin keys FIRST: a bad --plugins value must fail before
    # any config work, like the reference CLI's plugin resolution
    layout = resolve_plugins(args.plugins) if args.plugins else (
        _PLUGIN_BUNDLE_KEY
    )

    # content-addressed pipeline cache: when the config tree is unchanged
    # (validated against hashes + glob results recorded with the plan),
    # replay the rendered file plan without re-running the pipeline.
    # License flags write into the output dir before scaffolding, so they
    # fall through to the full path.
    plan_key = None
    if not args.project_license and not args.source_header_license:
        cfg_sha = perfcache.file_sha(args.workload_config)
        if cfg_sha is not None:
            # the generator version joins the key inside perf.cache
            plan_key = (
                "init",
                os.path.abspath(args.workload_config),
                cfg_sha,
                args.repo,
                layout,
                os.path.relpath(args.workload_config, args.output_dir),
                bool(args.component_config),
                _boilerplate_text(args.output_dir),
            )
            with spans.span("plan-cache"):
                plan = perfcache.plan_get(plan_key, args.output_dir)
            if plan is not None:
                os.makedirs(args.output_dir, exist_ok=True)
                scaffold = Scaffold(
                    output_dir=args.output_dir,
                    boilerplate=_boilerplate_text(args.output_dir),
                )
                scaffold.execute(plan)
                print(f"project scaffolded at {args.output_dir} "
                      f"({len(scaffold.written)} files)")
                return 0

    with spans.span("config-parse"):
        processor = wconfig.parse(args.workload_config)
    init_workloads(processor)
    workload = processor.workload

    repo = args.repo or _default_repo(workload.name)
    config = ProjectConfig(
        repo=repo,
        domain=workload.domain,
        layout=layout,
        workload_config_path=os.path.relpath(
            args.workload_config, args.output_dir
        ),
        cli_root_command_name=workload.companion_root_cmd.name,
        cli_root_command_description=workload.companion_root_cmd.description,
        component_config=args.component_config,
    )

    os.makedirs(args.output_dir, exist_ok=True)

    if args.source_header_license:
        licensing.update_source_header(
            args.output_dir, args.source_header_license
        )
    if args.project_license:
        licensing.update_project_license(args.output_dir, args.project_license)

    names = [w.name for w in processor.get_workloads()]
    scaffold = scaffold_init(
        args.output_dir,
        config,
        names,
        boilerplate_text=_boilerplate_text(args.output_dir),
    )
    if plan_key is not None:
        with spans.span("plan-cache"):
            perfcache.plan_put(
                plan_key,
                scaffold.specs,
                dep_files=[p.path for p in processor.get_processors()],
                dep_globs=_dep_globs(processor, include_manifests=False),
            )
    print(f"project scaffolded at {args.output_dir} "
          f"({len(scaffold.written)} files)")
    return 0


def _report_dry_run(scaffold, project_changed: bool) -> None:
    """Print the dry-run change list + summary (shared by `create api`
    and `create webhook`)."""
    if project_changed:
        scaffold.changes.append(("overwrite", "PROJECT"))
    counts: dict[str, int] = {}
    for action, path in scaffold.changes:
        counts[action] = counts.get(action, 0) + 1
        print(f"{action:9s} {path}")
    summary = ", ".join(
        f"{counts[a]} {a}"
        for a in ("create", "overwrite", "fragment", "unchanged", "preserve")
        if a in counts
    )
    print(f"dry run: {summary or 'no changes'}; nothing written")


def _persist_project(config: ProjectConfig, output_dir: str) -> None:
    with open(
        os.path.join(output_dir, "PROJECT"), "w", encoding="utf-8"
    ) as handle:
        handle.write(config.to_yaml())


def cmd_edit(args: argparse.Namespace) -> int:
    """`edit`: update project attributes recorded in the PROJECT file
    (kubebuilder's `edit` from the golangv3 bundle the reference
    registers, pkg/cli/init.go:27-41; its only real knob is
    --multigroup)."""
    config = _load_project(args.output_dir)
    if args.multigroup is None:
        print("nothing to edit: pass --multigroup=true|false")
        return 0
    if not args.multigroup and config.multigroup:
        raise CLIError(
            "cannot disable multigroup: operator-forge projects lay out "
            "APIs as apis/<group>/<version> from the start, and existing "
            "groups are not collapsible"
        )
    changed = config.multigroup != args.multigroup
    config.multigroup = args.multigroup
    if changed:
        _persist_project(config, args.output_dir)
    # the layout is already group-scoped, so enabling multigroup
    # changes bookkeeping only
    print(
        f"multigroup={'true' if config.multigroup else 'false'} "
        f"(layout is apis/<group>/<version> either way)"
    )
    return 0


def cmd_create_webhook(args: argparse.Namespace) -> int:
    """`create webhook`: admission-webhook scaffolding (the reference
    CLI inherits kubebuilder's command via the golangv3 bundle,
    reference pkg/cli/init.go:27-41)."""
    if not args.defaulting and not args.programmatic_validation:
        raise CLIError(
            "nothing to scaffold: pass --defaulting and/or "
            "--programmatic-validation"
        )
    config = _load_project(args.output_dir)
    workload_config = args.workload_config or os.path.join(
        args.output_dir, config.workload_config_path
    )
    if not workload_config or not os.path.exists(workload_config):
        raise CLIError(
            f"workload config not found at {workload_config!r}; pass "
            "--workload-config"
        )
    if not os.path.exists(os.path.join(args.output_dir, "main.go")):
        raise CLIError(
            "main.go not found: run `create api` before `create webhook`"
        )

    processor = wconfig.parse(workload_config)
    init_workloads(processor)
    run_create_api(processor)

    changed = (
        (args.defaulting and not config.webhook_defaulting)
        or (args.programmatic_validation and not config.webhook_validation)
    )
    config.webhook_defaulting = (
        config.webhook_defaulting or args.defaulting
    )
    config.webhook_validation = (
        config.webhook_validation or args.programmatic_validation
    )

    # the stub is user-owned (SKIP): a pre-existing stub missing a
    # newly requested interface can't be upgraded in place, and
    # emitting manifests for an unserved path would reject every write
    # in-cluster (failurePolicy: Fail) — refuse, like kubebuilder does
    from ..scaffold.context import views_for
    from ..scaffold.templates import admission as admission_tpl

    if not args.force:
        stale = admission_tpl.stale_stubs(
            views_for(processor.get_workloads(), config),
            args.output_dir,
            config.webhook_defaulting,
            config.webhook_validation,
        )
        if stale:
            raise CLIError("\n".join(stale))

    scaffold = scaffold_webhook(
        args.output_dir,
        processor,
        config,
        boilerplate_text=_boilerplate_text(args.output_dir),
        dry_run=args.dry_run,
        force=args.force,
    )

    if args.dry_run:
        _report_dry_run(scaffold, changed)
        return 0

    if changed:
        _persist_project(config, args.output_dir)
    print(
        f"webhook scaffolded at {args.output_dir} "
        f"({len(scaffold.written)} files, {len(scaffold.skipped)} preserved)"
    )
    return 0


def cmd_create_api(args: argparse.Namespace) -> int:
    if not args.resource and not args.controller:
        raise CLIError(
            "nothing to scaffold: --controller=false and --resource=false "
            "cannot be combined"
        )
    config = _load_project(args.output_dir)
    workload_config = args.workload_config or os.path.join(
        args.output_dir, config.workload_config_path
    )
    if not workload_config or not os.path.exists(workload_config):
        raise CLIError(
            f"workload config not found at {workload_config!r}; pass "
            "--workload-config"
        )

    # content-addressed pipeline cache (plain path only: the conversion
    # and admission paths read and mutate the existing output tree, so
    # their effect is not a pure function of the recorded inputs)
    boilerplate = _boilerplate_text(args.output_dir)
    plan_key = None
    if (
        not args.dry_run
        and not args.enable_conversion
        and not config.enable_conversion
        and not config.webhook_defaulting
        and not config.webhook_validation
    ):
        cfg_sha = perfcache.file_sha(workload_config)
        if cfg_sha is not None:
            plan_key = (
                "create-api",
                os.path.abspath(workload_config),
                cfg_sha,
                config.to_yaml(),
                bool(args.resource),
                bool(args.controller),
                boilerplate,
            )
            with spans.span("plan-cache"):
                plan = perfcache.plan_get(plan_key, args.output_dir)
            if plan is not None:
                specs, fragments = plan
                scaffold = Scaffold(
                    output_dir=args.output_dir, boilerplate=boilerplate
                )
                scaffold.execute(specs, fragments)
                print(
                    f"api scaffolded at {args.output_dir} "
                    f"({len(scaffold.written)} files, "
                    f"{len(scaffold.skipped)} preserved)"
                )
                return 0

    with spans.span("config-parse"):
        processor = wconfig.parse(workload_config)
    init_workloads(processor)
    run_create_api(processor)

    newly_enabled = args.enable_conversion and not config.enable_conversion
    config.enable_conversion = config.enable_conversion or args.enable_conversion

    # the CRD renderer merges against previously scaffolded CRD bases, so
    # their pre-execution state is part of the plan's dependency snapshot
    crd_reldir = os.path.join("config", "crd", "bases")
    crd_state = (
        perfcache.dir_state(args.output_dir, crd_reldir)
        if plan_key is not None
        else ()
    )

    scaffold = scaffold_api(
        args.output_dir,
        processor,
        config,
        boilerplate_text=boilerplate,
        with_resources=args.resource,
        with_controllers=args.controller,
        enable_conversion=config.enable_conversion,
        dry_run=args.dry_run,
    )

    if plan_key is not None:
        dep_files = [p.path for p in processor.get_processors()]
        dep_files.extend(
            manifest.filename
            for workload in processor.get_workloads()
            for manifest in workload.spec.manifests
        )
        # two acceptable CRD-base states: what the renderer merged
        # against, and what this plan just wrote (re-rendering over its
        # own output is a fixed point)
        crd_states = [crd_state]
        post_state = perfcache.dir_state(args.output_dir, crd_reldir)
        if post_state != crd_state:
            crd_states.append(post_state)
        with spans.span("plan-cache"):
            perfcache.plan_put(
                plan_key,
                (scaffold.specs, scaffold.fragments),
                dep_files=dep_files,
                dep_globs=_dep_globs(processor, include_manifests=True),
                out_state=[(crd_reldir, crd_states)],
            )

    if args.dry_run:
        # the real run records the conversion opt-in in PROJECT
        _report_dry_run(scaffold, newly_enabled)
        return 0

    # persist the opt-in only after a successful scaffold: recording it
    # first would make every later plain `create api` re-enter a failing
    # conversion path
    if newly_enabled:
        _persist_project(config, args.output_dir)
    print(
        f"api scaffolded at {args.output_dir} "
        f"({len(scaffold.written)} files, {len(scaffold.skipped)} preserved)"
    )
    return 0


def cmd_init_config(args: argparse.Namespace) -> int:
    init_config_mod.write_config(args.workload_type, args.path, args.force)
    return 0


def cmd_update_license(args: argparse.Namespace) -> int:
    if not args.project_license and not args.source_header_license:
        raise CLIError(
            "provide --project-license and/or --source-header-license"
        )
    if args.project_license:
        licensing.update_project_license(args.output_dir, args.project_license)
    if args.source_header_license:
        licensing.update_source_header(
            args.output_dir, args.source_header_license
        )
        rewritten = licensing.update_existing_source_headers(
            args.output_dir, args.source_header_license
        )
        print(f"updated headers in {len(rewritten)} files")
    return 0


_BASH_COMPLETION = """# bash completion for operator-forge
_operator_forge() {
    local cur prev
    cur="${COMP_WORDS[COMP_CWORD]}"
    prev="${COMP_WORDS[COMP_CWORD-1]}"
    case "$prev" in
        operator-forge)
            COMPREPLY=($(compgen -W "init create edit init-config update completion version preview validate vet test batch serve daemon connect fleet fleet-status watch cache cache-server stats explain trace" -- "$cur"));;
        create)
            COMPREPLY=($(compgen -W "api webhook" -- "$cur"));;
        init-config)
            COMPREPLY=($(compgen -W "standalone collection component" -- "$cur"));;
        update)
            COMPREPLY=($(compgen -W "license" -- "$cur"));;
        cache)
            COMPREPLY=($(compgen -W "gc verify" -- "$cur"));;
        completion)
            COMPREPLY=($(compgen -W "bash zsh fish" -- "$cur"));;
        *)
            case "$cur" in
                OPERATOR_FORGE_RENDER=*)
                    COMPREPLY=($(compgen -W "OPERATOR_FORGE_RENDER=ref OPERATOR_FORGE_RENDER=program" -- "$cur"));;
                OPERATOR_FORGE_GOCHECK=*)
                    COMPREPLY=($(compgen -W "OPERATOR_FORGE_GOCHECK=walk OPERATOR_FORGE_GOCHECK=compile OPERATOR_FORGE_GOCHECK=bytecode" -- "$cur"));;
                OPERATOR_FORGE_GOCHECK_RACE=*)
                    COMPREPLY=($(compgen -W "OPERATOR_FORGE_GOCHECK_RACE=on OPERATOR_FORGE_GOCHECK_RACE=off" -- "$cur"));;
                OPERATOR_FORGE_CACHE=*)
                    COMPREPLY=($(compgen -W "OPERATOR_FORGE_CACHE=off OPERATOR_FORGE_CACHE=mem OPERATOR_FORGE_CACHE=disk" -- "$cur"));;
                OPERATOR_FORGE_DAEMON_SUPERSEDE=*)
                    COMPREPLY=($(compgen -W "OPERATOR_FORGE_DAEMON_SUPERSEDE=on OPERATOR_FORGE_DAEMON_SUPERSEDE=off" -- "$cur"));;
                OPERATOR_FORGE_DAEMON_EDITOR_BOOST=*)
                    COMPREPLY=($(compgen -W "OPERATOR_FORGE_DAEMON_EDITOR_BOOST=on OPERATOR_FORGE_DAEMON_EDITOR_BOOST=off" -- "$cur"));;
                *)
                    COMPREPLY=($(compgen -f -- "$cur"));;
            esac;;
    esac
}
complete -F _operator_forge operator-forge
"""

_ZSH_COMPLETION = """#compdef operator-forge
_arguments '1: :(init create edit init-config update completion version preview validate vet test batch serve daemon connect fleet fleet-status watch cache cache-server stats explain trace)' '*: :_files'
"""

_FISH_COMPLETION = """# fish completion for operator-forge
complete -c operator-forge -f -n __fish_use_subcommand \
    -a 'init create edit init-config update completion version preview validate vet test batch serve daemon connect fleet fleet-status watch cache cache-server stats explain trace'
complete -c operator-forge -f -n '__fish_seen_subcommand_from create' -a 'api webhook'
complete -c operator-forge -f -n '__fish_seen_subcommand_from init-config' \
    -a 'standalone collection component'
complete -c operator-forge -f -n '__fish_seen_subcommand_from update' -a 'license'
complete -c operator-forge -f -n '__fish_seen_subcommand_from cache' -a 'gc verify'
complete -c operator-forge -f -n '__fish_seen_subcommand_from completion' -a 'bash zsh fish'
"""


def cmd_completion(args: argparse.Namespace) -> int:
    if args.shell == "bash":
        sys.stdout.write(_BASH_COMPLETION)
    elif args.shell == "zsh":
        sys.stdout.write(_ZSH_COMPLETION)
    elif args.shell == "fish":
        sys.stdout.write(_FISH_COMPLETION)
    else:
        raise CLIError(f"unsupported shell {args.shell!r}")
    return 0


def cmd_version(_: argparse.Namespace) -> int:
    print(f"operator-forge version {__version__}")
    return 0


def cmd_preview(args: argparse.Namespace) -> int:
    """Render child manifests for a CR without building the operator —
    the native equivalent of the generated companion CLI's `generate`
    subcommand (reference templates/cli/cmd_generate_sub.go:49-332)."""
    from operator_forge.markers import MarkerError
    from operator_forge.workload.config import ConfigParseError
    from operator_forge.workload.create_api import CreateAPIError
    from operator_forge.workload.kinds import (
        ManifestProcessingError,
        WorkloadConfigError,
    )
    from operator_forge.workload.preview import PreviewError, preview
    from operator_forge.yamldoc import YamlDocError

    try:
        rendered = preview(
            args.workload_config,
            args.workload_manifest,
            collection_manifest=args.collection_manifest,
        )
    except (
        PreviewError,
        ConfigParseError,
        CreateAPIError,
        WorkloadConfigError,
        ManifestProcessingError,
        MarkerError,
        YamlDocError,
        OSError,
    ) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    if not rendered:
        # a valid CR can legitimately render zero children (all guards off)
        print("no child resources to render", file=sys.stderr)
        return 0
    sys.stdout.write(rendered)
    return 0


def cmd_validate(args: argparse.Namespace) -> int:
    """Validate CR manifests against a generated project's CRD schemas
    (types, unknown properties, required fields) without a cluster."""
    from operator_forge.workload.crdschema import (
        ValidationError,
        load_project_crds,
        validate_cr,
    )

    try:
        with open(args.manifest, encoding="utf-8") as fh:
            docs = [
                d for d in pyyaml.safe_load_all(fh.read()) if d is not None
            ]
    except (OSError, pyyaml.YAMLError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    if not docs:
        print(f"error: no documents in {args.manifest}", file=sys.stderr)
        return 1
    try:
        crds = load_project_crds(args.project)
    except ValidationError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    failures = 0
    for doc in docs:
        if isinstance(doc, dict):
            label = f"{doc.get('apiVersion')}/{doc.get('kind')}"
        else:
            label = f"document ({type(doc).__name__})"
        errors = validate_cr(args.project, doc, crds=crds)
        if errors:
            failures += 1
            for err in errors:
                print(f"{label}: {err}", file=sys.stderr)
        else:
            print(f"{label}: valid")
    return 1 if failures else 0


def cmd_vet(args: argparse.Namespace) -> int:
    """Check every .go file of a generated project through the
    analyzer framework (gocheck/analysis/): the syntax/type/structural
    gate plus the data-flow analyzers (shadow, ineffassign,
    unreachable, errcheck, loopclosure, copylocks, structtag) — the
    no-toolchain stand-in for CI's `go build ./... && go vet ./...`
    (reference .github/workflows/test.yaml:53-105).

    ``--analyzers a,b`` selects a subset (run order is fixed);
    ``--json`` emits one JSON object per diagnostic with stable key
    order, for batch/serve clients.
    """
    import json as _json

    from operator_forge.gocheck.analysis import (
        AnalysisError,
        analyze_project,
    )

    root = args.path
    if not os.path.isdir(root):
        print(f"error: {root} is not a directory", file=sys.stderr)
        return 1
    names = None
    if args.analyzers:
        names = [n.strip() for n in args.analyzers.split(",") if n.strip()]
    try:
        diagnostics = analyze_project(root, analyzers=names)
    except AnalysisError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    if args.json:
        for diag in diagnostics:
            print(_json.dumps(diag.to_dict()))
        return 1 if diagnostics else 0
    for diag in diagnostics:
        print(diag.text(), file=sys.stderr)
    if diagnostics:
        print(f"vet: {len(diagnostics)} problem(s)", file=sys.stderr)
        return 1
    print("vet: all Go files check cleanly")
    return 0


def cmd_test(args: argparse.Namespace) -> int:
    """Run the generated project's OWN Go test suite — unit, envtest,
    and (with --e2e) the e2e lifecycle tests — under the bundled Go
    interpreter against a fake cluster, with no Go toolchain and no
    real cluster.  The reference gets this guarantee from CI running
    `go test` / kind (.github/workflows/test.yaml:55-141); here it is
    a local command.

    Packages fan out across OPERATOR_FORGE_JOBS threads (each package
    gets an isolated world; the report is collected in input order, so
    it is identical to a serial run), function bodies execute through
    the tiered interpreter (OPERATOR_FORGE_GOCHECK=walk|compile|
    bytecode, default bytecode: closure-lowered once per content hash,
    hot bodies promoted to register bytecode), and a re-run over a
    byte-identical tree replays the cached report
    (OPERATOR_FORGE_CACHE).  `-v` streams per-test lines and therefore
    runs packages serially."""
    from operator_forge.gocheck.world import run_project_tests

    root = args.path
    if not os.path.isdir(root):
        print(f"error: {root} is not a directory", file=sys.stderr)
        return 1
    if args.run:
        import re as _re

        try:
            _re.compile(args.run)
        except _re.error as exc:
            print(f"error: invalid --run pattern: {exc}", file=sys.stderr)
            return 1
    def verbose_start(name):
        print(f"=== RUN   {name}", flush=True)

    def verbose_result(name, passed):
        print(f"--- {'PASS' if passed else 'FAIL'}: {name}", flush=True)

    results = run_project_tests(
        root, include_e2e=args.e2e, run_filter=args.run or None,
        progress=lambda rel: print(f"--- {rel}"),
        on_test=verbose_result if args.v else None,
        on_test_start=verbose_start if args.v else None,
    )
    if not results:
        print("test: no *_test.go packages found", file=sys.stderr)
        return 1
    failed = 0
    for res in results:
        if res.skipped:
            print(f"skip  {res.rel}  (e2e; pass --e2e to run)")
            continue
        if res.error:
            failed += 1
            print(f"FAIL  {res.rel}  interpreter: {res.error}")
            continue
        status = "ok  " if res.ok else "FAIL"
        print(f"{status}  {res.rel}  ({len(res.ran)} tests, "
              f"{res.seconds:.2f}s)")
        for name, messages in res.failures:
            failed += 1
            print(f"  --- FAIL: {name}")
            for msg in messages:
                print(f"      {msg}")
        for leak in getattr(res, "leaks", ()):
            print(f"  leak: {leak}")
    if failed or any(not res.ok and not res.skipped for res in results):
        print("test: FAIL", file=sys.stderr)
        return 1
    print("test: ok")
    return 0


def cmd_batch(args: argparse.Namespace) -> int:
    """`batch`: run a manifest of init/create-api/vet/lint/test jobs
    through
    the batch orchestrator (PR 3) — jobs over distinct directories fan
    out across the OPERATOR_FORGE_WORKERS=thread|process backend, jobs
    over one directory chain in manifest order, unchanged jobs replay
    from the content cache, and results report in manifest order.
    With --addr the manifest runs through a resident `operator-forge
    daemon` instead of this process, so its warm caches serve the
    batch."""
    from ..serve.batch import cmd_batch as run

    return run(args.manifest, json_lines=args.json, addr=args.addr)


def cmd_serve(args: argparse.Namespace) -> int:
    """`serve`: keep one resident process hot and answer JSON-lines
    requests on stdin (ping/job/batch/watch/stats/shutdown), one JSON
    response line each (watch streams one per cycle) — warm caches and
    compiled interpreter bodies persist across requests."""
    from ..serve.server import serve_loop

    return serve_loop()


def cmd_daemon(args: argparse.Namespace) -> int:
    """`daemon`: the serve protocol for N concurrent clients — a
    unix/TCP socket listener whose sessions multiplex over the shared
    worker pool through a round-robin fair scheduler with bounded
    per-session and global admission queues (`busy` + retry_after on
    overflow), per-project cache namespaces, and cache-memory budgets
    enforced by a maintenance tick.  SIGTERM/SIGINT (or a client's
    shutdown op) drains: in-flight requests finish, every session gets
    a final drained-shutdown line, exit 0.  The `gopls -listen` /
    Bazel-server analogue."""
    from ..serve.daemon import serve_daemon

    return serve_daemon(
        args.listen, clients=args.clients, fleet=args.fleet
    )


def cmd_fleet(args: argparse.Namespace) -> int:
    """`fleet`: the fault-tolerant coordinator over N daemons — daemons
    register with heartbeat leases (one missed lease: suspect; two:
    evicted), client jobs route by project-namespace affinity with
    work-stealing for cold trees, an in-flight submission whose daemon
    dies is re-dispatched idempotently to a healthy one (bounded
    deterministic retry, then in-process quarantine), and SIGTERM
    drains every daemon, answers queued clients busy, and exits 0.
    The Bazel --remote_executor analogue."""
    from ..serve.fleet import serve_fleet

    elastic = None
    if args.max:
        elastic = {"min": args.min, "max": args.max}
    return serve_fleet(
        args.listen, lease=args.lease, clients=args.clients,
        elastic=elastic,
    )


def cmd_fleet_status(args: argparse.Namespace) -> int:
    """`fleet-status`: the fleet observability surface — per-daemon
    lease age, in-flight jobs, degrade gauges, and the eviction/
    re-dispatch counters — from a running coordinator's stats op, in
    stable key order.  With --json, that fleet surface as one JSON
    object (the full stats document is available from the `stats` op
    via `connect`)."""
    import json as _json

    from ..serve.fleet import fleet_status

    try:
        stats = fleet_status(args.addr)
    except (OSError, ConnectionError) as exc:
        print(f"error: coordinator at {args.addr}: {exc}",
              file=sys.stderr)
        return 1
    fleet = stats.get("fleet")
    tiers = stats.get("tiers") or {}
    # stable-order sanitizer surface, mirroring the tiers/editor lines
    sanitize = {
        "checked": tiers.get("sanitize.checked", 0),
        "clock_merges": tiers.get("sanitize.clock_merges", 0),
        "races": tiers.get("sanitize.races", 0),
    }
    if args.json:
        if fleet is not None:
            fleet = dict(fleet)
            fleet["sanitize"] = sanitize
        print(_json.dumps(stats if fleet is None else fleet))
        return 0 if fleet is not None else 1
    if fleet is None:
        print("error: no fleet surface in the stats payload "
              "(is this a coordinator?)", file=sys.stderr)
        return 1
    scale = fleet.get("scale") or {}
    scale_note = (
        f" autoscale={scale.get('min', 0)}..{scale.get('max', 0)}"
        if scale.get("max") else ""
    )
    print(
        f"fleet: {fleet['listen']} lease={fleet['lease_s']:g}s "
        f"members={len(fleet['members'])} "
        f"queued={fleet['queued_requests']} "
        f"affinities={fleet['affinities']} "
        f"populated={fleet.get('populated_namespaces', 0)}"
        f"{scale_note}"
    )
    for member_id, m in fleet["members"].items():
        artifact = m.get("artifact") or {}
        print(
            f"  {member_id}  {m['addr']}  {m['state']}"
            f"{' degraded' if m['degraded'] else ''}"
            f"{' spawned' if m.get('spawned') else ''}  "
            f"lease_age={m['lease_age_s']:.2f}s  "
            f"in_flight={m['in_flight']}/{m['capacity']}  "
            f"queued={m['queued']}  dispatched={m['dispatched']}  "
            f"namespaces={m.get('namespaces', 0)}  "
            "artifact["
            + " ".join(
                f"{key}={artifact.get(key, 0)}"
                for key in sorted(artifact)
            )
            + "]"
        )
    counters = fleet["counters"]
    print(
        "  counters: "
        + " ".join(
            f"{name.split('.', 1)[1]}={counters[name]}"
            for name in sorted(counters)
        )
    )
    print(
        "sanitize: checked=%d clock_merges=%d races=%d"
        % (sanitize["checked"], sanitize["clock_merges"],
           sanitize["races"])
    )
    return 0


def cmd_connect(args: argparse.Namespace) -> int:
    """`connect`: drive a running daemon from a terminal or script —
    JSON-lines requests on stdin are relayed to the daemon and every
    response line (including a watch op's streamed cycles) is printed
    to stdout as it arrives.  stdin EOF half-closes the connection and
    waits for the daemon's remaining answers."""
    from ..serve.daemon import DaemonClient

    try:
        client = DaemonClient(args.addr)
    except OSError as exc:
        print(f"error: daemon at {args.addr}: {exc}", file=sys.stderr)
        return 1

    def pump_responses():
        while True:
            line = client.read_line()
            if not line:
                return
            sys.stdout.write(line)
            sys.stdout.flush()

    reader = threading.Thread(target=pump_responses, daemon=True)
    reader.start()
    try:
        for line in sys.stdin:
            if not line.strip():
                continue
            client.send_line(line)
    except (OSError, KeyboardInterrupt):
        pass
    client.half_close()
    reader.join()
    client.close()
    return 0


def cmd_watch(args: argparse.Namespace) -> int:
    """`watch`: the edit loop, served — run a batch manifest's jobs,
    then poll their input trees (mtime+hash) and re-run the minimal
    job set on every change.  Deltas feed the dependency graph
    (perf/depgraph.py), so a one-file edit recomputes only that file's
    artifacts plus their transitive dependents: the index is patched,
    unchanged files' diagnostics replay, untouched test packages'
    suites replay, and untouched job groups skip entirely.  Each cycle
    prints its per-cycle `graph` dirty/reused/recomputed counts."""
    from ..serve.watch import cmd_watch as run

    return run(
        args.manifest,
        cycles=args.cycles if args.cycles > 0 else None,
        interval=args.interval,
        json_lines=args.json,
    )


def cmd_cache_gc(args: argparse.Namespace) -> int:
    """`cache gc`: prune the on-disk content cache to its size ceiling
    (OPERATOR_FORGE_CACHE_MAX_MB, default 256), least-recently-used
    entries first.  Removal is whole-file, so surviving entries always
    verify; a pruned entry is simply a future miss.  The summary is
    always machine-readable JSON (stable key order) — scripts consume
    it, and `--verbose` adds detail keys rather than switching to
    human prose."""
    import json as _json

    max_bytes = None
    if args.max_mb is not None:
        max_bytes = int(args.max_mb * 1024 * 1024)
    purged = None
    if args.purge_quarantine:
        # purge BEFORE the sweep so the reported quarantine footprint
        # reflects the post-purge state (normally zero)
        purged = perfcache.get_cache().purge_quarantine()
    summary = perfcache.gc(max_bytes)
    out = {
        "entries_removed": summary["entries_removed"],
        "bytes_reclaimed": summary["bytes_reclaimed"],
        "bytes_remaining": summary["bytes_remaining"],
        # quarantined files are outside the live store but still on
        # disk; gc reports them (and --purge-quarantine reclaims them)
        "quarantine_entries": summary["quarantine_entries"],
        "quarantine_bytes": summary["quarantine_bytes"],
        # flight-recorder capsules share the cache dir's budget: the
        # sweep removes expired ones and reports what remains
        "flight_entries": summary["flight_entries"],
        "flight_bytes": summary["flight_bytes"],
        "flight_removed": summary["flight_removed"],
        "flight_bytes_reclaimed": summary["flight_bytes_reclaimed"],
    }
    if purged is not None:
        out["quarantine_purged_entries"] = purged["entries_removed"]
        out["quarantine_purged_bytes"] = purged["bytes_reclaimed"]
    if args.verbose or args.json:
        # detail keys, including the pre-PR-6 --json spellings, so
        # existing consumers of removed/bytes_before/bytes_after keep
        # reading real values
        for key in ("entries", "max_bytes", "removed", "bytes_before",
                    "bytes_after"):
            out[key] = summary[key]
    print(_json.dumps(out))
    return 0


def cmd_cache_server(args: argparse.Namespace) -> int:
    """`cache-server`: serve a shared content-addressed artifact store
    over a unix socket or TCP (the remote tier of the three-level
    mem → disk → remote cache hierarchy).  Blobs are stored and served
    as the opaque HMAC-signed bytes clients produce; the server never
    unpickles and never needs the signing key — clients verify every
    fetched blob with their own key before deserializing, so a
    compromised or mismatched server costs misses, never code
    execution.  The store reuses the local disk layout, including the
    LRU ceiling (OPERATOR_FORGE_CACHE_MAX_MB / --max-mb).  Point
    clients at it with OPERATOR_FORGE_REMOTE_CACHE=<addr>."""
    from ..perf.remote import serve_cache

    return serve_cache(args.listen, root=args.dir, max_mb=args.max_mb)


def cmd_cache_verify(args: argparse.Namespace) -> int:
    """`cache verify`: scan the whole persisted store, authenticating
    (HMAC) and unpickling every entry — the no-toolchain analogue of
    GOCACHE verification.  Bad entries (unreadable, truncated, failed
    signature, unpicklable) are reported; with --repair they move to
    the quarantine/ directory so they can never be re-read.  The
    summary is always machine-readable JSON (stable key order).
    Exit status: 1 when bad entries remain in the live store (found
    without --repair, or --repair could not move them), 0 otherwise
    (clean store, or --repair quarantined every bad entry)."""
    import json as _json

    summary = perfcache.verify(repair=args.repair)
    print(_json.dumps(summary))
    return 1 if summary["bad"] > summary["quarantined"] else 0


def cmd_stats(args: argparse.Namespace) -> int:
    """`stats`: the observability surface — per-namespace cache
    hit/miss attribution, dependency-graph counters, the metrics
    registry (counters, gauges, p50/p99 latency histograms),
    per-tenant SLO telemetry, and the span table — in stable key
    order.  By default the surface of THIS process (a one-shot CLI
    reports its own, mostly cold, state); with --addr the same `stats`
    op is asked of a running daemon/fleet coordinator, whose numbers
    accumulate across requests — before this flag, `operator-forge
    stats` next to a busy daemon reported an empty registry."""
    import json as _json

    from ..perf import metrics

    if args.addr:
        from ..serve.daemon import DaemonClient

        try:
            with DaemonClient(args.addr) as client:
                report = client.request({"op": "stats", "id": "stats"})
        except (OSError, ConnectionError) as exc:
            print(f"error: server at {args.addr}: {exc}",
                  file=sys.stderr)
            return 1
        if report.get("ok") is False:
            print(f"error: server at {args.addr}: "
                  f"{report.get('error')}", file=sys.stderr)
            return 1
        # the serve stats op and metrics.report() share the same keys;
        # drop the protocol envelope so both paths render identically
        for key in ("ok", "op", "id", "seconds"):
            report.pop(key, None)
    else:
        report = metrics.report()
    if args.json:
        print(_json.dumps(report))
        return 0
    print("cache namespaces:")
    for stage, entry in report["cache"].items():
        print(
            f"  {stage}: {entry['hits']} hits / {entry['misses']} "
            f"misses (ratio {entry['ratio']})"
        )
    if not report["cache"]:
        print("  (none)")
    graph = report["graph"]
    print(
        "graph: dirty=%d reused=%d recomputed=%d"
        % (graph["dirty"], graph["reused"], graph["recomputed"])
    )
    tiers = report["tiers"]
    print(
        "tiers: mode=%s lowered=%d promoted=%d hydrated=%d reused=%d "
        "bytecode_executed=%d deopt=%d"
        % (
            tiers.get("mode"), tiers.get("compile.lowered", 0),
            tiers.get("compile.promoted", 0),
            tiers.get("compile.hydrated", 0),
            tiers.get("compile.reused", 0),
            tiers.get("bytecode.executed", 0),
            tiers.get("bytecode.deopt", 0),
        )
    )
    print(
        "render: mode=%s lowered=%d hydrated=%d executed=%d deopt=%d"
        % (
            tiers.get("render_mode"),
            tiers.get("render.lowered", 0),
            tiers.get("render.hydrated", 0),
            tiers.get("render.executed", 0),
            tiers.get("render.deopt", 0),
        )
    )
    editor = report.get("editor") or {}
    if editor:
        print(
            "editor: overlays=%d superseded=%d push_p50=%s push_p99=%s"
            % (
                editor.get("overlays", 0),
                editor.get("superseded", 0)
                + editor.get("superseded_inflight", 0),
                editor.get("push_p50"), editor.get("push_p99"),
            )
        )
    from ..gocheck import sanitize as _sanitize

    print(
        "sanitize: race=%s checked=%d clock_merges=%d races=%d"
        % (
            _sanitize.race_mode(),
            tiers.get("sanitize.checked", 0),
            tiers.get("sanitize.clock_merges", 0),
            tiers.get("sanitize.races", 0),
        )
    )
    slo = report.get("slo") or {}
    if slo:
        print("slo tenants:")
        for tenant, entry in slo.items():
            print(
                f"  {tenant}: count={entry['count']} "
                f"p50={entry['p50']} p99={entry['p99']} "
                f"p999={entry['p999']} "
                f"deadline_misses={entry['deadline_misses']}"
            )
    snap = report["metrics"]
    for name, value in snap["counters"].items():
        print(f"counter {name}: {value}")
    for name, value in snap["gauges"].items():
        print(f"gauge {name}: {value}")
    for name, hist in snap["histograms"].items():
        print(
            f"histogram {name}: count={hist['count']} "
            f"p50={hist['p50']} p99={hist['p99']} max={hist['max']}"
        )
    if report["spans"]:
        print("spans:")
        for name, data in report["spans"].items():
            print(
                f"  {name}: {data['calls']} calls, {data['s']:.4f}s"
            )
    return 0


def cmd_explain(args: argparse.Namespace) -> int:
    """`explain`: why would (or did) an edit recompute what it
    recomputed?  Given a project root and one or more changed files,
    print the invalidation chain — changed file → dirtied per-file
    diagnostics node → dirtied package suites (reverse import
    closure) → project-index delta → minimally re-run jobs.  The
    chain is derived from the tree's bytes, not from live cache state,
    so the report is byte-identical across cache modes, worker
    backends, and job counts (the observability counterpart of Bazel's
    --explain and `go build`'s cache-key reasoning)."""
    import json as _json

    from operator_forge.gocheck.explain import (
        explain_report,
        explain_summary,
    )

    root = args.path
    if not os.path.isdir(root):
        print(f"error: {root} is not a directory", file=sys.stderr)
        return 1
    # copies: argparse's append action hands back the parser's shared
    # default list when a flag wasn't passed, and build_parser() is
    # cached — mutating it would pollute every later parse
    changed = list(args.changed or [])
    removed = list(args.removed or [])
    if not changed and not removed:
        print(
            "error: pass --changed <file> (and/or --removed <file>), "
            "relative to the project root",
            file=sys.stderr,
        )
        return 1
    for rel in list(changed):
        if not os.path.exists(os.path.join(root, rel)):
            print(
                f"warning: {rel} does not exist under {root} "
                "(explaining it as a removal)",
                file=sys.stderr,
            )
            changed.remove(rel)
            removed.append(rel)
    if args.json:
        for entry in explain_summary(root, changed, removed):
            print(_json.dumps(entry))
        return 0
    sys.stdout.write(explain_report(root, changed, removed))
    return 0


def cmd_trace(args: argparse.Namespace) -> int:
    """`trace`: run any operator-forge command with structured tracing
    enabled and write the merged timeline as Chrome trace-event JSON
    (load it in chrome://tracing or Perfetto).  Worker processes ship
    their span buffers back through the HMAC-signed result round-trip,
    so one file covers serial, thread-pool, and process-pool work.
    Equivalent: OPERATOR_FORGE_TRACE=<path> operator-forge <cmd>."""
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        raise CLIError(
            "trace: give a command to run, e.g. "
            "`operator-forge trace --out trace.json vet <dir>`"
        )
    if cmd[0] == "trace":
        raise CLIError("trace: cannot trace itself")
    spans.clear_events()
    spans.enable_tracing(True)
    try:
        rc = main(cmd)
    finally:
        spans.enable_tracing(None)
        n = spans.write_chrome_trace(args.out)
        print(f"trace: {n} events -> {args.out}", file=sys.stderr)
    return rc


@functools.cache
def build_parser() -> argparse.ArgumentParser:
    # cached: construction is ~4ms and the parser is safely reusable
    # (no append-actions or mutable defaults)
    parser = argparse.ArgumentParser(
        prog="operator-forge",
        description=(
            "Generate complete Kubernetes operator projects from workload "
            "config YAML and marker-annotated manifests."
        ),
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_init = sub.add_parser("init", help="scaffold a new operator project")
    p_init.add_argument("--workload-config", required=True)
    p_init.add_argument("--repo", default="", help="go module path")
    p_init.add_argument("--output-dir", default=".")
    p_init.add_argument(
        "--plugins", default="",
        help="plugin keys to scaffold with (kubebuilder-style, e.g. "
             "go/v3 or workload.operator-forge.io/v1); the workload "
             "bundle is the default and only generator",
    )
    p_init.add_argument("--project-license", default="")
    p_init.add_argument("--source-header-license", default="")
    p_init.add_argument(
        "--component-config", action="store_true",
        help="generated main.go loads manager options from a "
             "component-config file (--config flag) instead of "
             "individual flags",
    )
    p_init.set_defaults(func=cmd_init)

    p_create = sub.add_parser("create", help="create resources in the project")
    create_sub = p_create.add_subparsers(dest="create_command", required=True)
    p_api = create_sub.add_parser(
        "api", help="scaffold APIs, controllers and companion CLI"
    )
    p_api.add_argument("--workload-config", default="")
    p_api.add_argument("--output-dir", default=".")
    # kubebuilder-compatible flags (reference docs/api-updates-upgrades.md):
    # --controller=false skips controller scaffolding; --resource=false
    # skips API/resource scaffolding; --force is accepted for compatibility
    # (regeneration always overwrites generated files here)
    p_api.add_argument(
        "--controller", nargs="?", const="true", default="true", type=_parse_bool
    )
    p_api.add_argument(
        "--resource", nargs="?", const="true", default="true", type=_parse_bool
    )
    p_api.add_argument("--force", action="store_true")
    p_api.add_argument(
        "--dry-run",
        action="store_true",
        help="report what would be created/overwritten/preserved "
        "without writing anything",
    )
    p_api.add_argument(
        "--enable-conversion", action="store_true",
        help="scaffold conversion-webhook wiring (hub/spoke stubs, webhook "
        "Service, cert-manager certificate, CRD conversion strategy) for "
        "kinds with multiple API versions; persisted in the PROJECT file",
    )
    p_api.set_defaults(func=cmd_create_api)

    p_webhook = create_sub.add_parser(
        "webhook",
        help="scaffold defaulting/validating admission webhooks "
        "(kubebuilder-compatible; run after `create api`)",
    )
    p_webhook.add_argument("--workload-config", default="")
    p_webhook.add_argument("--output-dir", default=".")
    p_webhook.add_argument(
        "--defaulting", action="store_true",
        help="scaffold a webhook.Defaulter (mutating webhook)",
    )
    p_webhook.add_argument(
        "--programmatic-validation", action="store_true",
        help="scaffold a webhook.Validator (validating webhook)",
    )
    p_webhook.add_argument(
        "--force", action="store_true",
        help="regenerate the user-owned webhook stub instead of "
        "preserving it (discards edits; kubebuilder semantics)",
    )
    p_webhook.add_argument(
        "--dry-run",
        action="store_true",
        help="report what would be created/overwritten/preserved "
        "without writing anything",
    )
    p_webhook.set_defaults(func=cmd_create_webhook)

    p_edit = sub.add_parser(
        "edit",
        help="update project attributes recorded in the PROJECT file "
        "(kubebuilder-compatible)",
    )
    p_edit.add_argument("--output-dir", default=".")
    p_edit.add_argument(
        "--multigroup", nargs="?", const="true", default=None,
        type=_parse_bool,
        help="record multi-group intent; the generated layout is "
        "apis/<group>/<version> regardless",
    )
    p_edit.set_defaults(func=cmd_edit)

    p_cfg = sub.add_parser(
        "init-config", help="emit a sample workload config"
    )
    p_cfg.add_argument(
        "workload_type", choices=["standalone", "collection", "component"]
    )
    p_cfg.add_argument("--path", default="-")
    p_cfg.add_argument("--force", action="store_true")
    p_cfg.set_defaults(func=cmd_init_config)

    p_update = sub.add_parser("update", help="update project attributes")
    update_sub = p_update.add_subparsers(dest="update_command", required=True)
    p_license = update_sub.add_parser("license", help="update license files")
    p_license.add_argument("--project-license", default="")
    p_license.add_argument("--source-header-license", default="")
    p_license.add_argument("--output-dir", default=".")
    p_license.set_defaults(func=cmd_update_license)

    p_completion = sub.add_parser("completion", help="shell completion")
    p_completion.add_argument("shell", choices=["bash", "zsh", "fish"])
    p_completion.set_defaults(func=cmd_completion)

    p_version = sub.add_parser("version", help="print the version")
    p_version.set_defaults(func=cmd_version)

    p_vet = sub.add_parser(
        "vet",
        help="run the analyzer framework over the Go files of a "
             "generated project (syntax, types, structure, data flow)",
    )
    p_vet.add_argument("path", help="root of the generated project")
    p_vet.add_argument(
        "--analyzers", default="", metavar="A,B",
        help="comma-separated analyzer subset (default: all; see "
             "docs/no-toolchain-tools.md for the registry)",
    )
    p_vet.add_argument(
        "--json", action="store_true",
        help="emit one JSON object per diagnostic (stable key order) "
             "instead of human text",
    )
    p_vet.set_defaults(func=cmd_vet)

    p_test = sub.add_parser(
        "test",
        help="run the generated project's Go test suite (no toolchain "
             "or cluster needed)",
    )
    p_test.add_argument("path", help="generated project directory")
    p_test.add_argument(
        "--e2e", action="store_true",
        help="also run the e2e lifecycle suite (interprets main.go and "
             "simulates the cluster's builtin controllers)",
    )
    p_test.add_argument(
        "--run", default="", metavar="REGEX",
        help="run only tests matching the pattern (go test -run)",
    )
    p_test.add_argument(
        "-v", action="store_true",
        help="print each test as it runs (go test -v)",
    )
    p_test.set_defaults(func=cmd_test)

    p_preview = sub.add_parser(
        "preview",
        help="render child manifests for a custom resource without "
        "building the operator",
    )
    p_preview.add_argument(
        "--workload-config", required=True, help="workload config YAML"
    )
    p_preview.add_argument(
        "--workload-manifest",
        required=True,
        help="custom-resource manifest to render children for",
    )
    p_preview.add_argument(
        "--collection-manifest",
        default=None,
        help="collection custom-resource manifest (for components)",
    )
    p_preview.set_defaults(func=cmd_preview)

    p_validate = sub.add_parser(
        "validate",
        help="validate CR manifests against the generated CRD schemas",
    )
    p_validate.add_argument(
        "--project",
        required=True,
        help="root of the generated project (reads config/crd/bases)",
    )
    p_validate.add_argument(
        "--manifest", required=True, help="CR manifest(s) to validate"
    )
    p_validate.set_defaults(func=cmd_validate)

    p_batch = sub.add_parser(
        "batch",
        help="run a manifest of init/create-api/vet/lint/test jobs "
             "concurrently with cached-result replay",
    )
    p_batch.add_argument(
        "--manifest", required=True,
        help="YAML/JSON job manifest (see docs/no-toolchain-tools.md); "
             "relative paths resolve against the manifest's directory",
    )
    p_batch.add_argument(
        "--json", action="store_true",
        help="emit one JSON line per job result plus a summary line",
    )
    p_batch.add_argument(
        "--addr", default="", metavar="ADDR",
        help="run the manifest through a running `operator-forge "
             "daemon` at this address (unix:/path or host:port) "
             "instead of this process",
    )
    p_batch.set_defaults(func=cmd_batch)

    p_serve = sub.add_parser(
        "serve",
        help="persistent JSON-lines request loop on stdin (warm caches "
             "across requests)",
    )
    p_serve.set_defaults(func=cmd_serve)

    p_daemon = sub.add_parser(
        "daemon",
        help="serve the JSON-lines protocol to N concurrent clients "
             "over a unix or TCP socket (fair scheduling, bounded "
             "admission queues, shared warm caches)",
    )
    p_daemon.add_argument(
        "--listen", required=True, metavar="ADDR",
        help="unix:/path/to.sock (or any path) for a unix socket, "
             "host:port for TCP (port 0 picks a free port)",
    )
    p_daemon.add_argument(
        "--clients", type=int, default=None, metavar="N",
        help="concurrent-connection ceiling (default: "
             "OPERATOR_FORGE_DAEMON_CLIENTS, 64)",
    )
    p_daemon.add_argument(
        "--fleet", default=None, metavar="ADDR",
        help="register with (and heartbeat to) the fleet coordinator "
             "at this address; re-registers automatically across "
             "coordinator restarts",
    )
    p_daemon.set_defaults(func=cmd_daemon)

    p_fleet = sub.add_parser(
        "fleet",
        help="coordinate a fleet of daemons: heartbeat-leased "
             "membership, project-affinity routing with work-stealing, "
             "idempotent re-dispatch when a daemon dies mid-run, and "
             "fleet-wide SIGTERM drain",
    )
    p_fleet.add_argument(
        "--listen", required=True, metavar="ADDR",
        help="unix:/path/to.sock (or any path) for a unix socket, "
             "host:port for TCP (port 0 picks a free port)",
    )
    p_fleet.add_argument(
        "--lease", type=float, default=None, metavar="S",
        help="heartbeat lease seconds (default: "
             "OPERATOR_FORGE_FLEET_LEASE_S, 5); one missed lease marks "
             "a daemon suspect, a second evicts it",
    )
    p_fleet.add_argument(
        "--clients", type=int, default=None, metavar="N",
        help="concurrent-connection ceiling (default: "
             "OPERATOR_FORGE_FLEET_CLIENTS, 128)",
    )
    p_fleet.add_argument(
        "--min", type=int, default=0, metavar="N",
        help="autoscaler pool floor: keep at least N daemons "
             "registered, spawning coordinator-owned ones when short "
             "(default: OPERATOR_FORGE_FLEET_MIN)",
    )
    p_fleet.add_argument(
        "--max", type=int, default=0, metavar="N",
        help="autoscaler pool ceiling; 0 disables elasticity "
             "(default: OPERATOR_FORGE_FLEET_MAX).  Spawned daemons "
             "get private cache roots and share artifacts only "
             "through OPERATOR_FORGE_REMOTE_CACHE",
    )
    p_fleet.set_defaults(func=cmd_fleet)

    p_fleet_status = sub.add_parser(
        "fleet-status",
        help="one stats round trip to a running coordinator: "
             "per-daemon lease age, in-flight load, degrade flags, and "
             "the eviction/re-dispatch counters",
    )
    p_fleet_status.add_argument(
        "--addr", required=True, metavar="ADDR",
        help="the coordinator's listen address (unix:/path or "
             "host:port)",
    )
    p_fleet_status.add_argument(
        "--json", action="store_true",
        help="print the fleet surface (members, lease ages, counters) "
             "as one JSON object in stable key order",
    )
    p_fleet_status.set_defaults(func=cmd_fleet_status)

    p_connect = sub.add_parser(
        "connect",
        help="relay JSON-lines requests from stdin to a running "
             "daemon and print its responses",
    )
    p_connect.add_argument(
        "--addr", required=True, metavar="ADDR",
        help="the daemon's listen address (unix:/path or host:port)",
    )
    p_connect.set_defaults(func=cmd_connect)

    p_watch = sub.add_parser(
        "watch",
        help="watch a batch manifest's input trees and re-run the "
             "minimal job set on every change (incremental edit loop)",
    )
    p_watch.add_argument(
        "--manifest", required=True,
        help="YAML/JSON job manifest (same format as `batch`)",
    )
    p_watch.add_argument(
        "--interval", type=float, default=0.5, metavar="SECONDS",
        help="poll interval (default 0.5s)",
    )
    p_watch.add_argument(
        "--cycles", type=int, default=0, metavar="N",
        help="stop after N job runs (0 = watch until interrupted)",
    )
    p_watch.add_argument(
        "--json", action="store_true",
        help="emit one JSON line per cycle instead of human summaries",
    )
    p_watch.set_defaults(func=cmd_watch)

    p_cache = sub.add_parser(
        "cache", help="manage the content-addressed cache"
    )
    cache_sub = p_cache.add_subparsers(dest="cache_command", required=True)
    p_gc = cache_sub.add_parser(
        "gc",
        help="prune the disk cache to its size ceiling "
             "(OPERATOR_FORGE_CACHE_MAX_MB, default 256), LRU first",
    )
    p_gc.add_argument(
        "--max-mb", type=float, default=None, metavar="MB",
        help="one-off ceiling override for this collection",
    )
    p_gc.add_argument(
        "--json", action="store_true",
        help="include the detail keys older scripts consumed "
             "(removed, bytes_before, bytes_after, ...); the summary "
             "itself is always JSON",
    )
    p_gc.add_argument(
        "--verbose", action="store_true",
        help="include detail keys (entries, max_bytes, removed, "
             "bytes_before, bytes_after) in the JSON summary",
    )
    p_gc.add_argument(
        "--purge-quarantine", action="store_true",
        help="also delete quarantined (damaged, already-neutralized) "
             "entries instead of only reporting their footprint",
    )
    p_gc.set_defaults(func=cmd_cache_gc)
    p_verify = cache_sub.add_parser(
        "verify",
        help="scan the disk cache, authenticating and unpickling "
             "every entry; report (and with --repair quarantine) "
             "damaged ones",
    )
    p_verify.add_argument(
        "--repair", action="store_true",
        help="move bad entries to the quarantine/ directory instead "
             "of only reporting them",
    )
    p_verify.set_defaults(func=cmd_cache_verify)

    p_cache_server = sub.add_parser(
        "cache-server",
        help="serve a shared remote artifact cache (content-addressed "
             "get/put over a unix socket or TCP) for "
             "OPERATOR_FORGE_REMOTE_CACHE clients",
    )
    p_cache_server.add_argument(
        "--listen", required=True, metavar="ADDR",
        help="unix:/path/to.sock (or any path) for a unix socket, "
             "host:port for TCP (port 0 picks a free port)",
    )
    p_cache_server.add_argument(
        "--dir", default=None, metavar="DIR",
        help="store directory (default: OPERATOR_FORGE_CACHE_DIR or "
             ".operator-forge-cache)",
    )
    p_cache_server.add_argument(
        "--max-mb", type=float, default=None, metavar="MB",
        help="LRU store ceiling override "
             "(default: OPERATOR_FORGE_CACHE_MAX_MB, 256)",
    )
    p_cache_server.set_defaults(func=cmd_cache_server)

    p_stats = sub.add_parser(
        "stats",
        help="report the observability surface: cache hit/miss "
             "attribution, graph counters, metrics (p50/p99 "
             "histograms), per-tenant SLO telemetry, and the span "
             "table",
    )
    p_stats.add_argument(
        "--json", action="store_true",
        help="emit the full report as one JSON object (stable key "
             "order) instead of the human summary",
    )
    p_stats.add_argument(
        "--addr", default="", metavar="ADDR",
        help="query a running daemon/fleet coordinator at this "
             "address (unix:/path or host:port) over the stats op "
             "instead of reporting this process's registry",
    )
    p_stats.set_defaults(func=cmd_stats)

    p_explain = sub.add_parser(
        "explain",
        help="print the invalidation chain a changed file triggers "
             "(what recomputes, and why) for a generated project",
    )
    p_explain.add_argument("path", help="root of the generated project")
    p_explain.add_argument(
        "--changed", action="append", default=[], metavar="FILE",
        help="a changed file, relative to the project root "
             "(repeatable)",
    )
    p_explain.add_argument(
        "--removed", action="append", default=[], metavar="FILE",
        help="a removed file, relative to the project root "
             "(repeatable)",
    )
    p_explain.add_argument(
        "--json", action="store_true",
        help="emit one JSON object per changed file (stable key "
             "order) instead of the text report",
    )
    p_explain.set_defaults(func=cmd_explain)

    p_trace = sub.add_parser(
        "trace",
        help="run a command with structured tracing and write a "
             "Chrome trace-event JSON timeline",
    )
    p_trace.add_argument(
        "--out", required=True, metavar="PATH",
        help="where to write the Chrome trace JSON",
    )
    p_trace.add_argument(
        "cmd", nargs=argparse.REMAINDER,
        help="the operator-forge command to run under tracing",
    )
    p_trace.set_defaults(func=cmd_trace)

    return parser


# re-entrancy depth across every thread: batch/serve jobs and the
# `trace` wrapper all call main() recursively, and the env-driven
# Chrome-trace export must fire once, at the OUTERMOST exit — not per
# nested job (which would overwrite the file mid-run)
_depth_lock = threading.Lock()
_main_depth = [0]


def _new_depth_lock_after_fork() -> None:
    # fork (the perf.workers process pool) can land while a parent
    # thread holds the re-entrancy lock; the child would inherit it
    # locked and deadlock on its first main() call
    global _depth_lock
    _depth_lock = threading.Lock()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_new_depth_lock_after_fork)


def main(argv: list[str] | None = None) -> int:
    # BatchManifestError never reaches here: cmd_batch and the serve
    # loop both catch it at their own boundary, keeping the serve
    # package out of the startup import path
    args = build_parser().parse_args(argv)
    with _depth_lock:
        _main_depth[0] += 1
    try:
        with spans.span(f"command:{args.command}"):
            return args.func(args)
    except (
        CLIError,
        CreateAPIError,
        ScaffoldError,
        wconfig.ConfigParseError,
        licensing.LicenseError,
        init_config_mod.InitConfigError,
    ) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # the reader went away (operator-forge test ... | head): exit
        # quietly with the conventional SIGPIPE status
        try:
            sys.stdout.close()
        except OSError:
            pass
        return 141
    finally:
        with _depth_lock:
            _main_depth[0] -= 1
            outermost = _main_depth[0] == 0
        if outermost:
            # env-path resolution, worker suppression, and the
            # announce line all live in spans.export_env_trace (the
            # drain-path hooks call the same helper)
            spans.export_env_trace()
        # a profiled run that fails still reports the work it did
        if os.environ.get("OPERATOR_FORGE_PROFILE", "") not in ("", "0"):
            spans.report(sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
