"""`init-config`: emit sample workload-config YAML.

Reference: pkg/cli/init_config.go:50-170 +
internal/workload/v1/commands/subcommand/init_config.go:35-152.
"""

from __future__ import annotations

import os
import sys

from ..workload.kinds import WorkloadAPISpec

SAMPLE_RESOURCE_FILES = ["resources.yaml"]


class InitConfigError(Exception):
    pass


def _api_block(spec: WorkloadAPISpec, include_domain: bool = True) -> list[str]:
    lines = ["  api:"]
    if include_domain:
        lines.append(f"    domain: {spec.domain}")
    lines.extend(
        [
            f"    group: {spec.group}",
            f"    version: {spec.version}",
            f"    kind: {spec.kind}",
            f"    clusterScoped: {'true' if spec.cluster_scoped else 'false'}",
        ]
    )
    return lines


def sample_config(workload_type: str) -> str:
    """Build the sample config for ``standalone``, ``collection`` or
    ``component``."""
    spec = WorkloadAPISpec.sample()
    if workload_type == "standalone":
        lines = [
            "name: my-app",
            "kind: StandaloneWorkload",
            "spec:",
            *_api_block(spec),
            "  companionCliRootcmd:",
            "    name: myappctl",
            "    description: Manage my-app",
            "  resources:",
            *[f"  - {f}" for f in SAMPLE_RESOURCE_FILES],
        ]
    elif workload_type == "collection":
        lines = [
            "name: my-collection",
            "kind: WorkloadCollection",
            "spec:",
            *_api_block(spec),
            "  companionCliRootcmd:",
            "    name: myctl",
            "    description: Manage my-collection and its components",
            "  companionCliSubcmd:",
            "    name: collection",
            "    description: Manage my-collection",
            "  componentFiles:",
            "  - my-component.yaml",
            "  resources:",
            *[f"  - {f}" for f in SAMPLE_RESOURCE_FILES],
        ]
    elif workload_type == "component":
        lines = [
            "name: my-component",
            "kind: ComponentWorkload",
            "spec:",
            *_api_block(spec, include_domain=False),
            "  companionCliSubcmd:",
            "    name: mycomponent",
            "    description: Manage my-component",
            "  dependencies: []",
            "  resources:",
            *[f"  - {f}" for f in SAMPLE_RESOURCE_FILES],
        ]
    else:
        raise InitConfigError(
            f"unknown workload type {workload_type!r}; expected standalone, "
            "collection or component"
        )
    return "\n".join(lines) + "\n"


def write_config(workload_type: str, path: str = "-", force: bool = False) -> None:
    """Emit the sample to stdout (``-``) or a file
    (reference init_config.go:64-88 outputFile)."""
    content = sample_config(workload_type)
    if path == "-" or not path:
        sys.stdout.write(content)
        return
    if os.path.exists(path) and not force:
        raise InitConfigError(
            f"file {path} already exists; use --force to overwrite"
        )
    with open(path, "w", encoding="utf-8") as handle:
        handle.write(content)
