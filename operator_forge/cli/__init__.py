"""operator-forge command-line interface (reference: pkg/cli + cmd)."""
