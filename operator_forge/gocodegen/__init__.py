"""YAML-manifest -> Go object-construction source generator.

The equivalent of the external module
vmware-tanzu-labs/object-code-generator-for-k8s (``generate.Generate``,
called by the reference at internal/workload/v1/kinds/workload.go:266).
Given a (marker-rewritten) manifest document, emits Go source constructing an
``unstructured.Unstructured`` object, honoring the marker substitution
contract:

- a ``!!var <expr>`` scalar becomes the bare Go expression ``<expr>``;
- a string containing ``!!start <expr> !!end`` fragments becomes a
  ``fmt.Sprintf`` interpolation of the surrounding literal text;
- all other scalars become typed Go literals.
"""

from .generate import (  # noqa: F401
    generate,
    generate_for_document,
    generate_for_document_lowered,
)
