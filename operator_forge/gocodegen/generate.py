"""Emit Go source that constructs an unstructured Kubernetes object."""

from __future__ import annotations

import re

from ..yamldoc import Document, Mapping, Scalar, Sequence
from ..yamldoc.load import load_documents
from ..yamldoc.model import (
    BOOL_TAG,
    FLOAT_TAG,
    INT_TAG,
    NULL_TAG,
    VAR_TAG,
)

_START_END_RE = re.compile(r"!!start\s+(.+?)\s+!!end")


class GenerateError(Exception):
    pass


def _go_quote(value: str) -> str:
    out = []
    for ch in value:
        if ch == "\\":
            out.append("\\\\")
        elif ch == '"':
            out.append('\\"')
        elif ch == "\n":
            out.append("\\n")
        elif ch == "\t":
            out.append("\\t")
        elif ord(ch) < 0x20:
            out.append(f"\\x{ord(ch):02x}")
        else:
            out.append(ch)
    return '"' + "".join(out) + '"'


def _string_expr(value: str) -> str:
    """Render a string that may contain ``!!start <expr> !!end`` fragments.

    Plain strings render as quoted literals; mixed strings render as a
    ``fmt.Sprintf`` call with ``%v`` verbs for each substituted expression;
    a string that is exactly one fragment renders as the expression itself.
    """
    matches = list(_START_END_RE.finditer(value))
    if not matches:
        return _go_quote(value)
    full = matches[0]
    if len(matches) == 1 and full.start() == 0 and full.end() == len(value):
        return full.group(1)
    fmt_parts: list[str] = []
    args: list[str] = []
    pos = 0
    for match in matches:
        fmt_parts.append(value[pos : match.start()].replace("%", "%%"))
        fmt_parts.append("%v")
        args.append(match.group(1))
        pos = match.end()
    fmt_parts.append(value[pos:].replace("%", "%%"))
    fmt_literal = _go_quote("".join(fmt_parts))
    return f"fmt.Sprintf({fmt_literal}, {', '.join(args)})"


def _scalar_expr(scalar: Scalar) -> str:
    if scalar.tag == VAR_TAG:
        return scalar.value
    if scalar.tag == INT_TAG:
        return str(scalar.python_value())
    if scalar.tag == FLOAT_TAG:
        return str(scalar.python_value())
    if scalar.tag == BOOL_TAG:
        return "true" if scalar.python_value() else "false"
    if scalar.tag == NULL_TAG:
        return "nil"
    return _string_expr(scalar.value)


def _node_expr(node, indent: int) -> str:
    pad = "\t" * indent
    child_pad = "\t" * (indent + 1)
    if isinstance(node, Scalar):
        return _scalar_expr(node)
    if isinstance(node, Mapping):
        if not node.entries:
            return "map[string]interface{}{}"
        lines = ["map[string]interface{}{"]
        for entry in node.entries:
            comments = [
                f"{child_pad}// {c.lstrip('# ')}" for c in entry.head_comments
                if c.strip("# ")
            ]
            lines.extend(comments)
            value = _node_expr(entry.value, indent + 1)
            suffix = (
                f" // {entry.line_comment.lstrip('# ')}"
                if entry.line_comment
                else ""
            )
            lines.append(
                f"{child_pad}{_go_quote(entry.key.value)}: {value},{suffix}"
            )
        lines.append(pad + "}")
        return "\n".join(lines)
    if isinstance(node, Sequence):
        if not node.items:
            return "[]interface{}{}"
        lines = ["[]interface{}{"]
        for item in node.items:
            for c in item.head_comments:
                if c.strip("# "):
                    lines.append(f"{child_pad}// {c.lstrip('# ')}")
            value = _node_expr(item.node, indent + 1)
            suffix = (
                f" // {item.line_comment.lstrip('# ')}"
                if item.line_comment
                else ""
            )
            lines.append(f"{child_pad}{value},{suffix}")
        lines.append(pad + "}")
        return "\n".join(lines)
    raise GenerateError(f"cannot generate code for node {type(node)!r}")


def uses_sprintf(code: str) -> bool:
    return "fmt.Sprintf(" in code


def generate_for_document(doc: Document, var_name: str) -> str:
    """Generate a Go variable declaration constructing the manifest object."""
    if not isinstance(doc.root, Mapping):
        raise GenerateError("manifest document root must be a mapping")
    object_expr = _node_expr(doc.root, indent=1)
    return (
        f"var {var_name} = &unstructured.Unstructured{{\n"
        f"\tObject: {object_expr},\n"
        f"}}"
    )


def generate_for_document_lowered(
    doc: Document, var_name: str, content_key: str
) -> str:
    """The render-program tier over :func:`generate_for_document`: the
    emitted Go source is a pure function of the document's source bytes
    (``content_key``) and the variable name, so the emission lowers
    once per content hash into the ``render.lower`` blob store and
    replays across processes without re-walking the node tree."""
    from ..scaffold import render

    return render.lowered_blob(
        "gocodegen.document",
        (content_key, var_name),
        lambda: generate_for_document(doc, var_name),
    )


def generate(manifest_yaml: str, var_name: str) -> str:
    """Parse one manifest document and generate its Go constructor source
    (the ocgk ``generate.Generate`` equivalent)."""
    docs = load_documents(manifest_yaml)
    docs = [d for d in docs if d.root is not None]
    if len(docs) != 1:
        raise GenerateError(
            f"expected exactly one manifest document, found {len(docs)}"
        )
    return generate_for_document(docs[0], var_name)
