"""operator-forge: a from-scratch, capability-equivalent rebuild of
vmware-tanzu-labs/operator-builder.

operator-forge generates complete Kubernetes operator projects (CRD API types,
phase-driven controllers, RBAC, kustomize config, e2e tests, and a companion
CLI) from declarative workload-config YAML plus ``+operator-builder:*`` markers
embedded in ordinary Kubernetes manifests.

Capability contract mirrors the reference (see SURVEY.md for the full layer
map; reference layers cited per-module):

- ``operator_forge.utils``     <-> reference ``internal/utils``
- ``operator_forge.yamldoc``   <-> reference's use of gopkg.in/yaml.v3 node
  trees (comment-preserving YAML round-trip)
- ``operator_forge.markers``   <-> reference ``internal/markers`` (lexer,
  parser, marker registry, inspector)
- ``operator_forge.workload``  <-> reference ``internal/workload/v1``
- ``operator_forge.gocodegen`` <-> the external module
  vmware-tanzu-labs/object-code-generator-for-k8s used at
  ``internal/workload/v1/kinds/workload.go:266``
- ``operator_forge.scaffold``  <-> reference
  ``internal/plugins/workload/v1/scaffolds`` + kubebuilder machinery
- ``operator_forge.cli``       <-> reference ``pkg/cli`` + ``cmd``
- ``operator_forge.licensing`` <-> reference ``internal/license``
"""

__version__ = "0.1.0"
