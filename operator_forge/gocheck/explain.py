"""Invalidation provenance, derived structurally (``explain``).

``operator-forge explain <root> --changed <file>`` answers *"what
recomputes, and why, when this file changes?"* — the local counterpart
of Bazel's ``--explain`` log and ``go build``'s cache-key reasoning.

The report is deliberately **not** read from the live dependency graph
(:data:`operator_forge.perf.depgraph.GRAPH` records *what happened*,
which legitimately differs across cache modes — ``off`` installs no
nodes at all — and across worker backends, where process workers keep
their own graphs).  Instead the chain is **derived from the tree's
bytes**: the file's package membership, the project's reverse import
closure (``go.mod`` module path + per-file imports), and the artifact
kinds each incremental layer keys on (per-file diagnostics, per-package
suites, the project index, generation plans).  A pure function of tree
content is byte-identical across ``OPERATOR_FORGE_CACHE=off|mem|disk``,
``OPERATOR_FORGE_WORKERS=thread|process``, and any ``JOBS`` width —
the property bench.py's ``telemetry.explain_identity`` guard and
tests/test_observability.py enforce.

The same derivation feeds the ``watch`` loop's per-cycle provenance
summary and the serve ``explain`` op.
"""

from __future__ import annotations

import os

from ..perf import overlay as pf_overlay
from .gopkg import ProjectRuntime
from .structural import parse_imports, prune_go_dirs


def module_path(root: str) -> str:
    """The project's Go module path (``go.mod``), with the same
    fallback the interpreter's world uses."""
    return ProjectRuntime._module_path(root)


def _pkg_path(module: str, pkg_dir: str) -> str:
    return module if pkg_dir == "." else f"{module}/{pkg_dir}"


# per-file import memo keyed on (path, mtime_ns, size): the watch loop
# calls package_imports every cycle, and a one-file edit must cost one
# file READ, not a whole-tree re-parse (the walk itself is stat-only —
# the same order of work as the watch snapshot poll).  Bounded: stale
# paths are dropped whenever the table outgrows the live tree.
_file_imports_memo: dict = {}


def _imports_of(path: str, mtime_ns: int, size: int):
    overlay_text = pf_overlay.get(path)
    if overlay_text is not None:
        # overlay bytes bypass the (mtime, size) memo: the disk stat no
        # longer describes the content the checks will actually read
        return tuple(p for _alias, p in parse_imports(overlay_text))
    key = (mtime_ns, size)
    hit = _file_imports_memo.get(path)
    if hit is not None and hit[0] == key:
        return hit[1]
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except (OSError, UnicodeDecodeError):
        return ()
    imports = tuple(p for _alias, p in parse_imports(text))
    _file_imports_memo[path] = (key, imports)
    return imports


def package_imports(root: str) -> dict:
    """``{package_dir_rel: sorted imported paths}`` over every ``.go``
    file under ``root`` (test files included — a package's suite
    re-runs when anything in its *test* import closure changes too),
    with the standard tree-pruning rules.  Unchanged files (same
    mtime+size) replay their imports from the in-process memo."""
    imports: dict = {}
    live_paths = set()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = prune_go_dirs(dirnames)
        go_files = [
            name for name in sorted(filenames)
            if name.endswith(".go") and not name.startswith(("_", "."))
        ]
        if not go_files:
            continue
        rel = os.path.relpath(dirpath, root).replace(os.sep, "/")
        rel = "." if rel == "." else rel
        paths = set()
        for name in go_files:
            path = os.path.join(dirpath, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            live_paths.add(path)
            paths.update(_imports_of(path, st.st_mtime_ns, st.st_size))
        imports[rel] = sorted(paths)
    if len(_file_imports_memo) > 4 * max(len(live_paths), 256):
        # many roots/deleted trees accumulated: drop dead entries
        for path in list(_file_imports_memo):
            if path not in live_paths:
                del _file_imports_memo[path]
    return imports


def reverse_import_chains(root: str, pkg_dir: str, imports=None) -> dict:
    """``{dependent_pkg_dir: import chain}`` for every package whose
    (transitive) import closure contains ``pkg_dir``'s package.  The
    chain lists package dirs from the dependent down to ``pkg_dir``;
    BFS expands in sorted order, so the first-found chain — and with
    it the whole mapping — is deterministic."""
    module = module_path(root)
    if imports is None:
        imports = package_imports(root)
    chains: dict = {}
    order = sorted(imports)  # hoisted: BFS determinism needs the order,
    frontier = [(pkg_dir, (pkg_dir,))]  # not a re-sort per frontier entry
    while frontier:
        next_frontier = []
        for target_dir, chain in frontier:
            target_path = _pkg_path(module, target_dir)
            for importer in order:
                if importer == pkg_dir or importer in chains:
                    continue
                if target_path in imports[importer]:
                    chains[importer] = (importer,) + chain
                    next_frontier.append((importer, (importer,) + chain))
        frontier = next_frontier
    return chains


def chain_for(root: str, rel: str, imports=None) -> list:
    """The invalidation chain for one changed file, as deterministic
    report lines (no timestamps, no absolute paths beyond what the
    caller passed as ``root``)."""
    rel = rel.replace(os.sep, "/")
    lines = [f"file {rel} changed"]
    if rel == "go.mod":
        lines.append(
            "  -> module path may change: every package re-keys "
            "(project index, all suites, all import resolution)"
        )
        lines.append("  -> jobs re-run: vet, test (full recompute)")
        return lines
    if rel.endswith(".go"):
        pkg_dir = os.path.dirname(rel).replace(os.sep, "/") or "."
        module = module_path(root)
        lines.append(
            f"  -> invalidated node src:{rel} "
            f"(re-parse + re-analyze: per-file diagnostics)"
        )
        if rel.endswith("_test.go"):
            lines.append(
                f"  -> invalidated suite {pkg_dir} "
                f"(package owns the edited test file)"
            )
        else:
            lines.append(
                f"  -> invalidated suite {pkg_dir} "
                f"(package contains {rel})"
            )
            lines.append(
                f"  -> invalidated package surface "
                f"pkg:{_pkg_path(module, pkg_dir)} "
                f"(exported decls consulted by other files' analysis)"
            )
            for dep_dir, chain in sorted(
                reverse_import_chains(root, pkg_dir, imports).items()
            ):
                arrow = " -> ".join(chain)
                lines.append(
                    f"  -> invalidated suite {dep_dir} "
                    f"(import chain: {arrow})"
                )
        lines.append(
            f"  -> project index patched by delta ({rel}); "
            f"unchanged files' scans replay"
        )
        lines.append(
            "  -> jobs re-run minimally: vet, test "
            "(every other artifact replays from its trace)"
        )
        return lines
    # a non-Go input: workload config, marker-annotated manifest, or
    # any other byte the generation plan snapshotted
    lines.append(
        "  -> generation plan dependency snapshot no longer matches "
        "(config/manifest bytes are part of the plan key)"
    )
    lines.append(
        "  -> init / create api re-render; byte-identical outputs are "
        "left untouched"
    )
    lines.append(
        "  -> regenerated files re-vet / re-test downstream; "
        "unchanged artifacts replay"
    )
    return lines


def explain_report(root: str, changed, removed=(), imports=None) -> str:
    """The full deterministic provenance report for a change set:
    one chain block per changed/removed file, sorted, with a one-line
    header.  ``changed``/``removed`` are paths relative to ``root``;
    pass a precomputed ``imports`` map to share one tree walk across
    sibling calls (the serve op derives summary AND report)."""
    changed = sorted(
        {str(rel).replace(os.sep, "/") for rel in changed}
    )
    removed = sorted(
        {str(rel).replace(os.sep, "/") for rel in removed}
    )
    total = len(changed) + len(removed)
    noun = "change" if total == 1 else "changes"
    out = [f"explain: {total} {noun} under {root}"]
    if imports is None and any(
        rel.endswith(".go") for rel in changed + removed
    ):
        imports = package_imports(root)
    for rel in changed:
        out.extend(chain_for(root, rel, imports))
    for rel in removed:
        out.append(f"file {rel} removed")
        out.extend(chain_for(root, rel, imports)[1:])
    return "\n".join(out) + "\n"


def explain_summary(root: str, changed, removed=(), imports=None) -> list:
    """Structured form of :func:`explain_report` for JSON consumers
    (the ``watch`` per-cycle payload and ``explain --json``): a sorted
    list of ``{"file", "event", "chain"}`` entries."""
    rels_changed = sorted(
        {str(rel).replace(os.sep, "/") for rel in changed}
    )
    rels_removed = sorted(
        {str(rel).replace(os.sep, "/") for rel in removed}
    )
    if imports is None and any(
        rel.endswith(".go") for rel in rels_changed + rels_removed
    ):
        imports = package_imports(root)
    out = []
    for event, rels in (("changed", rels_changed),
                        ("removed", rels_removed)):
        for rel in rels:
            out.append({
                "file": rel,
                "event": event,
                "chain": chain_for(root, rel, imports)[1:],
            })
    return out
